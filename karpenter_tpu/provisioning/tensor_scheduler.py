"""TPU-accelerated scheduler front end.

Builds the encoded PackProblem from the same inputs the host Scheduler takes,
runs the device feasibility precompute + grouped packer (ops/binpack.py), and
materializes results in the host Results shape. Falls back to the host oracle
scheduler (provisioning/scheduler.py) whenever the batch isn't expressible in
the tensor kernel or when packing left relaxable pods unscheduled — so observable
semantics always match the reference (scheduler.go) either way.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim as APINodeClaim, NodeClaimSpec
from ..api.objects import ObjectMeta, OwnerReference, Pod
from ..cloudprovider.types import InstanceType
from ..obs.tracer import TRACER
from ..ops import binpack
from ..ops import encode as enc
from ..scheduling import taints as scheduling_taints
from ..scheduling.requirement import IN, Requirement
from ..scheduling.requirements import (ALLOW_UNDEFINED_WELL_KNOWN, Requirements,
                                       label_requirements)
from ..utils import resources as res
from .grouping import PodGroup, group_pods, partition_pods
# claim_name_seq: ONE process-wide claim-name sequence shared with the host
# oracle (independent counters minted colliding claim names)
from .scheduler import (MAX_INSTANCE_TYPES, NodeClaimTemplate, Results, Scheduler,
                        _daemon_overhead, _req_to_selector, claim_name_seq)
from .topology import ClusterView, Topology


def _pow2_bucket(n: int, minimum: int) -> int:
    """Next power of two >= max(n, minimum): bounded distinct jit shapes
    (shared implementation: ops/encode.pow2_bucket)."""
    return enc.pow2_bucket(n, minimum)


def _single_process() -> bool:
    """Gate for the exist-only delta kernel (binpack.exist_delta): it runs
    a plain single-device jit over the full node axis, which a multi-
    process fleet can't serve — each process holds only its local rows."""
    import jax
    return jax.process_count() == 1


@dataclass
class _CatalogEncoding:
    """Catalog-side tensors shared across solves. The instance-type catalog
    is stable between reconcile passes (providers refresh it on the order of
    minutes), while the solver runs every batch window — so the vocabulary,
    the encoded IT requirement masks, the offering tensors, AND their
    device-resident copies are all reusable. Reuse is only legal when the
    new solve introduces no vocabulary entries (checked by _fits_vocab):
    complement-encoded masks enumerate the value universe, so any new value
    would invalidate every cached row."""
    vocab: object
    zone_key: int
    captype_key: int
    it_enc: object
    it_alloc: np.ndarray
    it_capacity: np.ndarray
    it_price: np.ndarray
    off_zone: np.ndarray
    off_captype: np.ndarray
    off_available: np.ndarray
    off_price: np.ndarray
    zone_values: np.ndarray
    allow_undefined: np.ndarray
    device_cache: dict
    # offering identities as strings [T] / [T, O] ("" = absent slot):
    # the unavailable-offerings registry mask is built by matching its
    # (instance_type, zone, capacity_type) patterns against these in a few
    # vectorized passes per solve — no per-offering Python on the hot path
    off_names: np.ndarray = None
    off_zone_names: np.ndarray = None
    off_ct_names: np.ndarray = None


import threading
import time
from collections import OrderedDict


class SolverCircuitBreaker:
    """Device-failure circuit breaker on the tensor solve path.

    The host oracle is always a correct (slower) fallback, so a *crashing*
    tensor path — device OOM, runtime wedged, kernel bug on an unforeseen
    shape — must degrade the solver to the oracle instead of failing every
    provisioning pass through its retry budget. Classic three-state
    breaker: CLOSED counts consecutive tensor-path exceptions; at
    `threshold` it OPENs (every solve goes straight to the host with
    fallback_reason="circuit_open", no tensor attempt, no device touch);
    after `cooldown` seconds the next solve HALF-OPENs as a probe — one
    success re-closes, one failure re-opens for another cooldown.

    The closed-state hot path is a single attribute compare — zero
    measurable overhead on the headline solve (BENCH_MODE=faults pins
    this). State transitions publish the solver_circuit_state gauge
    (0=closed, 1=open, 2=half-open) — only when constructed with
    `publish=True`: the gauge is a single series, so exactly one breaker
    (the process-wide SOLVER_CIRCUIT) owns it; ad-hoc breakers (bench,
    tests, experiments) must not stomp the production export. `now` is
    injectable for fake-clock tests; the default is monotonic wall time.
    Thread-safe: the sidecar serves solves from a thread pool, so failure
    counting and transitions take a lock (concurrent half-open probes are
    allowed — worst case a few extra probes race, all of which must
    succeed to matter)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    _GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(self, threshold: int = 5, cooldown: float = 30.0,
                 now=None, publish: bool = False):
        self.threshold = threshold
        self.cooldown = cooldown
        self._now = now or time.monotonic
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at = 0.0
        self._publish_metric = publish
        self.state = self.CLOSED
        self._publish()

    def _publish(self) -> None:
        if not self._publish_metric:
            return
        from ..metrics.registry import SOLVER_CIRCUIT_STATE
        SOLVER_CIRCUIT_STATE.set(self._GAUGE[self.state])

    def allow(self) -> bool:
        """May this solve attempt the tensor path?"""
        if self.state == self.CLOSED:
            return True
        with self._lock:
            if self.state == self.OPEN \
                    and self._now() - self._opened_at >= self.cooldown:
                self.state = self.HALF_OPEN
                self._publish()
            return self.state != self.OPEN

    def record_success(self) -> None:
        if self._failures == 0 and self.state == self.CLOSED:
            return  # hot path: nothing to reset, skip the lock
        with self._lock:
            self._failures = 0
            if self.state != self.CLOSED:
                self.state = self.CLOSED
                self._publish()

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self.state == self.HALF_OPEN \
                    or self._failures >= self.threshold:
                self._opened_at = self._now()
                if self.state != self.OPEN:
                    self.state = self.OPEN
                    self._publish()

    def reset(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = 0.0
            if self.state != self.CLOSED:
                self.state = self.CLOSED
                self._publish()


# Process-wide breaker: TensorScheduler instances are constructed per solve
# (provisioner scheduler_factory), so breaker state MUST outlive them. Sole
# owner of the solver_circuit_state gauge.
SOLVER_CIRCUIT = SolverCircuitBreaker(publish=True)

_CATALOG_CACHE: "OrderedDict[tuple, _CatalogEncoding]" = OrderedDict()
_CATALOG_CACHE_MAX = 4
# the sidecar serves concurrent solves from a thread pool; the cache (and
# its LRU reordering) is the only cross-request mutable state on this path
_CATALOG_CACHE_LOCK = threading.Lock()


def _reqs_digest(reqs) -> tuple:
    return tuple(sorted(
        (r.key, r.complement, frozenset(r.values), r.greater_than, r.less_than)
        for r in reqs.values()))


def _catalog_cache_key(catalog: List[InstanceType]) -> tuple:
    """Content key over every fact the encoding depends on: name, requirement
    set, capacity/allocatable, and offerings. Requirements are keyed
    explicitly (not assumed stable per name) so a provider mutating an IT's
    requirement set in place can never reuse stale complement-encoded masks."""
    return tuple(
        (it.name, _reqs_digest(it.requirements),
         tuple(sorted(it.allocatable().items())),
         tuple(sorted(it.capacity.items())),
         tuple((o.zone, o.capacity_type, o.price, o.available)
               for o in it.offerings))
        for it in catalog)


def _ordered_union(its_lists) -> "Tuple[List[InstanceType], Dict[str, int]]":
    """Name-deduped instance-type union in first-seen order — THE union
    order behind the order-dependent catalog encodings. build_problem and
    catalog_cache_token must share it: a divergent order would key the
    device-encoding cache with a token for a differently-ordered encoding."""
    catalog: List[InstanceType] = []
    it_index: Dict[str, int] = {}
    for its in its_lists:
        for it in its:
            if it.name not in it_index:
                it_index[it.name] = len(catalog)
                catalog.append(it)
    return catalog, it_index


def catalog_cache_token(nodepools, instance_types) -> tuple:
    """Precomputed catalog cache key for callers whose catalog is immutable
    for their lifetime (the sidecar session): hashing 2k instance types per
    solve is pure overhead when the owner guarantees no in-place mutation.
    Uses build_problem's union order (_ordered_union; pools with no
    instance types contribute nothing either way)."""
    catalog, _ = _ordered_union(
        instance_types.get(np_.name, []) for np_ in nodepools)
    return _catalog_cache_key(catalog)


def catalog_encoding_pin(token):
    """Strong reference to the live CatalogEncoding for `token` (None when
    nothing is cached yet). Multi-tenant sidecar sessions pin their
    tenant's encoding: vocab IDENTITY gates every ProblemState row cache,
    so an LRU eviction forced by ANOTHER tenant's catalog traffic would
    silently demote this tenant's next solve to a cold re-encode."""
    with _CATALOG_CACHE_LOCK:
        return _CATALOG_CACHE.get(token)


def restore_catalog_encoding(token, ce) -> None:
    """Reinstate a pinned encoding the LRU evicted under other tenants'
    traffic — the PINNED object, never a re-encode, so vocab identity (and
    with it every delta cache keyed on it) survives. May briefly push the
    cache past its LRU cap; bounded by the sidecar's session cap."""
    if ce is None:
        return
    with _CATALOG_CACHE_LOCK:
        if token not in _CATALOG_CACHE:
            _CATALOG_CACHE[token] = ce
        _CATALOG_CACHE.move_to_end(token)


class TensorNodeClaim:
    """A launch decision produced by the tensor packer; interface-compatible
    with provisioning.scheduler.InFlightNodeClaim for downstream consumers."""

    def __init__(self, template: NodeClaimTemplate, requirements: Requirements,
                 instance_types: List[InstanceType], pods: List[Pod], requests: dict):
        self.template = template
        self.requirements = requirements
        self.instance_type_options = instance_types
        self.pods = pods
        self.requests = requests

    def finalize(self) -> None:
        self.requirements.delete(api_labels.LABEL_HOSTNAME)

    def remove_instance_types_by_price_and_min_values(self, reqs, max_price: float):
        """Consolidation price filter (nodeclaim.go:136-145)."""
        from ..cloudprovider.types import satisfies_min_values
        self.instance_type_options = [
            it for it in self.instance_type_options
            if it.offerings.available().worst_launch_price(reqs) < max_price]
        _, err = satisfies_min_values(self.instance_type_options, reqs)
        if err is not None:
            return None, err
        return self, None

    def to_nodeclaim(self) -> APINodeClaim:
        t = self.template
        reqs = self.requirements.copy()
        instance_types = self.instance_type_options[:MAX_INSTANCE_TYPES]
        mv = reqs.get(api_labels.LABEL_INSTANCE_TYPE).min_values
        reqs.add(Requirement(api_labels.LABEL_INSTANCE_TYPE, IN,
                             [it.name for it in instance_types], min_values=mv))
        return APINodeClaim(
            metadata=ObjectMeta(
                name=f"{t.nodepool_name}-{next(claim_name_seq):05d}",
                labels=dict(t.labels), annotations=dict(t.annotations),
                owner_refs=[OwnerReference(kind="NodePool", name=t.nodepool_name,
                                           uid=t.nodepool_uid, block_owner_deletion=True)]),
            spec=NodeClaimSpec(
                requirements=[_req_to_selector(r) for r in reqs.values()],
                resources_requests=dict(self.requests),
                taints=list(t.taints), startup_taints=list(t.startup_taints),
                node_class_ref=t.node_class_ref, expire_after=t.expire_after,
                termination_grace_period=t.termination_grace_period))


@dataclass
class TensorExistingNode:
    state_node: object
    pods: List[Pod]

    @property
    def name(self):
        return self.state_node.name()


class TensorScheduler:
    def __init__(self, nodepools, instance_types: Dict[str, List[InstanceType]],
                 state_nodes=(), daemonset_pods: List[Pod] = (),
                 cluster: Optional[ClusterView] = None,
                 initial_zone_counts=None, force_tensor: bool = False,
                 mesh=None, catalog_token: Optional[tuple] = None,
                 circuit: Optional[SolverCircuitBreaker] = None,
                 unavailable=None, problem_state=None,
                 pack_shards: int = 0):
        self.nodepools = list(nodepools)
        self.instance_types = instance_types
        self.state_nodes = list(state_nodes)
        self.daemonset_pods = list(daemonset_pods)
        self.cluster = cluster or ClusterView()
        self.initial_zone_counts = initial_zone_counts  # callable (group, zones)->counts
        self.force_tensor = force_tensor
        # optional jax.sharding.Mesh: run the feasibility precompute sharded
        # over a multi-chip mesh (parallel/mesh.py) instead of single-device
        self.mesh = mesh
        # > 1: pods/groups-sharded HIERARCHICAL pack (parallel/mesh.
        # sharded_pack, DEVIATIONS 22) — per-shard packs + cross-shard
        # remainder reconcile. Opt-in: decisions may differ from the
        # sequential pack in remainder-node composition (pod errors stay
        # exact), so the default 0 keeps every caller on the oracle-exact
        # sequential pack. Engages only when the problem passes the
        # pack_shardable() gate; a ProblemState warm start composes (the
        # pack carries per-shard seeds + a reconcile memo on the WarmStart).
        self.pack_shards = pack_shards
        # precomputed catalog cache key (catalog_cache_token): ONLY valid
        # when the caller guarantees the catalog is never mutated in place
        self.catalog_token = catalog_token
        # shared breaker by default: schedulers are per-solve, trips aren't
        self.circuit = circuit if circuit is not None else SOLVER_CIRCUIT
        # state.unavailable.UnavailableOfferings: live entries are masked
        # out of off_available / it_price before every solve (tensor path)
        # and out of the catalog copies the host fallback sees, so neither
        # solver ever places onto an offering known to be dry
        self.unavailable = unavailable
        # the pattern set the LAST solve actually masked with: consumers
        # that must reproduce this solve's view (the flight recorder's
        # captured catalog) read these instead of the live registry, whose
        # TTLs keep ticking under a real clock. _drought_pinned marks that
        # THIS solve already snapshotted them (tensor build), so a host
        # fallback later in the same solve reuses the identical view.
        self.drought_patterns: tuple = ()
        self._drought_pinned = False
        # optional flightrec.FlightRecorder: every solve() is captured as a
        # replayable DecisionRecord. None (the default) costs one attribute
        # compare per solve.
        self.flight_recorder = None
        self.fallback_reason: str = ""
        # provisioning.problem_state.ProblemState: the persistent cross-pass
        # delta cache (node rows, group rows, topology-count memo, warm-pack
        # seed). None (the default) keeps the self-contained cold path —
        # disruption simulation probes and ad-hoc schedulers never share it.
        self.problem_state = problem_state
        if problem_state is not None:
            # bind the state to this scheduler's mesh/shard identity: a
            # flip (mesh recreated over other devices, shard count change,
            # mesh dropped) drops the per-shard seeds + reconcile memo so
            # a mesh<->single-device swap in one process can never replay
            # artifacts recorded under the other carve
            if mesh is not None:
                from ..parallel.mesh import (PODS_GROUPS_AXIS,
                                             mesh_cache_key)
                problem_state.attach_mesh(
                    mesh_cache_key(mesh),
                    int(dict(mesh.shape).get(PODS_GROUPS_AXIS, 0)),
                    pack_shards)
            else:
                problem_state.attach_mesh(None, 0, pack_shards)
        # trace id of the pass this scheduler's last solve() ran under
        # ("" when tracing is disabled): stamped onto flight-recorder
        # records and the provisioner's summary log line
        self.last_trace_id = ""
        # "cold" | "delta": how this solve's problem encode was produced
        # (delta = cached rows against an unchanged vocabulary). Recorded on
        # every flight-recorder DecisionRecord; replay re-encodes cold, so a
        # byte-identical replay verdict on a delta record pins the delta
        # path's determinism contract.
        self.encode_kind = "cold"
        # (pods solved on the tensor path, pods handed to the host pass)
        self.partition = (0, 0)
        # per-solve fallback cost attribution (obs/fallbacks.py): shape-
        # class pod counts + the host-vs-tensor wall split of the LAST
        # solve — the fleet simulator and /debug/fallbacks read this
        self.fallback_attribution: dict = {}
        # which subsystem's traffic this scheduler's solves represent in
        # the fallback ledger: the provisioner's simulation entry point
        # (schedule_with(record=False)) and the DisruptionSnapshot flip
        # this to "disruption" EXPLICITLY, so candidate-build probes never
        # pollute the headline provisioning totals even with tracing off
        # (the root-span heuristic in _record_fallbacks is a backstop,
        # not the source of truth)
        self.ledger_subsystem = "provisioning"
        self._breakdown: list = []     # partition_pods (reason, count) rows
        self._tensor_seconds = 0.0
        self._host_seconds = 0.0
        # per-instance state-node encoding memo keyed by vocab identity:
        # the disruption snapshot builds several problems against the SAME
        # frozen node set + catalog vocab per pass, and re-encoding 5k node
        # label sets per build was the dominant host cost (group-side work
        # is tiny). Provisioning constructs a scheduler per solve, so the
        # memo is exactly one-pass-scoped there too.
        self._exist_memo: dict = {}

    # -- public -------------------------------------------------------------

    def solve(self, pods: List[Pod], prebuckets=None) -> Results:
        from ..utils.gcpause import no_gc
        rec = self.flight_recorder
        # roots its own PassTrace when no pass span is active (bench, sims);
        # nests under the provisioner/disruption pass loop otherwise
        with TRACER.span("solve", pods=len(pods)) as sp:
            started = time.perf_counter() if rec is not None else 0.0
            with no_gc():
                results = self._solve(pods, prebuckets)
            sp.set(encode_kind=self.encode_kind,
                   fallback_reason=self.fallback_reason)
            TRACER.annotate(encode_kind=self.encode_kind)
            # the pass trace_id joins this solve's trace, its flight-recorder
            # record, and the provisioner's log line
            self.last_trace_id = TRACER.current_trace_id()
            self._record_fallbacks(len(pods))
            if rec is not None:
                rec.capture_provisioning(self, pods, results,
                                         time.perf_counter() - started)
        return results

    def _solve(self, pods: List[Pod], prebuckets=None) -> Results:
        # fresh registry snapshot per solve (see drought_patterns)
        self._drought_pinned = False
        self.encode_kind = "cold"
        self._breakdown = []
        self._tensor_seconds = 0.0
        self._host_seconds = 0.0
        if self.problem_state is not None:
            self.problem_state.begin_solve()
        # port eligibility needs existing-node usage: a port occupied on a
        # live node makes its pods CONFLICTED (capped groups with per-node
        # exclusion) instead of constraint-free
        if self.state_nodes:
            usages = [sn.host_port_usage() for sn in self.state_nodes]

            def port_occupied(triples):
                return any(u.conflicts_triples(triples) for u in usages)
        else:
            port_occupied = lambda triples: False  # noqa: E731
        groups, leftover, reason = partition_pods(
            pods, prebuckets=prebuckets, port_occupied=port_occupied,
            breakdown=self._breakdown)
        self.partition = (sum(g.count for g in groups), len(leftover))
        if not groups:
            return self._host_solve(pods, reason)
        if not self.force_tensor and not self.circuit.allow():
            # breaker open: the device path crashed repeatedly — serve
            # from the host oracle without touching the device until the
            # cooldown's half-open probe
            return self._host_solve(pods, "circuit_open")
        eligible = [p for g in groups for p in g.pods]
        t0 = time.perf_counter()
        try:
            try:
                results = self._tensor_solve(groups, eligible)
            finally:
                self._tensor_seconds += time.perf_counter() - t0
        except _FallbackError as e:
            # expected expressibility fallback: the kernel worked as
            # designed, so the breaker doesn't count it either way
            return self._host_solve(pods, str(e))
        except Exception as e:  # noqa: BLE001 — device-failure degradation
            from ..parallel.mesh import DeviceLadderExhausted
            if isinstance(e, DeviceLadderExhausted):
                # every ladder rung is gone: each lost device already fed
                # its OWN breaker, so the global one must not double-trip
                # — serve the host oracle and let the next pass's
                # half-open probes re-test the fleet
                return self._host_solve(pods,
                                        f"device ladder exhausted: {e}")
            self.circuit.record_failure()
            if self.force_tensor:
                raise
            return self._host_solve(pods, f"tensor solve failed: {e!r}")
        self.circuit.record_success()
        # the host pass only adds value over the packer for pods whose group
        # carries relaxable preferences (the relaxation ladder,
        # preferences.go:38-57) — for everything else it re-derives the same
        # verdict at O(pods x claims) host cost, so packer errors on
        # non-relaxable groups are final
        relaxable_err = None
        if results.pod_errors and not self.force_tensor:
            # errors minted while a nodepool LIMIT was excluding capacity
            # aren't oracle-final: the greedy order decides who gets the
            # scarce budget, and the packer's group order can strand a pod
            # the host's pod order would place — re-solve on the host path
            # (the oracle). Bounded cost: limits+errors batches are rare.
            if results.limit_constrained:
                return self._host_solve(
                    pods, "pack errors under nodepool limit pressure")
            err_uids = set(results.pod_errors)
            relaxable_err = [
                g for g in groups
                if g.has_relaxable and any(p.uid in err_uids for p in g.pods)]
        if not leftover:
            if relaxable_err:
                return self._host_solve(
                    pods, "unscheduled pods with relaxable preferences")
            return results
        # partitioned: the tensor bulk is committed; stragglers (plus any
        # relaxable-group pods the packer couldn't place — they get the
        # host's relaxation ladder) run through a host scheduler seeded with
        # the tensor placements, so capacity and in-flight nodes are shared
        # (scheduler.go:267-283 semantics: existing -> in-flight -> new)
        retry = [p for g in (relaxable_err or []) for p in g.pods
                 if p.uid in results.pod_errors]
        retry_uids = {p.uid for p in retry}
        kept_errors = {uid: err for uid, err in results.pod_errors.items()
                       if uid not in retry_uids}
        final = self._host_solve_remainder(leftover + retry, results)
        for uid, err in kept_errors.items():
            final.pod_errors.setdefault(uid, err)
        return final

    def _explain_errors(self, errors: Dict[str, str], groups, templates
                        ) -> None:
        """Error-message parity for the kernel's generic verdicts: when a
        group failed because NO template's requirements admit it, rewrite
        'no instance type satisfied the pod' into the host oracle's
        per-nodepool incompatibility string (scheduler.py:600-621) —
        including the near-miss label hints (requirements.go:189-251) that
        operators debug typos with."""
        explained: Dict[int, Optional[str]] = {}
        uid_group = {p.uid: gi for gi, g in enumerate(groups)
                     for p in g.pods}
        for uid, msg in errors.items():
            if msg != "no instance type satisfied the pod":
                continue
            gi = uid_group.get(uid)
            if gi is None:
                continue
            if gi not in explained:
                parts = []
                for nct in templates:
                    errs = nct.requirements.compatible(
                        groups[gi].requirements, ALLOW_UNDEFINED_WELL_KNOWN)
                    if errs:
                        # byte-for-byte the host oracle's string:
                        # scheduler.py:614 wraps scheduler.py:122's
                        # "incompatible requirements, {first error}"
                        # (nodeclaim.go:83 wraps the same way)
                        parts.append(
                            f'incompatible with nodepool '
                            f'"{nct.nodepool_name}", incompatible '
                            f'requirements, {errs[0]}')
                # only a FULLY requirement-incompatible group gets the
                # rewrite: with any compatible template the failure is
                # resource-shaped and the generic message is the truth
                explained[gi] = ("; ".join(parts)
                                 if len(parts) == len(templates) else None)
            if explained[gi]:
                errors[uid] = explained[gi]

    def _host_solve(self, pods: List[Pod], reason: str) -> Results:
        self.fallback_reason = reason
        with TRACER.span("host.solve", pods=len(pods), reason=reason):
            t0 = time.perf_counter()
            try:
                return self._make_host(pods).solve(pods)
            finally:
                self._host_seconds += time.perf_counter() - t0

    def _record_fallbacks(self, n_pods: int) -> None:
        """Assemble this solve's fallback cost attribution and feed the
        process-wide ledger. Per-class pod counts come from the
        partitioner's breakdown; a whole-batch fallback (circuit open,
        device error, an expressibility _FallbackError, limit-pressure or
        relaxable-preference re-solves) additionally charges the
        tensor-eligible pods to the fallback's own class, since they ran
        host too. A solve under a disruption.pass root is a candidate-build
        probe, not provisioning traffic — attributed to the disruption
        subsystem so ROADMAP item-1 priorities read clean."""
        from ..obs.fallbacks import (LEDGER, classify_breakdown,
                                     classify_reason)
        classes = classify_breakdown(self._breakdown)
        tensor_pods, host_pods = self.partition
        if self.fallback_reason:
            if tensor_pods:
                c = classify_reason(self.fallback_reason)
                classes[c] = classes.get(c, 0) + tensor_pods
            tensor_pods, host_pods = 0, n_pods
        self.fallback_attribution = {
            "classes": classes,
            "tensor_pods": tensor_pods,
            "host_pods": host_pods,
            "tensor_seconds": self._tensor_seconds,
            "host_seconds": self._host_seconds,
        }
        subsystem = self.ledger_subsystem
        if subsystem == "provisioning" \
                and TRACER.current_root_name().startswith("disruption"):
            # backstop for unflagged schedulers running under a disruption
            # pass (the explicit flag is the source of truth — it also
            # works with tracing disabled)
            subsystem = "disruption"
        LEDGER.record_solve(
            classes, tensor_pods, host_pods,
            self._tensor_seconds, self._host_seconds,
            trace_id=self.last_trace_id, encode_kind=self.encode_kind,
            subsystem=subsystem)

    def _make_host(self, pods: List[Pod]) -> Scheduler:
        from .domains import build_topology_domains
        instance_types = self.instance_types
        if self.unavailable is not None:
            # the host oracle reads offering availability off the catalog
            # objects, so the registry mask rides in as available=False
            # copies — fallback solves route around droughts exactly like
            # the tensor path's off_available mask. Patterns are pinned
            # once per solve so a tensor attempt, its host remainder, and
            # the capture/replay view all share ONE registry snapshot.
            from ..state.unavailable import mask_catalog
            if not self._drought_pinned:
                self.drought_patterns = self.unavailable.live()
                self._drought_pinned = True
            instance_types = mask_catalog(instance_types,
                                          self.drought_patterns)
        domains = build_topology_domains(self.nodepools, instance_types)
        topo = Topology(self.cluster, domains, pods)
        return Scheduler(self.nodepools, instance_types, topo,
                         state_nodes=self.state_nodes,
                         daemonset_pods=self.daemonset_pods)

    def _host_solve_remainder(self, pods: List[Pod], tensor_results: Results
                              ) -> Results:
        """Run the host oracle over the straggler pods with the tensor bulk's
        placements already committed: existing-node usage is seeded so
        capacity isn't double-booked, the tensor launch decisions become
        in-flight claims the host greedy can keep packing
        (scheduler.go:267-283), and every tensor-placed pod is recorded into
        the host Topology's domain counts. The recording matters for RETRY
        pods — tensor-eligible pods the packer failed to place share labels
        and self-selecting spread/affinity selectors with their tensor-placed
        groupmates, so the host solve's skew arithmetic must see the tensor
        half. (Leftover pods can't couple by construction — partition_pods
        demotes any group whose selectors touch host-side pods.)"""
        with TRACER.span("host.remainder", pods=len(pods)):
            t0 = time.perf_counter()
            try:
                return self._host_remainder(pods, tensor_results)
            finally:
                self._host_seconds += time.perf_counter() - t0

    def _host_remainder(self, pods: List[Pod], tensor_results: Results
                        ) -> Results:
        from .scheduler import InFlightNodeClaim, _subtract_max
        host = self._make_host(pods)
        by_name = {en.name: en for en in host.existing_nodes}
        for ten in tensor_results.existing_nodes:
            en = by_name.get(ten.name)
            if en is None or not ten.pods:
                continue
            en.pods.extend(ten.pods)
            en.requests = res.merge(en.requests,
                                    *(p.requests() for p in ten.pods))
            for p in ten.pods:
                host.topology.record(p, en.requirements)
                # seed CSI attach usage too, or a host-side volume pod
                # double-books the slots the tensor pass just consumed
                # (volumeusage.go:201-208)
                if p.spec.volumes and en._volume_usage is not None \
                        and en._store is not None:
                    from ..scheduling.volumeusage import get_volumes
                    en._volume_usage.add(get_volumes(en._store, p))
                # seed port usage too: a host-side port pod must see the
                # slots the tensor pass just bound (hostportusage.go:34-90)
                if p.spec.host_ports:
                    from ..scheduling.hostports import get_host_ports
                    en._host_port_usage.add(p, get_host_ports(p))
        tmpl_idx = {t.nodepool_name: i for i, t in enumerate(host.templates)}
        for tnc in tensor_results.new_nodeclaims:
            i = tmpl_idx.get(tnc.template.nodepool_name)
            if i is None:
                continue
            nct = host.templates[i]
            nc = InFlightNodeClaim(nct, host.topology, host.daemon_overhead[i],
                                   tnc.instance_type_options)
            nc.requirements.add(*tnc.requirements.values())
            nc.pods = list(tnc.pods)
            nc.requests = res.merge(nc.requests, tnc.requests)
            for p in nc.pods:
                host.topology.record(p, nc.requirements,
                                     ALLOW_UNDEFINED_WELL_KNOWN)
                if p.spec.host_ports:
                    from ..scheduling.hostports import get_host_ports
                    nc.host_port_usage.add(p, get_host_ports(p))
            host.new_nodeclaims.append(nc)
            remaining = host.remaining_resources.get(nct.nodepool_name)
            if remaining is not None:
                host.remaining_resources[nct.nodepool_name] = _subtract_max(
                    remaining, nc.instance_type_options)
        return host.solve(pods)

    # -- tensor path ----------------------------------------------------------

    def precompute(self, problem) -> binpack.PackTensors:
        """Device feasibility precompute, sharded over self.mesh when set
        (behind the device-loss degradation ladder: a device lost
        mid-dispatch re-places the solve on the surviving carve instead of
        failing the pass). Shared by the provisioning solve and the
        consolidation prefix simulator (disruption/prefix.py), so one mesh
        knob scales both."""
        if self.mesh is not None:
            from ..parallel.mesh import resilient_precompute
            return resilient_precompute(problem, self.mesh)
        return binpack.precompute(problem)

    def build_problem(self, groups: List[PodGroup]):
        """Encode groups + catalog + state into a PackProblem; returns
        (problem, templates, catalog). Raises _FallbackError when the batch
        isn't expressible."""
        with TRACER.span("build_problem", groups=len(groups),
                         nodes=len(self.state_nodes)) as sp:
            out = self._build_problem(groups)
            sp.set(encode_kind=self.encode_kind)
            return out

    def _build_problem(self, groups: List[PodGroup]):
        templates: List[NodeClaimTemplate] = []
        for np_ in self.nodepools:
            nct = NodeClaimTemplate(np_)
            nct.instance_type_options = self.instance_types.get(np_.name, [])
            if nct.instance_type_options:
                templates.append(nct)
        if not templates:
            raise _FallbackError("no nodepools with instance types")

        # union instance-type catalog (shared order contract: _ordered_union)
        catalog, it_index = _ordered_union(
            nct.instance_type_options for nct in templates)
        T = len(catalog)
        M = len(templates)
        G = len(groups)

        ckey = (self.catalog_token if self.catalog_token is not None
                else _catalog_cache_key(catalog))
        with _CATALOG_CACHE_LOCK:
            ce = _CATALOG_CACHE.get(ckey)
        if ce is not None and not self._fits_vocab(ce.vocab, templates, groups):
            ce = None
        if ce is None:
            ce = self._encode_catalog(catalog, templates, groups)
        with _CATALOG_CACHE_LOCK:
            existing = _CATALOG_CACHE.get(ckey)
            if existing is not None and existing is not ce and \
                    self._fits_vocab(existing.vocab, templates, groups):
                ce = existing  # a concurrent request encoded it first
            else:
                if ckey not in _CATALOG_CACHE and \
                        len(_CATALOG_CACHE) >= _CATALOG_CACHE_MAX:
                    # LRU: catalogs alternate under multi-provider or prefix
                    # probing — evicting the least-recently-USED entry keeps
                    # the hot ones device-resident (was: arbitrary pop)
                    _CATALOG_CACHE.popitem(last=False)
                _CATALOG_CACHE[ckey] = ce
            # mark most-recently-used on hit AND on (re-)encode: a vocab-
            # overflow re-encode overwrites in place, which alone preserves
            # LRU position
            _CATALOG_CACHE.move_to_end(ckey)
        vocab = ce.vocab
        zone_key, captype_key = ce.zone_key, ce.captype_key
        it_enc, it_alloc, it_capacity = ce.it_enc, ce.it_alloc, ce.it_capacity
        it_price = ce.it_price
        off_zone, off_captype = ce.off_zone, ce.off_captype
        off_available, off_price = ce.off_available, ce.off_price
        zone_values, allow_undefined = ce.zone_values, ce.allow_undefined
        device_cache = ce.device_cache
        masked = self._drought_arrays(ce)
        if masked is not None:
            off_available, off_price, it_price, device_cache = masked

        ps = self.problem_state
        with TRACER.span("encode.groups", groups=G) as gsp:
            if ps is not None:
                # (_drought_arrays above already pinned this solve's registry
                # snapshot, so the warm-pack global token reads a stable view)
                self.encode_kind = ps.note_encode(vocab)
                g_rows = [ps.group_row(vocab, g) for g in groups]
                group_enc = enc.stack_encoded([r[0] for r in g_rows])
                group_req = np.stack([r[1] for r in g_rows])
                gsp.set(encoded=ps.last["group_rows_encoded"])
            else:
                group_enc = enc.stack_encoded(
                    [enc.encode_requirements(vocab, g.requirements)
                     for g in groups])
                group_req = np.stack(
                    [enc.encode_resource_vector(vocab, g.requests,
                                                capacity=False)
                     for g in groups])
        template_enc = enc.stack_encoded(
            [enc.encode_requirements(vocab, t.requirements) for t in templates])
        daemon = np.stack([
            enc.encode_resource_vector(vocab, _daemon_overhead(t, self.daemonset_pods),
                                       capacity=False)
            for t in templates])
        template_its = np.zeros((M, T), dtype=bool)
        for m, nct in enumerate(templates):
            for it in nct.instance_type_options:
                template_its[m, it_index[it.name]] = True

        # taints: host-checked per (group, template) and (group, existing node)
        tol_template = np.zeros((G, M), dtype=bool)
        for gi, g in enumerate(groups):
            probe = g.pods[0]
            for m, nct in enumerate(templates):
                tol_template[gi, m] = not scheduling_taints.tolerates(nct.taints, probe)

        min_its = self._min_its_floor(templates, groups)

        exist_enc = exist_avail = exist_zone = tol_exist = None
        exist_token = None
        if self.state_nodes and ps is not None:
            # persistent per-node rows: only dirty rows re-encode, and the
            # padded stack (plus its device upload, via exist_token) is
            # reused while the node set is unchanged
            with TRACER.span("encode.nodes",
                             nodes=len(self.state_nodes)) as nsp:
                (exist_enc, exist_avail, exist_zone, taint_lists,
                 exist_token) = ps.node_rows(vocab, zone_key,
                                             self.state_nodes,
                                             self.daemonset_pods)
                tol_exist = _tol_exist_matrix(groups, taint_lists,
                                              exist_enc.mask.shape[0])
                nsp.set(dirty=ps.last["node_rows_reencoded"])
                sd = ps.last.get("shard_dirty")
                if sd is not None:
                    # per-shard dirty-row counts, "shard:count" pairs —
                    # the sharded state's delta-residency trace signal
                    nsp.set(shard_dirty=",".join(
                        f"{s}:{d}" for s, d in sorted(sd.items())))
        elif self.state_nodes:
            with TRACER.span("encode.nodes", nodes=len(self.state_nodes)):
                exist_enc, exist_avail, exist_zone, tol_exist = \
                    self._cold_node_rows(vocab, zone_key, groups, G)

        group_count = np.array([g.count for g in groups], dtype=np.int64)
        if ps is not None:
            # group-axis pow2 bucket: steady-state churn nudges G every
            # pass; stable padded shapes keep the compiled-executable cache
            # hitting (the node axis is already bucketed). Padded rows are
            # empty-Requirements with zero requests — never packable, and
            # the packer only iterates the real G anyway.
            Gp = _pow2_bucket(G, 16)
            if Gp > G:
                pad = Gp - G
                zero = enc.encode_requirements(vocab, Requirements())
                group_enc = enc.pad_stacked(group_enc, Gp, zero)
                group_req = np.concatenate(
                    [group_req, np.zeros((pad,) + group_req.shape[1:],
                                         group_req.dtype)])
                group_count = np.concatenate(
                    [group_count, np.zeros(pad, np.int64)])
                tol_template = np.concatenate(
                    [tol_template, np.zeros((pad, M), bool)])
                if tol_exist is not None:
                    tol_exist = np.concatenate(
                        [tol_exist,
                         np.zeros((pad, tol_exist.shape[1]), bool)])

        problem = binpack.PackProblem(
            vocab=vocab, group_enc=group_enc, group_req=group_req,
            group_count=group_count,
            template_enc=template_enc, daemon_overhead=daemon,
            tol_template=tol_template, it_enc=it_enc, it_alloc=it_alloc,
            it_capacity=it_capacity, it_price=it_price, template_its=template_its,
            off_zone=off_zone, off_captype=off_captype, off_available=off_available,
            zone_key=zone_key, captype_key=captype_key, zone_values=zone_values,
            off_price=off_price,
            exist_enc=exist_enc, exist_avail=exist_avail, exist_zone=exist_zone,
            tol_exist=tol_exist, allow_undefined=allow_undefined,
            device_cache=device_cache, min_its=min_its,
            exist_token=exist_token,
            exist_shard_tokens=(ps.exist_shard_tokens
                                if ps is not None and exist_token is not None
                                else None))
        return problem, templates, catalog

    def _cold_node_rows(self, vocab, zone_key: int, groups, G: int):
        """State-node encode for the self-contained (no ProblemState) path,
        memoized per vocab identity; returns the pow2-padded
        (exist_enc, exist_avail, exist_zone, tol_exist)."""
        memo = self._exist_memo.get(id(vocab))
        if memo is None:
            encs, avails, zones, taint_lists = [], [], [], []
            for sn in self.state_nodes:
                reqs = label_requirements(sn.labels())
                known = Requirements(
                    r for r in reqs.values()
                    if api_labels.NORMALIZED_LABELS.get(r.key, r.key)
                    in vocab.key_idx)
                encs.append(enc.encode_requirements(vocab, known))
                node_daemons = _node_remaining_daemons(
                    sn, self.daemonset_pods)
                avail = res.subtract(sn.available(), node_daemons)
                avails.append(enc.encode_resource_vector(vocab, avail,
                                                         capacity=True))
                z = sn.labels().get(api_labels.LABEL_TOPOLOGY_ZONE, "")
                zones.append(vocab.value_idx[zone_key].get(z, -1))
                taint_lists.append(sn.taints())
            # the memo holds the vocab itself so its id() can never be
            # recycled by a new object while the entry is alive
            memo = (vocab, encs, np.stack(avails),
                    np.array(zones, dtype=np.int32), taint_lists)
            self._exist_memo[id(vocab)] = memo
        _, encs, avail_rows, zone_rows, taint_lists = memo
        tol_exist = _tol_exist_matrix(groups, taint_lists,
                                      len(self.state_nodes))
        exist_enc = enc.stack_encoded(encs)
        exist_avail = avail_rows.copy()
        exist_zone = zone_rows.copy()
        # bucket the node-batch axis: padded rows have undefined masks and
        # zero capacity, so they are never packable (exist_cap < 1)
        N = len(self.state_nodes)
        Np = _pow2_bucket(N, 16)
        if Np > N:
            pad = Np - N
            zero = enc.encode_requirements(vocab, Requirements())
            exist_enc = enc.stack_encoded(
                encs + [zero] * pad)
            exist_avail = np.concatenate(
                [exist_avail, np.zeros((pad,) + exist_avail.shape[1:],
                                       exist_avail.dtype)])
            exist_zone = np.concatenate(
                [exist_zone, np.full(pad, -1, np.int32)])
            tol_exist = np.concatenate(
                [tol_exist, np.zeros((G, pad), bool)], axis=1)
        return exist_enc, exist_avail, exist_zone, tol_exist

    def _drought_arrays(self, ce: _CatalogEncoding):
        """Registry-masked (off_available, off_price, it_price,
        device_cache) for this solve, or None when no live entry touches
        the catalog. The mask is built by matching the registry's live
        (instance_type, zone, capacity_type) patterns against the
        encoding's cached identity arrays in a few vectorized passes — a
        zone-wide drought is one [T, O] compare, not 16k Python checks.
        A fully masked type's it_price becomes +inf (the empty-offerings
        contract, types.go:117-134). The masked device upload is cached
        per live-pattern set so repeated solves under the same drought
        state stay as upload-free as the unmasked path."""
        from ..state.unavailable import WILDCARD
        reg = self.unavailable
        if reg is None:
            return None
        # pinned once per solve/pass (like _make_host): a disruption
        # snapshot builds MANY problems through this one scheduler, and a
        # TTL lapsing mid-pass must not price candidate sets of the same
        # decision under different masks — nor leave drought_patterns
        # disagreeing with the mask the recorded winner sim actually used
        if not self._drought_pinned:
            self.drought_patterns = reg.live()
            self._drought_pinned = True
        patterns = self.drought_patterns
        if not patterns:
            return None
        hit = np.zeros(ce.off_available.shape, dtype=bool)
        for pit, pz, pct in patterns:
            m = np.ones(ce.off_available.shape, dtype=bool)
            if pit != WILDCARD:
                m &= (ce.off_names == pit)[:, None]
            if pz != WILDCARD:
                m &= ce.off_zone_names == pz
            if pct != WILDCARD:
                m &= ce.off_ct_names == pct
            hit |= m
        hit &= ce.off_available
        if not hit.any():
            return None
        off_available = ce.off_available & ~hit
        off_price = np.where(off_available, ce.off_price,
                             np.inf).astype(np.float32)
        it_price = off_price.min(axis=1)
        slot = ce.device_cache.get("drought")
        if slot is None or slot[0] != patterns:
            slot = (patterns, {})
            ce.device_cache["drought"] = slot
        return off_available, off_price, it_price, slot[1]

    @staticmethod
    def _min_its_floor(templates, groups) -> Optional[np.ndarray]:
        """[M, G] int32 minValues floor on distinct instance types for each
        combined (template, group) requirement set (intersection takes the
        max of both sides' minValues, requirement.py:86), or None when no
        floor exists anywhere. The packer enforces it per fill — the tensor
        twin of the per-add SatisfiesMinValues gate. minValues on any OTHER
        key needs per-key distinct-value counting over the surviving set;
        that stays on the host oracle."""
        def floor_of(reqs) -> int:
            mv = 0
            for r in reqs.values():
                if r.min_values:
                    if r.key != api_labels.LABEL_INSTANCE_TYPE:
                        raise _FallbackError(
                            f"minValues on {r.key} needs host-side "
                            "distinct-value tracking")
                    mv = max(mv, r.min_values)
            return mv

        mv_t = [floor_of(nct.requirements) for nct in templates]
        mv_g = [floor_of(g.requirements) for g in groups]
        if not any(mv_t) and not any(mv_g):
            return None
        return np.maximum(np.array(mv_t, dtype=np.int32)[:, None],
                          np.array(mv_g, dtype=np.int32)[None, :])

    def _fits_vocab(self, vocab, templates, groups) -> bool:
        """True when this solve introduces NO new vocabulary entry — the
        cache-reuse condition: every key/value a fresh build would observe
        from templates, groups, and state nodes is already present, so the
        cached masks (incl. complement rows, which enumerate the value
        universe) stay exact."""
        def reqs_fit(reqs: Requirements) -> bool:
            for key in reqs:
                norm = api_labels.NORMALIZED_LABELS.get(key, key)
                k = vocab.key_idx.get(norm)
                if k is None:
                    return False
                vi = vocab.value_idx[k]
                for v in reqs.get(key).values:
                    if v not in vi:
                        return False
            return True

        for nct in templates:
            if not reqs_fit(nct.requirements):
                return False
        for g in groups:
            if not reqs_fit(g.requirements):
                return False
            if any(r not in vocab.resource_idx for r in g.requests):
                return False
        for sn in self.state_nodes:
            reqs = label_requirements(sn.labels())
            for key in reqs:
                norm = api_labels.NORMALIZED_LABELS.get(key, key)
                k = vocab.key_idx.get(norm)
                if k is None:
                    continue  # node-only keys are never admitted (see below)
                vi = vocab.value_idx[k]
                for v in reqs.get(key).values:
                    if v not in vi:
                        return False
            if any(r not in vocab.resource_idx for r in sn.allocatable()):
                return False
        return True

    def _encode_catalog(self, catalog, templates, groups) -> _CatalogEncoding:
        """Fresh vocabulary + catalog-side tensors (the cacheable part of
        build_problem). Only COLD solves reach this — its span's absence is
        how a delta pass shows up in a trace."""
        with TRACER.span("encode.catalog", instance_types=len(catalog)):
            return self._encode_catalog_inner(catalog, templates, groups)

    def _encode_catalog_inner(self, catalog, templates, groups
                              ) -> _CatalogEncoding:
        vocab = enc.Vocab()
        zone_key = vocab.add_key(api_labels.LABEL_TOPOLOGY_ZONE)
        captype_key = vocab.add_key(api_labels.CAPACITY_TYPE_LABEL_KEY)
        for it in catalog:
            vocab.observe_requirements(it.requirements)
            vocab.observe_resources(it.capacity)
            for off in it.offerings:
                vocab.observe_requirements(off.requirements)
        for nct in templates:
            vocab.observe_requirements(nct.requirements)
        for g in groups:
            vocab.observe_requirements(g.requirements)
            vocab.observe_resources(g.requests)
        # Existing nodes only contribute VALUES for keys some group/template/
        # instance type already defines. A key defined solely by nodes (e.g.
        # kubernetes.io/hostname with one distinct value per node) can never
        # fail a compatibility check — the checked set is
        # a.defined & b.defined, and undefined-key violations only fire for
        # pod-side-defined keys (requirements.go:175-187) — so admitting it
        # would just blow the mask domain up to O(nodes) for nothing.
        for sn in self.state_nodes:
            reqs = label_requirements(sn.labels())
            for key in reqs:
                norm = api_labels.NORMALIZED_LABELS.get(key, key)
                if norm in vocab.key_idx:
                    for v in reqs.get(key).values:
                        vocab.add_value(norm, v)
            vocab.observe_resources(sn.allocatable())
        # power-of-two domain bucket: consolidation's prefix probes vary the
        # value counts per simulation; bucketing keeps mask shapes (and so
        # the jit cache) stable across probes
        vocab.freeze(domain_bucket=_pow2_bucket(vocab.D, 64))

        T = len(catalog)
        it_enc = enc.stack_encoded(
            [enc.encode_requirements(vocab, it.requirements) for it in catalog])
        it_alloc = np.stack([enc.encode_resource_vector(vocab, it.allocatable(), capacity=True)
                             for it in catalog])
        it_capacity = np.stack([enc.encode_resource_vector(vocab, it.capacity, capacity=True)
                                for it in catalog])
        O = max((len(it.offerings) for it in catalog), default=1)
        off_zone = np.full((T, O), -1, dtype=np.int32)
        off_captype = np.full((T, O), -1, dtype=np.int32)
        off_available = np.zeros((T, O), dtype=bool)
        off_price = np.full((T, O), np.inf, dtype=np.float32)
        it_price = np.full(T, np.inf, dtype=np.float32)
        off_names = np.array([it.name for it in catalog], dtype=object)
        off_zone_names = np.full((T, O), "", dtype=object)
        off_ct_names = np.full((T, O), "", dtype=object)
        for t, it in enumerate(catalog):
            for o, off in enumerate(it.offerings):
                if not off.available:
                    continue
                off_available[t, o] = True
                off_price[t, o] = off.price
                z = off.zone
                ct = off.capacity_type
                off_zone_names[t, o] = z
                off_ct_names[t, o] = ct
                if z:
                    off_zone[t, o] = vocab.value_idx[zone_key].get(z, -1)
                if ct:
                    off_captype[t, o] = vocab.value_idx[captype_key].get(ct, -1)
                it_price[t] = min(it_price[t], off.price)
        zone_values = np.arange(len(vocab.values[zone_key]), dtype=np.int32)
        allow_undefined = np.array([k in ALLOW_UNDEFINED_WELL_KNOWN
                                    for k in vocab.keys])
        return _CatalogEncoding(
            vocab=vocab, zone_key=zone_key, captype_key=captype_key,
            it_enc=it_enc, it_alloc=it_alloc, it_capacity=it_capacity,
            it_price=it_price, off_zone=off_zone, off_captype=off_captype,
            off_available=off_available, off_price=off_price,
            zone_values=zone_values, allow_undefined=allow_undefined,
            device_cache={}, off_names=off_names,
            off_zone_names=off_zone_names, off_ct_names=off_ct_names)

    def cluster_zone_counts(self, groups: List[PodGroup], zone_names,
                            exclude_uids) -> np.ndarray:
        """Back-compat view of cluster_topology_counts: zone counts only."""
        return self.cluster_topology_counts(groups, zone_names,
                                            exclude_uids)[0]

    def cluster_topology_counts(self, groups: List[PodGroup], zone_names,
                                exclude_uids):
        """The tensor twin of Topology countDomains (topology.go:268-321):
        initial domain occupancy from scheduled cluster pods matching each
        group's topology selectors, excluding the batch itself. Returns
        (izc [G, Z] per-zone counts for the group's zone-level constraint,
        exist_counts [G, N] per-packable-node counts for its hostname-level
        constraint, host_total [G] total hostname-level matches anywhere
        with a known node — the affinity no-bootstrap signal). The spread
        node filter (topologynodefilter.go) applies to spread constraints
        only; affinity groups count every matching pod."""
        from .grouping import HOST_KINDS, SPREAD_HOST, SPREAD_ZONE, ZONE_KINDS
        from .topology import TopologyNodeFilter, ignored_for_topology

        zone_idx = {z: i for i, z in enumerate(zone_names)}
        node_idx = {sn.name(): i for i, sn in enumerate(self.state_nodes)}
        G = len(groups)
        izc = np.zeros((G, len(zone_names)), dtype=np.int64)
        exist_counts = np.zeros((G, max(1, len(self.state_nodes))),
                                dtype=np.int64)
        host_total = np.zeros(G, dtype=np.int64)

        # the flagship two-constraint combo reuses one selector for both
        # specs: memoize list_pods per (namespace, selector shape) and
        # node_labels per node within the call
        def sel_key(namespace: str, sel) -> tuple:
            # LabelSelector normalizes match_labels to a tuple of pairs
            ml = getattr(sel, "match_labels", None) or ()
            if hasattr(ml, "items"):
                ml = tuple(sorted(ml.items()))
            me = getattr(sel, "match_expressions", None) or ()
            try:
                return (namespace, tuple(sorted(ml)), tuple(me))
            except TypeError:
                return (namespace, id(sel))

        pods_memo: dict = {}
        labels_memo: dict = {}

        def matched(namespace: str, sel):
            k = sel_key(namespace, sel)
            out = pods_memo.get(k)
            if out is None:
                out = []
                for p in self.cluster.list_pods(namespace, sel):
                    if p.uid in exclude_uids or ignored_for_topology(p):
                        continue
                    name = p.spec.node_name
                    if name not in labels_memo:
                        labels_memo[name] = self.cluster.node_labels(name)
                    if labels_memo[name] is not None:
                        out.append(p)
                pods_memo[k] = out
            return out

        for gi, g in enumerate(groups):
            # prefix probes can empty a group (all its pods belong to
            # non-prefix candidates); nothing pending means nothing to place
            if not g.topo or not g.pods:
                continue
            probe = g.pods[0]
            spread_filter = TopologyNodeFilter.for_pod(probe)
            for spec in g.topo:
                if spec.selector is None:
                    continue  # a nil selector selects nothing
                is_spread = spec.kind in (SPREAD_ZONE, SPREAD_HOST)
                for p in matched(probe.namespace, spec.selector):
                    labels = labels_memo[p.spec.node_name]
                    if is_spread and not spread_filter.matches_labels(labels):
                        continue
                    if spec.kind in ZONE_KINDS:
                        zone = labels.get(api_labels.LABEL_TOPOLOGY_ZONE)
                        if zone in zone_idx:
                            izc[gi, zone_idx[zone]] += 1
                    elif spec.kind in HOST_KINDS:
                        host_total[gi] += 1
                        n = node_idx.get(p.spec.node_name)
                        if n is not None:
                            exist_counts[gi, n] += 1
        return izc, exist_counts, host_total

    def _tensor_solve(self, groups: List[PodGroup], pods: List[Pod]) -> Results:
        self.fallback_reason = ""
        if any(p.spec.host_ports for p in self.daemonset_pods) and any(
                p.spec.host_ports for p in pods):
            # daemonset ports occupy EVERY node of a template; modeling
            # that per-template exclusion stays host-side (rare combo).
            # Checked against PODS, not groups: a batch-unique port pod
            # carries group.host_ports=() yet still binds its port — it
            # must not slip past this guard onto a daemonset's port
            raise _FallbackError(
                "daemonset host ports need per-pod conflict tracking")
        problem, templates, catalog = self.build_problem(groups)
        vocab = problem.vocab
        zone_key = problem.zone_key

        ps = self.problem_state
        with TRACER.span("precompute") as pcs:
            # persistent tensors memo (sharded-state churn fast path): the
            # device kernel's group side reads nothing that changes on a
            # pure count-wobble/node-churn pass, and the exist side feeds
            # ONLY exist_ok/exist_cap — so a group-part hit with a dirty
            # exist part runs the exist-only delta kernel (bit-identical
            # ops to the full kernel's exist branch) and splices the pair
            tensors = None
            memo_tok = None
            if ps is not None:
                memo_tok = (
                    (vocab, tuple(ps.sig(g) for g in groups), len(groups),
                     ps._daemon_token(self.daemonset_pods),
                     ps._templates_token(templates),
                     tuple(self.drought_patterns),
                     None if problem.min_its is None
                     else problem.min_its.tobytes(),
                     zone_key, problem.captype_key),
                    problem.exist_token)
                memo = ps.tensors_memo
                if memo is not None and memo[0] == memo_tok:
                    tensors = memo[1]
                    ps.last["precompute"] = "reused"
                elif (memo is not None and memo[0][0] == memo_tok[0]
                      and memo_tok[1] is not None
                      and problem.exist_enc is not None
                      and _single_process()):
                    import dataclasses
                    exist_ok, exist_cap = binpack.exist_delta(problem)
                    tensors = dataclasses.replace(
                        memo[1], exist_ok=exist_ok, exist_cap=exist_cap)
                    ps.last["precompute"] = "delta"
            if tensors is None:
                tensors = self.precompute(problem)
                if ps is not None:
                    ps.last["precompute"] = "computed"
            if ps is not None:
                ps.tensors_memo = (memo_tok, tensors)
                pcs.set(reused=ps.last["precompute"])

        # nodepool limits (scaled), minus existing node capacity per pool
        limits: List[Optional[dict]] = []
        for nct in templates:
            np_obj = next(p for p in self.nodepools if p.name == nct.nodepool_name)
            if not np_obj.spec.limits:
                limits.append(None)
                continue
            rem = dict(np_obj.spec.limits)
            for sn in self.state_nodes:
                if sn.labels().get(api_labels.NODEPOOL_LABEL_KEY) == nct.nodepool_name:
                    rem = res.subtract(rem, sn.capacity())
            limits.append({k: enc.scale_capacity(k, v) for k, v in rem.items()})
        limit_resources = sorted({k for lm in limits if lm for k in lm})

        Z = len(problem.zone_values)
        zone_names = vocab.values[zone_key]
        exist_counts = host_total = None
        with TRACER.span("topo.counts", groups=len(groups)) as tsp:
            if self.initial_zone_counts is not None:
                izc = np.zeros((len(groups), Z), dtype=np.int64)
                for gi, g in enumerate(groups):
                    counts = self.initial_zone_counts(g, zone_names)
                    for z, cnt in enumerate(counts):
                        izc[gi, z] = cnt
            elif self.problem_state is not None:
                # per-group counts memoized against Cluster.topo_revision:
                # the scheduled-pod selector scans run only for groups the
                # revision can no longer vouch for
                izc, exist_counts, host_total = \
                    self.problem_state.topology_counts(self, groups,
                                                       zone_names, pods)
                tsp.set(counted=self.problem_state.last[
                    "topo_groups_counted"])
            else:
                # default: count scheduled cluster pods matching each
                # group's topology selectors so a deployment scale-up
                # spreads against its existing replicas exactly like the
                # host path does
                izc, exist_counts, host_total = self.cluster_topology_counts(
                    groups, zone_names, {p.uid for p in pods})

        sn_order = sorted(range(len(self.state_nodes)),
                          key=lambda i: (not self.state_nodes[i].initialized(),
                                         self.state_nodes[i].name()))
        if exist_counts is not None:
            exist_counts = pad_exist_counts(problem, exist_counts)
        vol_group_counts, vol_node_remaining = \
            self._volume_limit_state(groups)
        group_ports = None
        exist_port_block = None
        if any(g.host_ports for g in groups):
            group_ports = [g.host_ports for g in groups]
            if self.state_nodes:
                # indexed by the problem's exist-node order (= state_nodes
                # position, the space _fill_existing's node_caps[n] uses)
                exist_port_block = np.zeros(
                    (len(groups), len(self.state_nodes)), dtype=bool)
                for gi, gp in enumerate(group_ports):
                    if not gp:
                        continue
                    for ni, sn in enumerate(self.state_nodes):
                        exist_port_block[gi, ni] = \
                            sn.host_port_usage().conflicts_triples(gp)
        warm = None
        if self.problem_state is not None:
            warm = self.problem_state.warm_start(
                self, vocab, groups, templates, limits,
                izc, exist_counts, host_total, problem.exist_token)
        use_sharded = False
        if self.pack_shards > 1:
            # warm no longer forces the sequential pack: sharded_pack
            # carries per-shard WarmStarts (warm.shard_seeds) through the
            # same checkpoint machinery, so the sharded state warm-replays
            from ..parallel.mesh import pack_shardable
            use_sharded = pack_shardable(problem, limits, group_ports,
                                         vol_group_counts)
        with TRACER.span("pack", groups=len(groups)) as psp:
            if use_sharded:
                from ..parallel.mesh import sharded_pack
                psp.set(sharded=self.pack_shards)
                pr = sharded_pack(problem, tensors, groups,
                                  self.pack_shards,
                                  initial_zone_counts=izc,
                                  exist_counts=exist_counts,
                                  host_match_total=host_total,
                                  warm=warm)
            else:
                packer = binpack.Packer(problem, tensors, groups, limits,
                                        limit_resources,
                                        initial_zone_counts=izc,
                                        exist_order=sn_order,
                                        exist_counts=exist_counts,
                                        host_match_total=host_total,
                                        vol_group_counts=vol_group_counts,
                                        vol_node_remaining=vol_node_remaining,
                                        group_ports=group_ports,
                                        exist_port_block=exist_port_block,
                                        warm=warm)
                pr = packer.pack()
            if self.problem_state is not None:
                self.problem_state.finish_pack(warm)
                psp.set(warm=self.problem_state.last["warm"],
                        warm_restored=self.problem_state.last[
                            "warm_restored"])
        with TRACER.span("materialize"):
            return self._materialize(pr, problem, groups, templates, catalog,
                                     vocab, zone_key)

    def _volume_limit_state(self, groups):
        """CSI attach-limit inputs for the packer's existing-node pass
        (volumeusage.go:187-220 linearized). Groups reaching the tensor path
        carry only EPHEMERAL volumes (grouping demotes the rest), so each
        pod consumes {driver: count} fresh attach slots on its node.
        Returns (vol_group_counts[g] = {driver: per-pod claims} | None,
        vol_node_remaining[n] = {driver: remaining slots} for limited
        drivers | None). Resolution order mirrors the host oracle: a wire
        pre-resolution rider when present, else the store reachable through
        the cluster view; unresolvable volumes impose no limits, exactly as
        a missing CSINode imposes none (volumeusage.go:187-199)."""
        vol_gis = [gi for gi, g in enumerate(groups)
                   if g.pods and g.pods[0].spec.volumes]
        if not vol_gis or not self.state_nodes:
            return None, None
        store = getattr(self.cluster, "store", None)
        group_counts: List[Optional[dict]] = [None] * len(groups)
        any_counts = False
        for gi in vol_gis:
            probe = groups[gi].pods[0]
            counts = getattr(probe.spec, "_volume_drivers", None)
            if counts is None and store is not None:
                from ..scheduling.volumeusage import get_volumes
                counts = {d: len(keys)
                          for d, keys in get_volumes(store, probe).items()}
            if counts:
                group_counts[gi] = dict(counts)
                any_counts = True
        if not any_counts:
            return None, None
        remaining: List[Optional[dict]] = []
        for sn in self.state_nodes:
            limits = getattr(sn, "volume_limits", None)
            if limits is None and store is not None:
                from ..scheduling.volumeusage import node_volume_limits
                limits = node_volume_limits(store, sn.name())
            limits = {d: lm for d, lm in (limits or {}).items()
                      if lm is not None}
            if not limits:
                remaining.append(None)
                continue
            used = getattr(sn, "volume_used", None)
            if used is None:
                vu = getattr(sn, "volume_usage", None)
                used = ({d: len(s) for d, s in vu().volumes.items()}
                        if vu is not None else {})
            remaining.append({d: max(0, lm - used.get(d, 0))
                              for d, lm in limits.items()})
        if all(r is None for r in remaining):
            return None, None
        return group_counts, remaining

    @staticmethod
    def _cohort_price_order(problem, it_set: np.ndarray, enc_mask: np.ndarray,
                            it_names: np.ndarray) -> np.ndarray:
        """Surviving instance types of a cohort ordered by cheapest admitted
        offering with name tiebreak — the vectorized OrderByPrice
        (types.go:117-134): an offering counts when available and its
        zone/captype value is admitted by the cohort's accumulated
        requirement mask (a [K, W] row of the pack's CohortSet)."""
        t_idx = np.where(it_set)[0]
        if t_idx.size == 0:
            return t_idx

        def admits(key: int, vals: np.ndarray) -> np.ndarray:
            mask = enc_mask[key]                           # [W] uint32
            word = np.where(vals >= 0, vals // 32, 0)
            bit = np.where(vals >= 0, vals % 32, 0).astype(np.uint32)
            has = (mask[word] >> bit) & np.uint32(1)
            return np.where(vals >= 0, has == 1, True)

        off_zone = problem.off_zone[t_idx]
        off_cap = problem.off_captype[t_idx]
        ok = (problem.off_available[t_idx]
              & admits(problem.zone_key, off_zone)
              & admits(problem.captype_key, off_cap))
        price = np.where(ok, problem.off_price[t_idx], np.inf).min(axis=1)
        # lexsort: price primary, name tiebreak (types.go:128-130)
        return t_idx[np.lexsort((it_names[t_idx], price))]

    def _materialize(self, pr: binpack.PackResult, problem, groups, templates,
                     catalog, vocab, zone_key) -> Results:
        # hand out pod objects per group in order
        cursors = [0] * len(groups)

        def take(g: int, n: int) -> List[Pod]:
            out = groups[g].pods[cursors[g]:cursors[g] + n]
            cursors[g] += n
            return out

        new_claims: List[TensorNodeClaim] = []
        it_names = np.array([it.name for it in catalog])
        # cohorts from one solve overwhelmingly share (it_set, zone/captype
        # admission) — memoize the ordering per distinct key
        order_cache: dict = {}
        cs = pr.cohorts  # the packer's columnar CohortSet
        for ci in range(cs.C if cs is not None else 0):
            it_set = cs.it_set[ci]
            enc_mask = cs.enc_mask[ci]
            okey = (it_set.tobytes(),
                    enc_mask[problem.zone_key].tobytes(),
                    enc_mask[problem.captype_key].tobytes())
            ordered = order_cache.get(okey)
            if ordered is None:
                ordered = [catalog[t]
                           for t in self._cohort_price_order(
                               problem, it_set, enc_mask, it_names)]
                order_cache[okey] = ordered
            m = int(cs.m[ci])
            pods_by_group = cs.pods_by_group[ci]
            base_reqs = templates[m].requirements.copy()
            for g in pods_by_group:
                base_reqs.add(*groups[g].requirements.values())
            zi = int(cs.zone[ci])
            if zi >= 0:
                zone_name = vocab.values[zone_key][zi]
                base_reqs.add(Requirement(api_labels.LABEL_TOPOLOGY_ZONE, IN,
                                          [zone_name]))
            # all pods of a group are identical: node requests = per-pod
            # requests scaled by fill (no per-pod re-merge), plus the
            # template's daemonset overhead — the claim's recorded resources
            # must match what the node will actually host
            # (scheduler.go:356-382; the packer already budgeted for it)
            requests: dict = dict(
                _daemon_overhead(templates[m], self.daemonset_pods))
            for g, fill in pods_by_group.items():
                for rname, v in groups[g].requests.items():
                    requests[rname] = requests.get(rname, 0) + v * fill
            for _ in range(int(cs.n[ci])):
                reqs = base_reqs.copy()
                pods: List[Pod] = []
                for g, fill in pods_by_group.items():
                    pods.extend(take(g, fill))
                tnc = TensorNodeClaim(
                    templates[m], reqs, ordered, pods, dict(requests))
                # sibling claims of one cohort differ only in their pods —
                # the sidecar result codec interns the claim shape by this
                # id so n identical nodes encode once (codec.py
                # encode_solve_response_rows)
                tnc.cohort_id = ci
                new_claims.append(tnc)
        existing: List[TensorExistingNode] = []
        for n, fills in pr.existing.items():
            pods = []
            for g, fill in fills:
                pods.extend(take(g, fill))
            existing.append(TensorExistingNode(self.state_nodes[n], pods))
        errors = dict(pr.errors)
        if errors:
            self._explain_errors(errors, groups, templates)
        return Results(new_nodeclaims=new_claims, existing_nodes=existing,
                       pod_errors=errors,
                       limit_constrained=pr.limit_constrained)


class _FallbackError(Exception):
    pass


def pad_exist_counts(problem, exist_counts: np.ndarray) -> np.ndarray:
    """Align [G, N] matching-pod counts with the packer's (pow2-padded)
    existing-node axis; padded rows are unpackable anyway (zero capacity)."""
    Np = (problem.exist_avail.shape[0]
          if problem.exist_avail is not None else 0)
    if exist_counts.shape[1] < max(Np, 1):
        exist_counts = np.pad(
            exist_counts, ((0, 0), (0, max(Np, 1) - exist_counts.shape[1])))
    return exist_counts


def _tol_exist_matrix(groups, taint_lists, total_cols: int) -> np.ndarray:
    """[G, total_cols] group x existing-node toleration matrix — THE one
    construction both the cold and delta encode paths share (a divergence
    would break the delta path's bit-identical contract). True = the
    group's probe pod tolerates node i's taints (tolerates() returns the
    error list, so untainted nodes default True); columns past
    len(taint_lists) are pow2 padding and stay False (never packable)."""
    G = len(groups)
    out = np.zeros((G, total_cols), dtype=bool)
    out[:, :len(taint_lists)] = True
    for i, nt in enumerate(taint_lists):
        if not nt:
            continue
        for gi, g in enumerate(groups):
            out[gi, i] = not scheduling_taints.tolerates(nt, g.pods[0])
    return out


def _node_remaining_daemons(sn, daemonset_pods) -> dict:
    """Remaining daemonset overhead a node must still absorb
    (existingnode.go:44-54)."""
    from ..scheduling.requirements import pod_requirements as preqs
    daemons = []
    node_taints = sn.taints()
    node_reqs = label_requirements(sn.labels())
    for p in daemonset_pods:
        if scheduling_taints.tolerates(node_taints, p):
            continue
        if node_reqs.compatible(preqs(p)):
            continue
        daemons.append(p)
    total = res.merge(*(p.requests() for p in daemons)) if daemons else {}
    remaining = res.subtract(total, sn.daemonset_requests())
    return {k: max(v, 0) for k, v in remaining.items()}
