"""Provisioning scheduler: greedy first-fit-decreasing with relaxation.

Host-side oracle with the semantics of
/root/reference/pkg/controllers/provisioning/scheduling/{scheduler,nodeclaim,
existingnode,nodeclaimtemplate,queue}.go. The TPU accelerated path
(karpenter_tpu.ops.binpack) reproduces this solver's decisions on dense
tensors; Scheduler is the entry point either way — it picks the accelerated
kernel when the batch is expressible there and falls back to this loop
otherwise, so behavior is always defined by these semantics.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim as APINodeClaim, NodeClaimSpec
from ..api.nodepool import NODEPOOL_HASH_VERSION, NodePool
from ..api.objects import ObjectMeta, OwnerReference, Pod, Taint
from ..cloudprovider.types import InstanceType, satisfies_min_values, truncate
from ..scheduling import taints as scheduling_taints
from ..scheduling.hostports import HostPortUsage, get_host_ports
from ..scheduling.requirement import IN, Requirement
from ..scheduling.requirements import (ALLOW_UNDEFINED_WELL_KNOWN, Requirements,
                                       has_preferred_node_affinity, label_requirements,
                                       node_selector_requirements, pod_requirements,
                                       strict_pod_requirements)
from ..utils import resources as res
from .preferences import Preferences
from .topology import Topology

MAX_INSTANCE_TYPES = 60  # nodeclaimtemplate.go:35

_hostname_seq = itertools.count(1)
# ONE claim-name counter for every solver path (host oracle, tensor,
# sidecar decode): independent counters minted colliding names — two paths
# both producing "default-00342" in one process is a store ConflictError
claim_name_seq = itertools.count(1)


class NodeClaimTemplate:
    """NodePool -> launchable template with precomputed requirements
    (nodeclaimtemplate.go:42-68)."""

    def __init__(self, nodepool: NodePool):
        self.nodepool_name = nodepool.name
        self.nodepool_uid = nodepool.metadata.uid
        spec = nodepool.spec.template.spec
        self.taints: List[Taint] = list(spec.taints)
        self.startup_taints: List[Taint] = list(spec.startup_taints)
        self.expire_after = spec.expire_after
        self.termination_grace_period = spec.termination_grace_period
        self.node_class_ref = spec.node_class_ref
        self.labels = dict(nodepool.spec.template.metadata_labels)
        self.labels[api_labels.NODEPOOL_LABEL_KEY] = nodepool.name
        self.annotations = dict(nodepool.spec.template.metadata_annotations)
        self.annotations[api_labels.NODEPOOL_HASH_ANNOTATION_KEY] = nodepool.static_hash()
        self.annotations[api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = NODEPOOL_HASH_VERSION
        self.requirements = Requirements()
        self.requirements.add(*node_selector_requirements(spec.requirements).values())
        self.requirements.add(*label_requirements(self.labels).values())
        self.instance_type_options: List[InstanceType] = []


class InFlightNodeClaim:
    """A node being packed (scheduling/nodeclaim.go:35-122). Keeps the full set
    of instance types that could satisfy the accumulated pods."""

    def __init__(self, template: NodeClaimTemplate, topology: Topology,
                 daemon_resources: dict, instance_types: List[InstanceType]):
        self.template = template
        self.hostname = f"hostname-placeholder-{next(_hostname_seq):05d}"
        topology.register(api_labels.LABEL_HOSTNAME, self.hostname)
        self.requirements = Requirements(template.requirements.values())
        self.requirements.add(Requirement(api_labels.LABEL_HOSTNAME, IN, [self.hostname]))
        self.instance_type_options = list(instance_types)
        self.daemon_resources = dict(daemon_resources)
        self.requests = dict(daemon_resources)
        self.topology = topology
        self.host_port_usage = HostPortUsage()
        self.pods: List[Pod] = []
        # (sig, ok_ids): instance types passing the requirements-only checks
        # for the last-added pod signature. Claim requirements only TIGHTEN
        # and adding an identical pod-requirement set tightens nothing, so
        # for successive same-signature adds (with no topology-derived
        # requirements) compat/offering verdicts are invariant — only the
        # fits check moves as requests accumulate. This collapses the
        # reference's per-pod re-filter (nodeclaim.go:108-117) to a
        # fits-only pass on the deployment-stamped hot path.
        self._compat_cache: Optional[tuple] = None
        # element-wise min allocatable across surviving instance types;
        # invalidated whenever the survivor set changes
        self._min_alloc: Optional[dict] = None

    def _compute_min_alloc(self) -> dict:
        its = self.instance_type_options
        keys: set = set()
        for it in its:
            keys.update(it.allocatable())
        return {k: min(it.allocatable().get(k, 0) for it in its)
                for k in keys}

    def add(self, pod: Pod, pod_requests: dict,
            pod_reqs: Optional[Requirements] = None,
            sig=None) -> Optional[str]:
        """Returns an error string, or None on success (nodeclaim.go:67-122)."""
        errs = scheduling_taints.tolerates(self.template.taints, pod)
        if errs:
            return errs[0]
        host_ports = get_host_ports(pod)
        conflicts = self.host_port_usage.conflicts(pod, host_ports)
        if conflicts:
            return f"checking host port usage, {conflicts[0]}"
        if pod_reqs is None:
            pod_reqs = pod_requirements(pod)
        # compat is non-mutating: check BEFORE paying for the copy — a pod
        # scans many full claims per solve, and most attempts fail here
        errs = self.requirements.compatible(pod_reqs, ALLOW_UNDEFINED_WELL_KNOWN)
        if errs:
            return f"incompatible requirements, {errs[0]}"
        nodeclaim_requirements = self.requirements.copy()
        nodeclaim_requirements.add(*pod_reqs.values())

        strict_reqs = pod_reqs
        if has_preferred_node_affinity(pod):
            strict_reqs = strict_pod_requirements(pod)
        topo_reqs, err = self.topology.add_requirements(
            strict_reqs, nodeclaim_requirements, pod, ALLOW_UNDEFINED_WELL_KNOWN)
        if err is not None:
            return err
        errs = nodeclaim_requirements.compatible(topo_reqs, ALLOW_UNDEFINED_WELL_KNOWN)
        if errs:
            return errs[0]
        nodeclaim_requirements.add(*topo_reqs.values())

        requests = res.merge(self.requests, pod_requests)
        remaining = None
        cacheable = sig is not None and not self.topology.last_add_tightened
        if cacheable and self._compat_cache is not None \
                and self._compat_cache[0] == sig:
            ok = self._compat_cache[1]
            # requests only grow: if they fit the element-wise MINIMUM
            # allocatable across survivors, no type can drop out — skip the
            # per-type scan (the hot loop at 50k identical pods). Only
            # meaningful when every survivor is signature-compatible; the
            # min is computed lazily there so the disabled regime pays zero
            fast = None
            if len(ok) == len(self.instance_type_options):
                if self._min_alloc is None:
                    self._min_alloc = self._compute_min_alloc()
                if res.fits(requests, self._min_alloc):
                    fast = self.instance_type_options
            if fast is None:
                fast = [it for it in self.instance_type_options
                        if id(it) in ok
                        and res.fits(requests, it.allocatable())]
            if fast and nodeclaim_requirements.has_min_values():
                _, err = satisfies_min_values(fast, nodeclaim_requirements)
                if err is not None:
                    fast = []
            if fast:
                remaining = fast
            # empty fast result: fall through to the full filter for the
            # exact failure-attribution message
        if remaining is None:
            ok_ids: Optional[set] = set() if cacheable else None
            remaining, reason = filter_instance_types(
                self.instance_type_options, nodeclaim_requirements, requests,
                ok_ids=ok_ids)
            if not remaining:
                return (f"no instance type satisfied resources "
                        f"{res.merge(self.daemon_resources, pod_requests)} and requirements ({reason})")
            if cacheable:
                self._compat_cache = (sig, ok_ids)

        if not cacheable:
            # this add may have tightened requirements in ways the cached
            # verdicts don't reflect (different signature / topology-derived
            # requirements): drop the cache rather than serve stale compat
            self._compat_cache = None

        self.pods.append(pod)
        if len(remaining) != len(self.instance_type_options):
            # filters only REMOVE: equal length means identical contents,
            # so the cached element-wise min stays valid
            self.instance_type_options = remaining
            self._min_alloc = None
        self.requests = requests
        self.requirements = nodeclaim_requirements
        self.topology.record(pod, nodeclaim_requirements, ALLOW_UNDEFINED_WELL_KNOWN)
        self.host_port_usage.add(pod, host_ports)
        return None

    def destroy(self) -> None:
        self.topology.unregister(api_labels.LABEL_HOSTNAME, self.hostname)

    def finalize(self) -> None:
        """Strip the placeholder hostname before launch (nodeclaim.go:130-134)."""
        self.requirements.delete(api_labels.LABEL_HOSTNAME)

    def remove_instance_types_by_price_and_min_values(self, reqs: Requirements,
                                                      max_price: float):
        """Consolidation price filter (nodeclaim.go:136-145)."""
        self.instance_type_options = [
            it for it in self.instance_type_options
            if it.offerings.available().worst_launch_price(reqs) < max_price]
        self._min_alloc = None
        _, err = satisfies_min_values(self.instance_type_options, reqs)
        if err is not None:
            return None, err
        return self, None

    def to_nodeclaim(self) -> APINodeClaim:
        """nodeclaimtemplate.go:70-95 — truncate instance types by price into an
        In requirement, emit the API NodeClaim."""
        t = self.template
        reqs = Requirements(self.requirements.values())
        instance_types = self.instance_type_options[:MAX_INSTANCE_TYPES]
        mv = reqs.get(api_labels.LABEL_INSTANCE_TYPE).min_values
        reqs.add(Requirement(api_labels.LABEL_INSTANCE_TYPE, IN,
                             [it.name for it in instance_types], min_values=mv))
        nc = APINodeClaim(
            metadata=ObjectMeta(
                name=f"{t.nodepool_name}-{next(claim_name_seq):05d}",
                labels=dict(t.labels), annotations=dict(t.annotations),
                owner_refs=[OwnerReference(kind="NodePool", name=t.nodepool_name,
                                           uid=t.nodepool_uid, block_owner_deletion=True)]),
            spec=NodeClaimSpec(
                requirements=[_req_to_selector(r) for r in reqs.values()],
                resources_requests=dict(self.requests),
                taints=list(t.taints), startup_taints=list(t.startup_taints),
                node_class_ref=t.node_class_ref, expire_after=t.expire_after,
                termination_grace_period=t.termination_grace_period))
        return nc


@dataclass
class _SelectorReq:
    key: str
    operator: str
    values: tuple
    min_values: Optional[int] = None


def _req_to_selector(r: Requirement) -> _SelectorReq:
    op = r.operator()
    if r.greater_than is not None:
        return _SelectorReq(r.key, "Gt", (str(r.greater_than),), r.min_values)
    if r.less_than is not None:
        return _SelectorReq(r.key, "Lt", (str(r.less_than),), r.min_values)
    return _SelectorReq(r.key, op, tuple(r.values_list()), r.min_values)


class ExistingNode:
    """A live/in-flight node being packed (existingnode.go:31-128)."""

    def __init__(self, state_node, topology: Topology, taints: List[Taint],
                 daemon_resources: dict, store=None):
        self.state_node = state_node
        self.cached_available = state_node.available()
        self.cached_taints = taints
        self.topology = topology
        remaining_daemons = res.subtract(daemon_resources, state_node.daemonset_requests())
        self.requests = {k: max(v, 0) for k, v in remaining_daemons.items()}
        self.requirements = label_requirements(state_node.labels())
        self.requirements.add(Requirement(api_labels.LABEL_HOSTNAME, IN,
                                          [state_node.hostname()]))
        topology.register(api_labels.LABEL_HOSTNAME, state_node.hostname())
        self.pods: List[Pod] = []
        self._host_port_usage = state_node.host_port_usage().copy()
        self._store = store
        vu = getattr(state_node, "volume_usage", None)
        self._volume_usage = vu().copy() if vu is not None else None

    @property
    def name(self):
        return self.state_node.name()

    def initialized(self) -> bool:
        return self.state_node.initialized()

    def add(self, pod: Pod, pod_requests: dict,
            pod_reqs: Optional[Requirements] = None) -> Optional[str]:
        errs = scheduling_taints.tolerates(self.cached_taints, pod)
        if errs:
            return errs[0]
        host_ports = get_host_ports(pod)
        conflicts = self._host_port_usage.conflicts(pod, host_ports)
        if conflicts:
            return f"checking host port usage, {conflicts[0]}"
        pod_vols = None
        if self._store is not None and self._volume_usage is not None \
                and pod.spec.volumes:
            from ..scheduling.volumeusage import (get_volumes,
                                                  node_volume_limits)
            pod_vols = get_volumes(self._store, pod)
            err = self._volume_usage.exceeds_limits(
                pod_vols, node_volume_limits(self._store,
                                             self.state_node.name()))
            if err is not None:
                return f"checking volume usage, {err}"
        requests = res.merge(self.requests, pod_requests)
        if not res.fits(requests, self.cached_available):
            return "exceeds node resources"
        if pod_reqs is None:
            pod_reqs = pod_requirements(pod)
        errs = self.requirements.compatible(pod_reqs)
        if errs:
            return errs[0]
        node_requirements = self.requirements.copy()
        node_requirements.add(*pod_reqs.values())
        strict_reqs = pod_reqs
        if has_preferred_node_affinity(pod):
            strict_reqs = strict_pod_requirements(pod)
        topo_reqs, err = self.topology.add_requirements(strict_reqs, node_requirements, pod)
        if err is not None:
            return err
        errs = node_requirements.compatible(topo_reqs)
        if errs:
            return errs[0]
        node_requirements.add(*topo_reqs.values())

        self.pods.append(pod)
        self.requests = requests
        self.requirements = node_requirements
        self.topology.record(pod, node_requirements)
        self._host_port_usage.add(pod, host_ports)
        if pod_vols and self._volume_usage is not None:
            self._volume_usage.add(pod_vols)
        return None


def filter_instance_types(instance_types: List[InstanceType], requirements: Requirements,
                          requests: dict, ok_ids: Optional[set] = None):
    """Per-IT compat x fits x offering filter with failure attribution
    (nodeclaim.go:248-293 + FailureReason :182-245). When `ok_ids` is
    given, it is filled with id(it) of every type passing the
    requirements-only checks (compat AND offering, regardless of fits) —
    the claim-side cache that lets successive same-signature adds skip the
    requirement re-evaluation (only fits changes as requests accumulate)."""
    remaining = []
    any_compat = any_fits = any_offer = False
    compat_and_fits = compat_and_offer = fits_and_offer = False
    for it in instance_types:
        compat = not it.requirements.intersects(requirements)
        fits_ = res.fits(requests, it.allocatable())
        offer = it.offerings.available().has_compatible(requirements)
        any_compat |= compat
        any_fits |= fits_
        any_offer |= offer
        compat_and_fits |= compat and fits_ and not offer
        compat_and_offer |= compat and offer and not fits_
        fits_and_offer |= fits_ and offer and not compat
        if compat and offer and ok_ids is not None:
            ok_ids.add(id(it))
        if compat and fits_ and offer:
            remaining.append(it)
    if requirements.has_min_values() and remaining:
        _, err = satisfies_min_values(remaining, requirements)
        if err is not None:
            return [], err
    if remaining:
        return remaining, ""
    if not any_compat and not any_fits and not any_offer:
        reason = "no instance type met the scheduling requirements or had enough resources or had a required offering"
    elif not any_compat and not any_fits:
        reason = "no instance type met the scheduling requirements or had enough resources"
    elif not any_compat and not any_offer:
        reason = "no instance type met the scheduling requirements or had a required offering"
    elif not any_fits and not any_offer:
        reason = "no instance type had enough resources or had a required offering"
    elif not any_compat:
        reason = "no instance type met all requirements"
    elif not any_fits:
        reason = "no instance type has enough resources"
    elif not any_offer:
        reason = "no instance type has the required offering"
    elif compat_and_fits:
        reason = "no instance type which met the scheduling requirements and had enough resources, had a required offering"
    elif fits_and_offer:
        reason = "no instance type which had enough resources and the required offering met the scheduling requirements"
    elif compat_and_offer:
        reason = "no instance type which met the scheduling requirements and the required offering had the required resources"
    else:
        reason = "no instance type met the requirements/resources/offering tuple"
    return [], reason


class Queue:
    """Pod retry queue with progress detection (queue.go:31-74)."""

    def __init__(self, pods: List[Pod], pod_requests: Dict[str, dict]):
        from collections import deque
        self.pods = deque(sorted(pods, key=lambda p: (
            -pod_requests[p.uid].get(res.CPU, 0),
            -pod_requests[p.uid].get(res.MEMORY, 0),
            p.metadata.creation_timestamp, p.uid)))
        self.last_len: Dict[str, int] = {}

    def pop(self):
        if not self.pods:
            return None
        p = self.pods[0]
        if self.last_len.get(p.uid) == len(self.pods):
            return None
        self.pods.popleft()
        return p

    def push(self, pod: Pod, relaxed: bool) -> None:
        self.pods.append(pod)
        if relaxed:
            self.last_len = {}
        else:
            self.last_len[pod.uid] = len(self.pods)


@dataclass
class Results:
    """scheduler.go:108-112."""
    new_nodeclaims: List[InFlightNodeClaim] = field(default_factory=list)
    existing_nodes: List[ExistingNode] = field(default_factory=list)
    pod_errors: Dict[str, str] = field(default_factory=dict)  # pod uid -> error
    # tensor path only: a nodepool limit excluded capacity during the pack,
    # so pod_errors are order-dependent rather than oracle-final
    # (PackResult.limit_constrained; drives the host re-solve guard)
    limit_constrained: bool = False

    def all_pods_scheduled(self) -> bool:
        return not self.pod_errors

    def truncate_instance_types(self, max_instance_types: int = MAX_INSTANCE_TYPES) -> "Results":
        """scheduler.go:187-205."""
        valid = []
        for nc in self.new_nodeclaims:
            truncated, err = truncate(nc.instance_type_options, nc.requirements,
                                      max_instance_types)
            if err is not None:
                for pod in nc.pods:
                    self.pod_errors[pod.uid] = (
                        f"pod didn't schedule because NodePool {nc.template.nodepool_name!r} "
                        f"couldn't meet minValues requirements, {err}")
            else:
                nc.instance_type_options = truncated
                valid.append(nc)
        self.new_nodeclaims = valid
        return self

    def node_count(self) -> int:
        return len(self.new_nodeclaims)


class Scheduler:
    """scheduler.go:47-105,207-315. Pure host loop; see ops/binpack.py for the
    accelerated path the provisioner prefers on large batches."""

    def __init__(self, nodepools: List[NodePool], instance_types: Dict[str, List[InstanceType]],
                 topology: Topology, state_nodes=(), daemonset_pods: List[Pod] = ()):
        tolerate_pns = any(
            t.effect == "PreferNoSchedule"
            for np in nodepools for t in np.spec.template.spec.taints)
        self.preferences = Preferences(tolerate_prefer_no_schedule=tolerate_pns)
        self.topology = topology
        self.templates: List[NodeClaimTemplate] = []
        for np in nodepools:
            nct = NodeClaimTemplate(np)
            nct.instance_type_options, _ = filter_instance_types(
                instance_types.get(np.name, []), nct.requirements, {})
            if nct.instance_type_options:
                self.templates.append(nct)
        self.remaining_resources: Dict[str, dict] = {
            np.name: dict(np.spec.limits) for np in nodepools if np.spec.limits}
        self.daemon_overhead: Dict[int, dict] = {}
        self.daemonset_pods = list(daemonset_pods)
        for i, nct in enumerate(self.templates):
            self.daemon_overhead[i] = _daemon_overhead(nct, self.daemonset_pods)
        self.new_nodeclaims: List[InFlightNodeClaim] = []
        self.existing_nodes: List[ExistingNode] = []
        self.cached_pod_requests: Dict[str, dict] = {}
        # pod_requirements(pod) is pure until relax() mutates the pod; memo
        # per uid saves rebuilding it on every claim attempt of the scan loop
        self._cached_pod_reqs: Dict[str, Requirements] = {}
        # content signatures backing the claims' compat caches; invalidated
        # together with _cached_pod_reqs when relax() mutates a pod
        self._pod_sigs: Dict[str, tuple] = {}
        self._calculate_existing_nodes(state_nodes)

    def _calculate_existing_nodes(self, state_nodes) -> None:
        """scheduler.go:317-353."""
        store = getattr(self.topology.cluster, "store", None)
        for node in state_nodes:
            node_taints = node.taints()
            daemons = []
            for p in self.daemonset_pods:
                if scheduling_taints.tolerates(node_taints, p):
                    continue
                if label_requirements(node.labels()).compatible(pod_requirements(p)):
                    continue
                daemons.append(p)
            daemon_requests = res.merge(*(pp.requests() for pp in daemons)) if daemons else {}
            self.existing_nodes.append(
                ExistingNode(node, self.topology, node_taints, daemon_requests,
                             store=store))
            pool = node.labels().get(api_labels.NODEPOOL_LABEL_KEY)
            if pool in self.remaining_resources:
                self.remaining_resources[pool] = res.subtract(
                    self.remaining_resources[pool], node.capacity())
        self.existing_nodes.sort(key=lambda n: (not n.initialized(), n.name))

    def solve(self, pods: List[Pod]) -> Results:
        """scheduler.go:207-265 — loop while the queue makes progress; on
        failure relax one preference rung and re-enqueue."""
        from ..utils.gcpause import no_gc
        with no_gc():
            return self._solve(pods)

    def _solve(self, pods: List[Pod]) -> Results:
        errors: Dict[str, str] = {}
        for p in pods:
            self.cached_pod_requests[p.uid] = p.requests()
        q = Queue(pods, self.cached_pod_requests)
        # establish the fewest-pods-first invariant once; _add maintains it
        # incrementally afterwards (stable-sort-equivalent repositioning)
        self.new_nodeclaims.sort(key=lambda n: len(n.pods))
        while True:
            pod = q.pop()
            if pod is None:
                break
            err = self._add(pod)
            if err is None:
                errors.pop(pod.uid, None)
                continue
            errors[pod.uid] = err
            relaxed = self.preferences.relax(pod)
            q.push(pod, relaxed)
            if relaxed:
                self._cached_pod_reqs.pop(pod.uid, None)
                self._pod_sigs.pop(pod.uid, None)
                self.topology.update(pod)
        for nc in self.new_nodeclaims:
            nc.finalize()
        return Results(new_nodeclaims=self.new_nodeclaims,
                       existing_nodes=self.existing_nodes, pod_errors=errors)

    def _pod_sig(self, pod: Pod, pod_reqs: Requirements,
                 pod_requests: dict):
        """Content signature over everything the claim compat cache depends
        on: requirement set, request vector, tolerations. Pods sharing a
        signature get identical taints/compat/offering verdicts from a
        claim in a given state."""
        sig = self._pod_sigs.get(pod.uid)
        if sig is None:
            from .grouping import _req_signature
            sig = (_req_signature(pod_reqs),
                   tuple(sorted(pod_requests.items())),
                   tuple(pod.spec.tolerations))
            self._pod_sigs[pod.uid] = sig
        return sig

    def _reposition(self, idx: int) -> None:
        """Restore sorted order after claims[idx] grew by one pod — the
        stable-sort-equivalent move: past every claim with a smaller count,
        before existing claims of the new count (they were later in the
        pre-sort order)."""
        claims = self.new_nodeclaims
        L = len(claims[idx].pods)
        j = idx
        while j + 1 < len(claims) and len(claims[j + 1].pods) < L:
            j += 1
        if j != idx:
            claims.insert(j, claims.pop(idx))

    def _insert_sorted(self, nc: "InFlightNodeClaim") -> None:
        """Append-equivalent of the stable sort: a fresh claim lands after
        existing claims with <= its count and before any larger."""
        claims = self.new_nodeclaims
        L = len(nc.pods)
        j = len(claims)
        while j > 0 and len(claims[j - 1].pods) > L:
            j -= 1
        claims.insert(j, nc)

    def _add(self, pod: Pod) -> Optional[str]:
        """scheduler.go:267-315: existing nodes -> in-flight claims (fewest pods
        first) -> new claim from templates in weight order."""
        pod_requests = self.cached_pod_requests[pod.uid]
        pod_reqs = self._cached_pod_reqs.get(pod.uid)
        if pod_reqs is None:
            pod_reqs = pod_requirements(pod)
            self._cached_pod_reqs[pod.uid] = pod_reqs
        sig = self._pod_sig(pod, pod_reqs, pod_requests)
        for node in self.existing_nodes:
            if node.add(pod, pod_requests, pod_reqs) is None:
                return None
        for i, nc in enumerate(self.new_nodeclaims):
            if nc.add(pod, pod_requests, pod_reqs, sig=sig) is None:
                self._reposition(i)
                return None
        errs = []
        for i, nct in enumerate(self.templates):
            instance_types = nct.instance_type_options
            remaining = self.remaining_resources.get(nct.nodepool_name)
            if remaining is not None:
                instance_types = [it for it in instance_types
                                  if not res.exceeds(it.capacity, remaining)]
                if not instance_types:
                    errs.append(f'all available instance types exceed limits for nodepool: "{nct.nodepool_name}"')
                    continue
            nc = InFlightNodeClaim(nct, self.topology, self.daemon_overhead[i], instance_types)
            err = nc.add(pod, pod_requests, pod_reqs, sig=sig)
            if err is not None:
                nc.destroy()
                errs.append(f'incompatible with nodepool "{nct.nodepool_name}", {err}')
                continue
            self._insert_sorted(nc)
            if remaining is not None:
                self.remaining_resources[nct.nodepool_name] = _subtract_max(
                    remaining, nc.instance_type_options)
            return None
        return "; ".join(errs) if errs else "no nodepool matched pod"


def _daemon_overhead(nct: NodeClaimTemplate, daemonset_pods: List[Pod]) -> dict:
    """scheduler.go:356-382."""
    compatible = [p for p in daemonset_pods if _daemon_pod_compatible(nct, p)]
    return res.merge(*(p.requests() for p in compatible)) if compatible else {}


def _daemon_pod_compatible(nct: NodeClaimTemplate, pod: Pod) -> bool:
    import copy
    prefs = Preferences()
    pod = copy.deepcopy(pod)
    prefs._tolerate_prefer_no_schedule_taints(pod)
    if scheduling_taints.tolerates(nct.taints, pod):
        return False
    while True:
        if nct.requirements.is_compatible(strict_pod_requirements(pod),
                                          ALLOW_UNDEFINED_WELL_KNOWN):
            return True
        if prefs._remove_required_node_affinity_term(pod) is None:
            return False


def _subtract_max(remaining: dict, instance_types: List[InstanceType]) -> dict:
    """Pessimistic limit tracking (scheduler.go:388-405)."""
    if not instance_types:
        return remaining
    it_max = res.max_resources([it.capacity for it in instance_types])
    return {k: v - it_max.get(k, 0) for k, v in remaining.items()}
