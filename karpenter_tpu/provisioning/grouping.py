"""Pod equivalence-class extraction for the tensor solver.

The reference scheduler loops pod-by-pod (scheduler.go:218-254), refiltering
instance types per pod — O(pods x ITs). Pods stamped from the same deployment
are interchangeable: identical requests, requirements, tolerations, labels and
topology constraints. Grouping collapses the loop to O(groups), which is the
main algorithmic win of the TPU design (SURVEY.md §7 layer 3).

A batch is *tensor-eligible* when every group's topology constraints fall in
the kernel-supported forms below and no constraint selects pods of another
group (cross-group count coupling). Otherwise the scheduler transparently
falls back to the host solver, whose semantics are always authoritative.

Supported per-group topology forms:
- zonal topology spread        (topologygroup.go nextDomainTopologySpread,
                                incl. minDomains floor-to-zero semantics)
- hostname topology spread
- zonal pod affinity           (all pods collapse to one zone)
- hostname pod affinity        (all pods onto one node, overflow unschedulable;
                                self-selecting only — non-self has no bootstrap
                                and needs live co-location state)
- zonal pod anti-affinity      (late committal: one pod per batch schedules)
- hostname pod anti-affinity   (one pod per node)

Each form may be self-selecting (the constraint's selector matches the pod's
own labels — the deployment case) or non-self-selecting (counts come only
from already-scheduled cluster pods; the packer treats the domain counts as
static since placing batch pods never changes them). A group may carry up to
TWO constraints when they layer cleanly: one zone-level constraint (zonal
spread or zonal affinity) plus one hostname-level constraint (hostname
spread or hostname anti-affinity) — the common real-world combo of "spread
across zones AND at most one per node". Anything else (zonal anti-affinity
or hostname affinity combined with another constraint, explicit affinity
namespaces, non-zone/hostname topology keys) demotes to the host path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import labels as api_labels
from ..api.objects import DO_NOT_SCHEDULE, Pod
from ..scheduling.requirements import (Requirements, has_preferred_node_affinity,
                                       pod_requirements)
from ..utils import resources as res


def _init_sig(entry):
    """Canonical signature for an init-container entry: (sorted items,
    sidecar flag) — both plain dicts and (requests, always) tuples."""
    req, always = res.init_entry(entry)
    return tuple(sorted(req.items())), always

# topology kinds
TOPO_NONE = "none"
SPREAD_ZONE = "spread-zone"
SPREAD_HOST = "spread-host"
AFFINITY_ZONE = "affinity-zone"
AFFINITY_HOST = "affinity-host"
ANTI_ZONE = "anti-zone"
ANTI_HOST = "anti-host"


ZONE_KINDS = (SPREAD_ZONE, AFFINITY_ZONE, ANTI_ZONE)
HOST_KINDS = (SPREAD_HOST, AFFINITY_HOST, ANTI_HOST)


@dataclass
class TopoSpec:
    kind: str
    max_skew: int = 1
    schedule_anyway: bool = False  # relaxable on failure
    min_domains: Optional[int] = None  # spread only (topologygroup.go:240-247)
    self_select: bool = True   # selector matches the group's own labels
    selector: object = None    # LabelSelector for cluster-pod counting


@dataclass
class PodGroup:
    pods: List[Pod]
    requirements: Requirements        # NewPodRequirements view (preferred folded in)
    requests: dict                    # milliunit ResourceList (per pod)
    tolerations: tuple
    labels: dict
    topo: List[TopoSpec] = field(default_factory=list)
    has_relaxable: bool = False       # preferred affinities / ScheduleAnyway present
    # (ip, port, protocol) triples shared by every pod of the group
    # (identical specs): within the group any two pods conflict on the same
    # node, so the packer caps host-port groups at one pod per node and
    # excludes cross-group/existing-node conflicts
    # (hostportusage.go:34-90 semantics, tensorized)
    host_ports: tuple = ()

    @property
    def count(self) -> int:
        return len(self.pods)


def _req_signature(reqs: Requirements):
    return tuple(sorted(
        (k, reqs.get(k).complement, frozenset(reqs.get(k).values),
         reqs.get(k).greater_than, reqs.get(k).less_than, reqs.get(k).min_values)
        for k in reqs))


def group_signature(g: PodGroup) -> tuple:
    """Content-stable identity of a tensor group ACROSS solves — unlike
    partition_pods' per-call signature (whose tokens are call-local ints),
    this hashes actual content, so the persistent ProblemState can match
    "the same deployment arrived again" between reconcile passes. Two
    groups with equal signatures encode to identical tensor rows and make
    identical packer decisions at equal counts; everything the packer or
    the topology counter reads off a group rides in here (requirements,
    requests, tolerations, labels, topo specs incl. selectors, ports, the
    probe's namespace + raw affinity/selector shape for the spread node
    filter)."""
    probe = g.pods[0]
    return (
        _req_signature(g.requirements),
        tuple(sorted(g.requests.items())),
        tuple(g.tolerations),
        tuple(sorted(g.labels.items())),
        tuple((s.kind, s.max_skew, s.schedule_anyway, s.min_domains,
               s.self_select, s.selector) for s in g.topo),
        tuple(g.host_ports),
        g.has_relaxable,
        probe.namespace,
        tuple(sorted(probe.spec.node_selector.items())),
        _affinity_key(probe),
        () if not probe.spec.volumes else tuple(probe.spec.volumes),
    )


def _port_triples(pod: Pod) -> tuple:
    """Canonical (ip, port, protocol) triples (hostportusage.go entry shape;
    an unset hostIP binds the wildcard)."""
    from ..scheduling.hostports import WILDCARD
    return tuple((hp.host_ip or WILDCARD, hp.port, hp.protocol)
                 for hp in pod.spec.host_ports)


def _demotion_reason(pod: Pod, psig, specs) -> str:
    """The ONE place tensor-ineligibility is decided for a bucket (both the
    prebucket fast path and the per-pod loop call it — a rule added to only
    one copy would silently split their verdicts). Ordered by precedence."""
    if psig is None:
        return "host ports require per-pod conflict tracking"
    if not all(ref.ephemeral for ref in pod.spec.volumes):
        # ephemeral volumes tensorize exactly: each pod brings its own
        # per-pod claim, so a group's CSI attach consumption is a per-node
        # linear cap (volumeusage.go:187-220). Shared PVCs / pre-bound PVs
        # keep set-dedup + PV-affinity semantics only the host models.
        return ("persistent volume claims shared across pods "
                "require host-side limit tracking")
    if specs is None:
        return "unsupported topology constraint shape"
    if psig and any(sp.kind == AFFINITY_HOST for sp in specs):
        # co-location demanded, >1/node forbidden: host-path only
        return ("host ports with hostname pod-affinity need "
                "per-pod host tracking")
    if any(sp.kind in ZONE_KINDS for sp in specs) \
            and has_preferred_node_affinity(pod):
        # kube keeps preferences OUT of spread-domain arithmetic
        # (topology_test.go:1299-1322), but pod_requirements folds the
        # heaviest preferred term — on ANY key, and any folded term can
        # shrink the feasible zone set through pool interactions — into
        # the group's requirement view. Zonal topology + any preference
        # therefore rides the host relaxation ladder, whose strict
        # requirements get this exactly right.
        return ("node-affinity preferences with zonal topology need "
                "the host relaxation ladder")
    return ""


def _selector_is_self(selector, labels: dict) -> bool:
    return selector is not None and selector.matches(labels)


def _term_namespaces_ok(term, pod: Pod) -> bool:
    """Explicit cross-namespace affinity terms need host-side namespace-aware
    counting (topology.go:341)."""
    return not term.namespaces or set(term.namespaces) == {pod.namespace}


def _classify_topology(pod: Pod) -> "Tuple[Optional[List[TopoSpec]], bool]":
    """Returns (specs, relaxable) or (None, _) when unsupported by the kernel."""
    specs: List[TopoSpec] = []
    relaxable = False
    for tsc in pod.spec.topology_spread_constraints:
        anyway = tsc.when_unsatisfiable != DO_NOT_SCHEDULE
        relaxable |= anyway
        self_sel = _selector_is_self(tsc.label_selector, pod.labels)
        if tsc.topology_key == api_labels.LABEL_TOPOLOGY_ZONE:
            specs.append(TopoSpec(SPREAD_ZONE, tsc.max_skew, anyway,
                                  min_domains=tsc.min_domains,
                                  self_select=self_sel,
                                  selector=tsc.label_selector))
        elif tsc.topology_key == api_labels.LABEL_HOSTNAME:
            # minDomains is irrelevant for hostname spreads: the global min
            # floors at 0 regardless (topologygroup.go:232-234)
            specs.append(TopoSpec(SPREAD_HOST, tsc.max_skew, anyway,
                                  self_select=self_sel,
                                  selector=tsc.label_selector))
        else:
            return None, relaxable
    aff = pod.spec.affinity
    if aff is not None:
        if aff.pod_affinity is not None:
            relaxable |= bool(aff.pod_affinity.preferred)
            for term in aff.pod_affinity.required:
                self_sel = _selector_is_self(term.label_selector, pod.labels)
                if not _term_namespaces_ok(term, pod):
                    return None, relaxable
                if term.topology_key == api_labels.LABEL_TOPOLOGY_ZONE:
                    specs.append(TopoSpec(AFFINITY_ZONE, self_select=self_sel,
                                          selector=term.label_selector))
                elif term.topology_key == api_labels.LABEL_HOSTNAME:
                    if not self_sel:
                        # non-self hostname affinity has no bootstrap and
                        # pins pods to live co-location state: host path
                        return None, relaxable
                    specs.append(TopoSpec(AFFINITY_HOST, self_select=True,
                                          selector=term.label_selector))
                else:
                    return None, relaxable
        if aff.pod_anti_affinity is not None:
            relaxable |= bool(aff.pod_anti_affinity.preferred)
            for term in aff.pod_anti_affinity.required:
                self_sel = _selector_is_self(term.label_selector, pod.labels)
                if not _term_namespaces_ok(term, pod):
                    return None, relaxable
                if term.topology_key == api_labels.LABEL_TOPOLOGY_ZONE:
                    specs.append(TopoSpec(ANTI_ZONE, self_select=self_sel,
                                          selector=term.label_selector))
                elif term.topology_key == api_labels.LABEL_HOSTNAME:
                    specs.append(TopoSpec(ANTI_HOST, self_select=self_sel,
                                          selector=term.label_selector))
                else:
                    return None, relaxable
    if len(specs) == 1:
        return specs, relaxable
    if len(specs) == 2:
        # supported layering: one zone-level + one hostname-level constraint,
        # where the zone constraint is spread or affinity and the hostname
        # constraint is spread or anti-affinity (zone choice and per-node
        # caps compose independently in the packer). Normalize zone-first.
        zone = [s for s in specs if s.kind in (SPREAD_ZONE, AFFINITY_ZONE)]
        host = [s for s in specs if s.kind in (SPREAD_HOST, ANTI_HOST)]
        if len(zone) == 1 and len(host) == 1:
            return zone + host, relaxable
        return None, relaxable
    if len(specs) > 2:
        return None, relaxable
    return specs, relaxable


def _affinity_key(pod: Pod):
    """Hashable structural key over the (frozen-dataclass) affinity terms."""
    a = pod.spec.affinity
    if a is None:
        return None
    parts = []
    if a.node_affinity is not None:
        parts.append(("node", tuple(a.node_affinity.required_terms),
                      tuple(a.node_affinity.preferred)))
    if a.pod_affinity is not None:
        parts.append(("pod", tuple(a.pod_affinity.required),
                      tuple(a.pod_affinity.preferred)))
    if a.pod_anti_affinity is not None:
        parts.append(("anti", tuple(a.pod_anti_affinity.required),
                      tuple(a.pod_anti_affinity.preferred)))
    return tuple(parts)


def group_pods(pods: List[Pod]) -> "Tuple[Optional[List[PodGroup]], str]":
    """All-or-nothing view of partition_pods: (groups, "") when EVERY pod is
    tensor-eligible, else (None, reason). Callers that can't mix solver
    paths per pod (the consolidation prefix simulator, the dryrun) use this;
    the provisioning solve uses partition_pods directly."""
    groups, leftover, reason = partition_pods(pods)
    if leftover:
        return None, reason
    return groups, ""


def _batch_conflicted_port_keys(pods: List[Pod]) -> set:
    """(port, protocol) keys used by 2+ batch pods with overlapping IPs
    (wildcard or duplicate). Users of such a key pairwise conflict
    (hostportusage.go:56-60); a key used once — or by distinct specific
    IPs only — constrains nothing within the batch."""
    by_pp: Dict[tuple, list] = {}
    for pod in pods:
        for ip, port, proto in _port_triples(pod):
            by_pp.setdefault((port, proto), []).append(ip)
    from ..scheduling.hostports import WILDCARD
    bad = set()
    for key, ips in by_pp.items():
        if len(ips) > 1 and (WILDCARD in ips or len(set(ips)) < len(ips)):
            bad.add(key)
    return bad


def partition_pods(pods: List[Pod], prebuckets: Optional[List[List[Pod]]] = None,
                   port_occupied=None, breakdown: Optional[list] = None):
    """Returns (groups, leftover_pods, reason): every pod lands on exactly
    one side. `groups` are tensor-eligible equivalence classes; `leftover`
    pods carry constraint shapes only the host oracle understands (host
    ports, volumes, unsupported topology forms) PLUS any group whose
    topology counts couple to a leftover pod or another group (shared
    selector domains must be counted by one solver). `reason` describes the
    first leftover cause (empty when leftover is empty).

    `breakdown`, when given, receives one ``(reason, pod_count)`` tuple per
    host-side bucket — the fallback cost ledger's raw attribution (the
    classification into shape classes happens in obs/fallbacks.py, so this
    module stays free of observability vocabulary).

    Two-phase: a cheap structural signature buckets the pods; the expensive
    classification (Requirements construction, topology-shape analysis) runs
    once per bucket — O(groups), not O(pods).

    `prebuckets` is the sidecar fast path: the wire's template column
    already partitions the batch into identical-spec buckets, so only each
    bucket's probe needs a signature (buckets whose probes collide merge —
    the wire keys templates by sub-object identity, which can split
    equal-content specs that this signature reunifies)."""
    groups: Dict = {}
    order: List = []
    # host-port eligibility (round 5): with a ``port_occupied`` checker the
    # caller vouches for existing-node usage, and ports that conflict with
    # NOTHING (batch-unique, unoccupied) constrain nothing — their pods
    # merge into ordinary groups instead of exploding G into single-pod
    # port groups. Without the checker (prefix sim, dryrun), port pods
    # demote to the host path wholesale, exactly the round-4 behavior.
    any_ports = any(p.spec.host_ports for p in pods) or (
        prebuckets is not None and any(
            b and b[0].spec.host_ports for b in prebuckets))
    bad_port_keys = ()
    if any_ports and port_occupied is not None:
        bad_port_keys = _batch_conflicted_port_keys(
            pods if prebuckets is None else
            [p for b in prebuckets for p in b])

    _port_sig_memo: Dict[tuple, object] = {}

    def port_sig(pod):
        """() when the pod's ports constrain nothing; the triples when they
        conflict (capped per-spec group); None -> demote (no checker).
        Memoized by triples: port_occupied scans every state node's usage,
        and identical specs (a deployment) must not re-pay that per pod."""
        triples = _port_triples(pod)
        if not triples:
            return ()
        if port_occupied is None:
            return None
        out = _port_sig_memo.get(triples, _port_sig_memo)
        if out is not _port_sig_memo:
            return out
        if any((port, proto) in bad_port_keys
               for _, port, proto in triples) or port_occupied(triples):
            out = triples
        else:
            out = ()
        _port_sig_memo[triples] = out
        return out

    # structural tokens memoized by sub-object identity: pods stamped from one
    # deployment share their spec sub-objects, so the expensive structural
    # hashing runs once per deployment, not once per pod — and the per-pod
    # signature is a tuple of small ints. Structural equality is preserved:
    # distinct-but-equal objects resolve to the same token via struct_tokens.
    # The loop body is manually inlined: at 50k pods the per-call overhead of
    # a tok() helper is itself a top-line cost.
    id_memo: Dict[int, int] = {}
    struct_tokens: Dict[object, int] = {}
    id_get = id_memo.get
    tok_setdefault = struct_tokens.setdefault

    def tok(obj, builder):
        t = id_get(id(obj))
        if t is None:
            t = tok_setdefault(builder(obj), len(struct_tokens))
            id_memo[id(obj)] = t
        return t

    ident = lambda o: o
    items_key = lambda d: tuple(sorted(d.items()))
    init_key = _init_sig
    reasons: Dict[int, str] = {}  # id(bucket) -> why it's host-path

    if prebuckets is not None:
        for bucket in prebuckets:
            if not bucket:
                continue
            probe = bucket[0]
            sig = (tuple(sorted(probe.spec.node_selector.items())),
                   _affinity_key(probe),
                   tuple(probe.spec.topology_spread_constraints),
                   tuple(probe.spec.tolerations),
                   tuple(sorted(probe.labels.items())),
                   tuple(tuple(sorted(r.items()))
                         for r in probe.container_requests),
                   tuple(_init_sig(r) for r in probe.init_container_requests),
                   port_sig(probe),
                   () if not probe.spec.volumes
                   else tuple(probe.spec.volumes))
            g = groups.get(sig)
            if g is None:
                psig = port_sig(probe)
                specs, relaxable = _classify_topology(probe)
                reason = _demotion_reason(probe, psig, specs)
                g = PodGroup(pods=[], requirements=pod_requirements(probe),
                             requests=probe.requests(),
                             tolerations=tuple(probe.spec.tolerations),
                             labels=dict(probe.labels), topo=specs or [],
                             has_relaxable=relaxable
                             or has_preferred_node_affinity(probe),
                             host_ports=psig or ())
                if reason:
                    reasons[id(g)] = reason
                groups[sig] = g
                order.append(g)
            g.pods.extend(bucket)
        return _finish_partition(order, reasons, breakdown)

    for pod in pods:
        spec = pod.spec
        aff = spec.affinity
        # labels + requests dicts are distinct objects per pod (stamped
        # metadata), so their id-memo never hits: key directly by content
        labels = pod.metadata.labels
        lt = tok_setdefault(tuple(sorted(labels.items())) if len(labels) > 1
                            else tuple(labels.items()), len(struct_tokens))
        reqs = pod.container_requests
        rt = (tok(reqs[0], items_key) if len(reqs) == 1
              else tuple(tok(r, items_key) for r in reqs))
        spread = spec.topology_spread_constraints
        sig = (
            # node_selector dicts are stamped fresh per pod, so the id-memo
            # never hits; the common empty case skips the content hash
            -1 if not spec.node_selector else tok(spec.node_selector, items_key),
            -1 if aff is None else tok(aff, lambda a, p=pod: _affinity_key(p)),
            tok(spread[0], ident) if len(spread) == 1
            else tuple(tok(c, ident) for c in spread),
            # empty collections are the common case: skip the generator
            () if not spec.tolerations
            else tuple(tok(t, ident) for t in spec.tolerations),
            lt,
            rt,
            () if not pod.init_container_requests
            else tuple(tok(r, init_key) for r in pod.init_container_requests),
            # port status keys the bucket: conflicting port specs must not
            # merge; constraint-free ports vanish from the signature
            () if not spec.host_ports else port_sig(pod),
            # volume content keys the bucket: ephemeral groups with distinct
            # storage classes must not merge (different CSI drivers/caps)
            () if not spec.volumes else tuple(spec.volumes),
        )
        g = groups.get(sig)
        if g is None:
            psig = port_sig(pod)
            specs, relaxable = _classify_topology(pod)
            reason = _demotion_reason(pod, psig, specs)
            g = PodGroup(pods=[], requirements=pod_requirements(pod),
                         requests=pod.requests(),
                         tolerations=tuple(pod.spec.tolerations),
                         labels=dict(pod.labels), topo=specs or [],
                         has_relaxable=relaxable or has_preferred_node_affinity(pod),
                         host_ports=psig or ())
            if reason:
                reasons[id(g)] = reason
            groups[sig] = g
            order.append(g)
        g.pods.append(pod)

    return _finish_partition(order, reasons, breakdown)


def _finish_partition(order: List[PodGroup], reasons: Dict[int, str],
                      breakdown: Optional[list] = None):
    # cross-group selector coupling: a topology selector matching another
    # bucket's labels means shared domain counts — both sides must be solved
    # by ONE solver. Any bucket coupled (transitively) to a host-path bucket
    # or to another eligible bucket is demoted to the host side.
    sels: Dict[int, list] = {}
    for g in order:
        out = []
        p = g.pods[0]
        for tsc in p.spec.topology_spread_constraints:
            if tsc.label_selector is not None:
                out.append(tsc.label_selector)
        aff = p.spec.affinity
        if aff is not None:
            for pa in (aff.pod_affinity, aff.pod_anti_affinity):
                if pa is None:
                    continue
                for term in pa.required:
                    if term.label_selector is not None:
                        out.append(term.label_selector)
                for wt in pa.preferred:
                    if wt.term.label_selector is not None:
                        out.append(wt.term.label_selector)
        sels[id(g)] = out

    eligible = [g for g in order if id(g) not in reasons]
    host_side = [g for g in order if id(g) in reasons]
    changed = True
    while changed:
        changed = False
        still = []
        for g in eligible:
            demote = ""
            # a host-side pod inside my selector domains (or vice versa)
            for h in host_side:
                if any(s.matches(h.labels) for s in sels[id(g)]) or \
                        any(s.matches(g.labels) for s in sels[id(h)]):
                    demote = "topology selector couples to host-path pods"
                    break
            if not demote and sels[id(g)]:
                # eligible-to-eligible coupling: the kernel counts each
                # group's domains independently, so shared counts demote both
                for g2 in eligible:
                    if g2 is not g and any(s.matches(g2.labels)
                                           for s in sels[id(g)]):
                        demote = "topology selector couples multiple pod groups"
                        break
            if demote:
                reasons[id(g)] = demote
                host_side.append(g)
                changed = True
            else:
                still.append(g)
        eligible = still

    leftover = [p for g in order if id(g) in reasons for p in g.pods]
    reason = next((reasons[id(g)] for g in order if id(g) in reasons), "")
    if breakdown is not None:
        breakdown.extend((reasons[id(g)], len(g.pods))
                         for g in order if id(g) in reasons)
    return [g for g in order if id(g) not in reasons], leftover, reason
