"""Topology-domain universe construction.

Mirrors /root/reference/pkg/controllers/provisioning/provisioner.go:236-283:
per nodepool, intersect instance-type requirements with the pool's template
requirements so e.g. zones offered by an instance type but excluded by the pool
don't expand the universe; pool-level In requirements also contribute.
"""

from __future__ import annotations

from typing import Dict, List, Set

from ..api.nodepool import NodePool
from ..cloudprovider.types import InstanceType
from ..scheduling.requirement import IN
from ..scheduling.requirements import (Requirements, label_requirements,
                                       node_selector_requirements)


def build_topology_domains(nodepools: List[NodePool],
                           instance_types: Dict[str, List[InstanceType]]) -> Dict[str, Set[str]]:
    domains: Dict[str, Set[str]] = {}
    for np in nodepools:
        pool_reqs_base = node_selector_requirements(np.spec.template.spec.requirements)
        pool_reqs_base.add(*label_requirements(np.spec.template.metadata_labels).values())
        for it in instance_types.get(np.name, []):
            reqs = Requirements(pool_reqs_base.values())
            reqs.add(*it.requirements.values())
            for key in reqs:
                domains.setdefault(key, set()).update(reqs.get(key).values_list())
        for key in pool_reqs_base:
            if pool_reqs_base.get(key).operator() == IN:
                domains.setdefault(key, set()).update(pool_reqs_base.get(key).values_list())
    return domains
