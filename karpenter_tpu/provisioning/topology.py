"""Topology tracking: spread constraints, pod (anti-)affinity, inverse anti-affinity.

Host-side oracle implementation with the semantics of
/root/reference/pkg/controllers/provisioning/scheduling/{topology,topologygroup,
topologynodefilter}.go. The TPU solver (karpenter_tpu.ops.topology) reproduces
the domain-count arithmetic as dense tensors; this module is the general path
and the conformance reference.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Set

from ..api import labels as api_labels
from ..api.objects import Pod, PodAffinityTerm, TopologySpreadConstraint
from ..scheduling.requirement import (DOES_NOT_EXIST, EXISTS, IN, Requirement)
from ..scheduling.requirements import (Requirements, label_requirements,
                                       node_selector_requirements)

MAX_INT32 = 2**31 - 1

SPREAD = "spread"
POD_AFFINITY = "pod-affinity"
POD_ANTI_AFFINITY = "pod-anti-affinity"


class TopologyNodeFilter:
    """OR of requirement sets limiting which nodes count for a spread
    (topologynodefilter.go:33-73). Empty filter matches everything."""

    def __init__(self, requirement_sets: List[Requirements]):
        self.requirement_sets = requirement_sets

    @classmethod
    def for_pod(cls, pod: Pod) -> "TopologyNodeFilter":
        selector_reqs = label_requirements(pod.spec.node_selector)
        aff = pod.spec.affinity
        if aff is None or aff.node_affinity is None or not aff.node_affinity.required_terms:
            return cls([selector_reqs])
        sets_ = []
        for term in aff.node_affinity.required_terms:
            reqs = Requirements()
            reqs.add(*selector_reqs.values())
            reqs.add(*node_selector_requirements(term.match_expressions).values())
            sets_.append(reqs)
        return cls(sets_)

    def matches_requirements(self, requirements: Requirements,
                             allow_undefined: frozenset = frozenset()) -> bool:
        if not self.requirement_sets:
            return True
        return any(not requirements.compatible(r, allow_undefined)
                   for r in self.requirement_sets)

    def matches_labels(self, labels: dict) -> bool:
        return self.matches_requirements(label_requirements(labels))

    def signature(self):
        out = []
        for reqs in self.requirement_sets:
            out.append(tuple(sorted((k, reqs.get(k).complement,
                                     frozenset(reqs.get(k).values),
                                     reqs.get(k).greater_than, reqs.get(k).less_than)
                                    for k in reqs)))
        return frozenset(out)


class TopologyGroup:
    """Domain->count tracking per constraint (topologygroup.go:56-175)."""

    def __init__(self, topo_type: str, key: str, pod: Pod, namespaces: Set[str],
                 selector, max_skew: int, min_domains: Optional[int],
                 domains: Iterable[str]):
        self.type = topo_type
        self.key = key
        self.namespaces = set(namespaces)
        self.selector = selector  # LabelSelector or None (None selects nothing)
        self.node_filter = (TopologyNodeFilter.for_pod(pod)
                            if topo_type == SPREAD else TopologyNodeFilter([]))
        self.max_skew = max_skew
        self.min_domains = min_domains
        self.domains: Dict[str, int] = {d: 0 for d in domains}
        self.empty_domains: Set[str] = set(domains)
        # occupied-domain index: hostname groups accumulate thousands of
        # placeholder domains (one per in-flight claim), while the occupied
        # set stays tiny — affinity selection must not scan the whole space
        self.nonempty: Set[str] = set()
        self.owners: Set[str] = set()

    # identity hash so one group tracks many same-shaped pods (topologygroup.go:159-175)
    def signature(self):
        sel_sig = None
        if self.selector is not None:
            sel_sig = (self.selector.match_labels, frozenset(self.selector.match_expressions))
        return (self.type, self.key, frozenset(self.namespaces), sel_sig,
                self.max_skew, self.node_filter.signature())

    def selects(self, pod: Pod) -> bool:
        return pod.namespace in self.namespaces and \
            self.selector is not None and self.selector.matches(pod.labels)

    def counts(self, pod: Pod, requirements: Requirements,
               allow_undefined: frozenset = frozenset()) -> bool:
        return self.selects(pod) and \
            self.node_filter.matches_requirements(requirements, allow_undefined)

    def record(self, *domains: str) -> None:
        for d in domains:
            self.domains[d] = self.domains.get(d, 0) + 1
            self.empty_domains.discard(d)
            self.nonempty.add(d)

    def register(self, *domains: str) -> None:
        for d in domains:
            if d not in self.domains:
                self.domains[d] = 0
                self.empty_domains.add(d)

    def unregister(self, *domains: str) -> None:
        for d in domains:
            self.domains.pop(d, None)
            self.empty_domains.discard(d)
            self.nonempty.discard(d)

    def get(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        if self.type == SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type == POD_AFFINITY:
            return self._next_domain_affinity(pod, pod_domains, node_domains)
        return self._next_domain_anti_affinity(pod_domains, node_domains)

    # --- selection rules ---------------------------------------------------

    def _domain_min_count(self, domains: Requirement) -> int:
        """topologygroup.go:229-250 — hostname topologies floor at 0 because a
        new node can always be created."""
        if self.key == api_labels.LABEL_HOSTNAME:
            return 0
        lo = MAX_INT32
        supported = 0
        for domain, count in self.domains.items():
            if domains.has(domain):
                supported += 1
                if count < lo:
                    lo = count
        if self.min_domains is not None and supported < self.min_domains:
            lo = 0
        return lo

    def _next_domain_spread(self, pod: Pod, pod_domains: Requirement,
                            node_domains: Requirement) -> Requirement:
        """Min-count domain within maxSkew of the global min (topologygroup.go:181-227).
        Deterministic tie-break on domain name keeps solves reproducible."""
        global_min = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)
        best_domain = ""
        best_count = MAX_INT32
        if node_domains.operator() == IN:
            candidates = [d for d in node_domains.values_list() if d in self.domains]
        else:
            candidates = [d for d in self.domains if node_domains.has(d)]
        for domain in sorted(candidates):
            count = self.domains[domain]
            if self_selecting:
                count += 1
            if count - global_min <= self.max_skew and count < best_count:
                best_domain = domain
                best_count = count
        if not best_domain:
            return Requirement(pod_domains.key, DOES_NOT_EXIST)
        return Requirement(pod_domains.key, IN, [best_domain])

    def _any_compatible_pod_domain(self, pod_domains: Requirement) -> bool:
        return any(pod_domains.has(d) for d in self.nonempty)

    def _next_domain_affinity(self, pod: Pod, pod_domains: Requirement,
                              node_domains: Requirement) -> Requirement:
        """topologygroup.go:253-300."""
        options = Requirement(pod_domains.key, DOES_NOT_EXIST)
        if node_domains.operator() == IN and \
                node_domains.length() < len(self.nonempty):
            for d in node_domains.values_list():
                if d in self.nonempty and pod_domains.has(d):
                    options.insert(d)
        else:
            for d in self.nonempty:
                if pod_domains.has(d) and node_domains.has(d):
                    options.insert(d)
        if options.length() != 0:
            return options
        # bootstrap: self-selecting pod with no (compatible) scheduled pods yet
        if self.selects(pod) and (len(self.domains) == len(self.empty_domains)
                                  or not self._any_compatible_pod_domain(pod_domains)):
            intersected = pod_domains.intersection(node_domains)
            for d in sorted(self.domains):
                if intersected.has(d):
                    options.insert(d)
                    break
            for d in sorted(self.domains):
                if pod_domains.has(d):
                    options.insert(d)
                    break
        return options

    def _next_domain_anti_affinity(self, pod_domains: Requirement,
                                   node_domains: Requirement) -> Requirement:
        """Empty domains only (topologygroup.go:316-342)."""
        options = Requirement(pod_domains.key, DOES_NOT_EXIST)
        if node_domains.operator() == IN and node_domains.length() < len(self.empty_domains):
            for d in node_domains.values_list():
                if d in self.empty_domains and pod_domains.has(d):
                    options.insert(d)
        else:
            for d in self.empty_domains:
                if node_domains.has(d) and pod_domains.has(d):
                    options.insert(d)
        return options


def has_pod_anti_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return aff is not None and aff.pod_anti_affinity is not None and \
        (len(aff.pod_anti_affinity.required) > 0 or len(aff.pod_anti_affinity.preferred) > 0)


def ignored_for_topology(pod: Pod) -> bool:
    """topology.go:449-451 — unscheduled/terminal/terminating pods don't count."""
    return (not pod.spec.node_name or pod.status.phase in ("Succeeded", "Failed")
            or pod.metadata.deletion_timestamp is not None)


class ClusterView:
    """Minimal view of the live cluster the topology needs: scheduled pods and
    node labels. Backed by state.Cluster in the full runtime; tests can stub it."""

    def list_pods(self, namespace: str, selector) -> List[Pod]:
        return []

    def node_labels(self, node_name: str) -> Optional[dict]:
        return None

    def for_pods_with_anti_affinity(self) -> Iterable:
        """Yields (pod, node_labels) pairs."""
        return []


class Topology:
    """topology.go:41-409."""

    def __init__(self, cluster: ClusterView, domains: Dict[str, Set[str]],
                 pods: List[Pod]):
        self.cluster = cluster
        self.domains = domains
        self.topologies: Dict = {}           # signature -> TopologyGroup
        self.inverse_topologies: Dict = {}   # signature -> TopologyGroup
        self.excluded_pods: Set[str] = {p.uid for p in pods}
        self._update_inverse_affinities()
        for p in pods:
            self.update(p)

    def update(self, pod: Pod) -> None:
        """Re-register the pod as owner of its current constraint set; called
        after preference relaxation (topology.go:99-134)."""
        for tg in self.topologies.values():
            tg.owners.discard(pod.uid)
        if has_pod_anti_affinity(pod):
            self._update_inverse_anti_affinity(pod, None)
        groups = self._new_for_topologies(pod) + self._new_for_affinities(pod)
        for tg in groups:
            sig = tg.signature()
            existing = self.topologies.get(sig)
            if existing is None:
                self._count_domains(tg)
                self.topologies[sig] = tg
            else:
                tg = existing
            tg.owners.add(pod.uid)

    def record(self, pod: Pod, requirements: Requirements,
               allow_undefined: frozenset = frozenset()) -> None:
        """topology.go:137-160."""
        for tg in self.topologies.values():
            if tg.counts(pod, requirements, allow_undefined):
                domains = requirements.get(tg.key)
                if tg.type == POD_ANTI_AFFINITY:
                    tg.record(*domains.values_list())
                elif domains.length() == 1:
                    tg.record(domains.values_list()[0])
        for tg in self.inverse_topologies.values():
            if pod.uid in tg.owners:
                tg.record(*requirements.get(tg.key).values_list())

    def add_requirements(self, pod_requirements: Requirements,
                         node_requirements: Requirements, pod: Pod,
                         allow_undefined: frozenset = frozenset()):
        """Tighten node requirements with topology domain selections; returns
        (Requirements, None) or (None, error) (topology.go:166-188). Sets
        `self.last_add_tightened` (valid until the next call — the solve is
        single-threaded) so callers can tell whether any topology group
        actually constrained this pod: a non-tightening result depends only
        on the inputs, which backs the claims' compat cache."""
        requirements = Requirements(node_requirements.values())
        self.last_add_tightened = False
        for tg in self._matching_topologies(pod, node_requirements, allow_undefined):
            pod_domains = pod_requirements.get(tg.key)
            node_domains = node_requirements.get(tg.key)
            domains = tg.get(pod, pod_domains, node_domains)
            if domains.length() == 0:
                return None, (f"unsatisfiable topology constraint for {tg.type}, "
                              f"key={tg.key}")
            requirements.add(domains)
            self.last_add_tightened = True
        return requirements, None

    def register(self, topology_key: str, domain: str) -> None:
        for tg in self.topologies.values():
            if tg.key == topology_key:
                tg.register(domain)
        for tg in self.inverse_topologies.values():
            if tg.key == topology_key:
                tg.register(domain)

    def unregister(self, topology_key: str, domain: str) -> None:
        for tg in self.topologies.values():
            if tg.key == topology_key:
                tg.unregister(domain)
        for tg in self.inverse_topologies.values():
            if tg.key == topology_key:
                tg.unregister(domain)

    # --- construction ------------------------------------------------------

    def _new_for_topologies(self, pod: Pod) -> List[TopologyGroup]:
        out = []
        for cs in pod.spec.topology_spread_constraints:
            out.append(TopologyGroup(
                SPREAD, cs.topology_key, pod, {pod.namespace}, cs.label_selector,
                cs.max_skew, cs.min_domains, self.domains.get(cs.topology_key, set())))
        return out

    def _new_for_affinities(self, pod: Pod) -> List[TopologyGroup]:
        out = []
        aff = pod.spec.affinity
        if aff is None:
            return out
        terms: List = []
        if aff.pod_affinity is not None:
            terms += [(POD_AFFINITY, t) for t in aff.pod_affinity.required]
            terms += [(POD_AFFINITY, wt.term) for wt in aff.pod_affinity.preferred]
        if aff.pod_anti_affinity is not None:
            terms += [(POD_ANTI_AFFINITY, t) for t in aff.pod_anti_affinity.required]
            terms += [(POD_ANTI_AFFINITY, wt.term) for wt in aff.pod_anti_affinity.preferred]
        for topo_type, term in terms:
            namespaces = set(term.namespaces) or {pod.namespace}
            out.append(TopologyGroup(
                topo_type, term.topology_key, pod, namespaces, term.label_selector,
                MAX_INT32, None, self.domains.get(term.topology_key, set())))
        return out

    def _update_inverse_affinities(self) -> None:
        for pod, node_labels in self.cluster.for_pods_with_anti_affinity():
            if pod.uid in self.excluded_pods:
                continue
            self._update_inverse_anti_affinity(pod, node_labels)

    def _update_inverse_anti_affinity(self, pod: Pod, node_labels: Optional[dict]) -> None:
        """Required anti-affinity terms only (topology.go:237-262)."""
        aff = pod.spec.affinity
        for term in aff.pod_anti_affinity.required:
            namespaces = set(term.namespaces) or {pod.namespace}
            tg = TopologyGroup(POD_ANTI_AFFINITY, term.topology_key, pod, namespaces,
                               term.label_selector, MAX_INT32, None,
                               self.domains.get(term.topology_key, set()))
            sig = tg.signature()
            existing = self.inverse_topologies.get(sig)
            if existing is None:
                self.inverse_topologies[sig] = tg
            else:
                tg = existing
            if node_labels is not None and tg.key in node_labels:
                tg.record(node_labels[tg.key])
            tg.owners.add(pod.uid)

    def _count_domains(self, tg: TopologyGroup) -> None:
        """Initial scan of scheduled cluster pods (topology.go:268-321)."""
        for ns in tg.namespaces:
            for p in self.cluster.list_pods(ns, tg.selector):
                if ignored_for_topology(p) or p.uid in self.excluded_pods:
                    continue
                labels = self.cluster.node_labels(p.spec.node_name)
                if labels is None:
                    continue
                domain = labels.get(tg.key)
                if domain is None and tg.key == api_labels.LABEL_HOSTNAME:
                    domain = p.spec.node_name
                if domain is None:
                    continue
                if not tg.node_filter.matches_labels(labels):
                    continue
                tg.record(domain)

    def _matching_topologies(self, pod: Pod, requirements: Requirements,
                             allow_undefined: frozenset):
        out = [tg for tg in self.topologies.values() if pod.uid in tg.owners]
        out += [tg for tg in self.inverse_topologies.values()
                if tg.counts(pod, requirements, allow_undefined)]
        return out
