"""Controller manager: the runtime that drives all reconcilers.

The reference uses controller-runtime (operator.go:105-206): watch-driven
per-object reconcilers plus singleton controllers (provisioner, disruption)
on their own loops. This manager reproduces that model on a deterministic
single dispatch queue:

- watch controllers subscribe to object kinds; store events enqueue
  (controller, object-ref) work items, deduped the way controller-runtime's
  workqueue dedupes;
- singleton controllers run on tick() — the test harness calls them
  explicitly (the reference's ExpectSingletonReconciled), the operator loop
  calls them on their poll cadence;
- requeue-after is honored via the injected clock, so fake clocks drive
  time-based reconciles in tests exactly like the reference's fake
  clock.Clock.

Determinism over parallelism is intentional: the reference needs 1000-way
reconcile concurrency because each reconcile blocks on API round-trips
(lifecycle/controller.go:102); here store ops are in-memory and the heavy
math lives in batched device programs, so a single dispatch loop keeps
ordering reproducible without sacrificing throughput.
"""

from __future__ import annotations

import heapq
import itertools
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..kube.store import DELETED, Event, Store
from ..logging import get_logger
from ..metrics.registry import RECONCILE_ERRORS, RECONCILE_QUARANTINED
from ..utils.backoff import ItemBackoff, TerminalError
from ..utils.clock import Clock
from ..utils.injection import with_controller

log = get_logger("manager")

# Per-item retry schedule: workqueue.DefaultTypedControllerRateLimiter's
# ItemExponentialFailureRateLimiter parameters scaled for an operator loop
# (1s base instead of 5ms — store ops are in-memory, so sub-second retries
# would just spin the dispatch loop against a persistent fault).
RETRY_BASE_SECONDS = 1.0
RETRY_CAP_SECONDS = 300.0
# Consecutive failures before an item is quarantined to the dead-letter set.
# The reference retries forever (rate-limited); quarantine is this runtime's
# crash-only refinement — see DEVIATIONS.md.
MAX_RETRIES = 10


# TerminalError's public home is this module (the reconcile runtime, like
# the reference's reconcile.TerminalError); it is DEFINED in utils/backoff
# alongside the retry policy so leaf modules can raise it without importing
# the controller runtime. Raised from a reconciler, the error is counted
# and logged but the item is neither retried nor quarantined. Wrap a cause:
# ``raise TerminalError(str(exc)) from exc``.
__all__ = ["Controller", "Manager", "Result", "SingletonController",
           "TerminalError"]


def _never_quarantine(exc: BaseException) -> bool:
    """Typed cloudprovider errors that signal an environmental condition
    (capacity, eventual consistency) back off forever rather than dead-
    lettering the item: the item is healthy, the world is not."""
    from ..cloudprovider.types import (InsufficientCapacityError,
                                       NodeClassNotReadyError)
    return isinstance(exc, (InsufficientCapacityError,
                            NodeClassNotReadyError))


class Result:
    """Reconcile result: optional requeue delay in seconds."""

    def __init__(self, requeue_after: Optional[float] = None):
        self.requeue_after = requeue_after


class Controller:
    """Watch-driven reconciler. Subclasses set `kinds` and implement
    reconcile(obj) -> Optional[Result]."""

    name: str = "controller"
    kinds: tuple = ()

    def reconcile(self, obj) -> Optional[Result]:
        raise NotImplementedError

    def interested(self, ev: Event) -> bool:
        """Event filter; default = any event for a watched kind."""
        return True


class SingletonController:
    """Poll-loop reconciler (provisioner, disruption). reconcile() returns an
    optional Result whose requeue_after sets the next poll delay."""

    name: str = "singleton"

    def reconcile(self) -> Optional[Result]:
        raise NotImplementedError


class Manager:
    def __init__(self, store: Store, clock: Optional[Clock] = None,
                 recorder=None, max_retries: int = MAX_RETRIES):
        self.store = store
        self.clock = clock or store.clock
        self.recorder = recorder
        self.controllers: List[Controller] = []
        self.singletons: List[SingletonController] = []
        self._queue: Deque[Tuple[Controller, object]] = deque()
        self._queued: set = set()
        # crash isolation: per-(controller, object) retry backoff, the
        # dead-letter set for items that exhausted their retries, and the
        # workqueue processing/dirty state that makes failure-path requeue
        # exactly-once (an event arriving DURING a reconcile marks the item
        # dirty instead of double-queueing it)
        self.backoff = ItemBackoff(RETRY_BASE_SECONDS, RETRY_CAP_SECONDS)
        self.max_retries = max_retries
        # quarantine budget, tracked separately from the delay backoff:
        # exempt (never-quarantine) errors escalate the DELAY but reset
        # this counter, so "insufficient capacity for an hour, then one
        # apiserver flake" gets a full fresh retry budget instead of
        # instant dead-lettering
        self._q_failures: Dict[tuple, int] = {}
        self.deadletter: Dict[tuple, dict] = {}
        self._processing: Optional[tuple] = None
        self._dirty = False
        # singleton crash isolation: a raising singleton is skipped until
        # its backoff delay elapses instead of crashing tick()
        self._singleton_next: Dict[str, float] = {}
        self._timers: list = []  # heap of (fire_at, seq, controller, obj)
        self._timer_seq = itertools.count()
        # AddAfter dedup, bounded per (controller, object): one LIVE heap
        # entry (the earliest fire time) plus at most one DEFERRED later
        # intent — the LATEST requested fire time — re-armed when the live
        # timer fires. client-go's delaying queue keeps a single entry per
        # item and only moves it earlier — but silently dropping a later
        # requeue loses a controller's periodic recheck when the earlier
        # reconcile returns no requeue (ADVICE r3); keeping the latest
        # intent preserves the final recheck (intermediate intents are
        # subsumed by the earlier fire's reconcile) while still preventing
        # per-event perpetual timer chains
        self._timer_pending: Dict[tuple, float] = {}
        self._timer_deferred: Dict[tuple, tuple] = {}  # key -> (fire_at, c, obj)
        store.watch(self._on_event)

    # -- registration -------------------------------------------------------

    def register(self, *controllers) -> "Manager":
        for c in controllers:
            if isinstance(c, SingletonController):
                self.singletons.append(c)
            else:
                self.controllers.append(c)
        return self

    # -- event plumbing -----------------------------------------------------

    def _on_event(self, ev: Event) -> None:
        for c in self.controllers:
            if ev.kind in c.kinds and c.interested(ev):
                self._enqueue(c, ev.obj)

    def _enqueue(self, controller: Controller, obj) -> None:
        key = (controller.name, type(obj).__name__,
               obj.metadata.namespace, obj.metadata.name)
        if key == self._processing:
            # workqueue dirty-set semantics: new work for the item being
            # reconciled is folded into ONE post-reconcile requeue (on
            # success) or into the already-armed retry (on failure) —
            # never a second concurrent queue entry
            self._dirty = True
            return
        if key in self.deadletter:
            # new work releases a quarantined item: fresh input is the
            # crash-only recovery signal, and the failure budget restarts
            self._release(key)
        if key in self._queued:
            return
        self._queued.add(key)
        self._queue.append((controller, obj))

    def requeue(self, controller: Controller, obj, after: float) -> None:
        key = (controller.name, type(obj).__name__,
               obj.metadata.namespace, obj.metadata.name)
        fire_at = self.clock.now() + after
        pending = self._timer_pending.get(key)
        if pending is not None:
            if fire_at >= pending:
                # keep the LATEST intent to re-arm after the live timer
                # fires: earlier intermediate intents are subsumed by the
                # live timer's reconcile (which sees newer state and re-arms
                # as needed), but the final periodic recheck must survive
                if fire_at > pending:
                    deferred = self._timer_deferred.get(key)
                    if deferred is None or fire_at > deferred[0]:
                        self._timer_deferred[key] = (fire_at, controller, obj)
                return
            # earlier than the live timer: move it up (old entry goes stale);
            # the displaced time stays pending as the deferred later intent
            deferred = self._timer_deferred.get(key)
            if deferred is None or pending > deferred[0]:
                self._timer_deferred[key] = (pending, controller, obj)
        self._timer_pending[key] = fire_at
        heapq.heappush(self._timers,
                       (fire_at, next(self._timer_seq), controller, obj))

    # -- dispatch -----------------------------------------------------------

    def _fire_due_timers(self) -> None:
        now = self.clock.now()
        while self._timers and self._timers[0][0] <= now:
            fire_at, _, c, obj = heapq.heappop(self._timers)
            key = (c.name, type(obj).__name__,
                   obj.metadata.namespace, obj.metadata.name)
            if self._timer_pending.get(key) != fire_at:
                continue  # superseded by an earlier requeue; stale heap entry
            del self._timer_pending[key]
            deferred = self._timer_deferred.pop(key, None)
            if deferred is not None:
                d_at, d_c, d_obj = deferred
                self._timer_pending[key] = d_at
                heapq.heappush(self._timers,
                               (d_at, next(self._timer_seq), d_c, d_obj))
            self._enqueue(c, obj)

    def drain(self, max_items: int = 100_000) -> int:
        """Dispatch queued work until quiet. Returns items processed.

        Every item runs under recovery (controller-runtime recovers
        reconcile panics, controller.go:105-117): a raising reconciler is
        logged, counted in reconcile_errors_total, and retried through the
        per-item exponential backoff; after max_retries consecutive
        failures the item moves to the dead-letter set. The store re-fetch
        runs inside the protected region too — a flaky store read is a
        retryable failure, not a dispatch-loop crash."""
        n = 0
        self._fire_due_timers()
        while self._queue and n < max_items:
            controller, obj = self._queue.popleft()
            key = (controller.name, type(obj).__name__,
                   obj.metadata.namespace, obj.metadata.name)
            self._queued.discard(key)
            self._processing = key
            self._dirty = False
            target = obj
            try:
                with with_controller(controller.name):
                    # re-fetch: reconcile current state, not the snapshot
                    live = self.store.get(type(obj), obj.metadata.name,
                                          obj.metadata.namespace)
                    target = live if live is not None else obj
                    result = controller.reconcile(target)
            except Exception as exc:  # noqa: BLE001 — crash isolation
                dirty = self._dirty
                self._processing = None
                self._reconcile_failed(controller, target, key, exc, dirty)
            else:
                self._processing = None
                self.backoff.forget(key)
                self._q_failures.pop(key, None)
                if result is not None and result.requeue_after is not None:
                    self.requeue(controller, target, result.requeue_after)
                if self._dirty:
                    self._enqueue(controller, target)
            n += 1
            self._fire_due_timers()
        return n

    # -- failure handling ----------------------------------------------------

    def _reconcile_failed(self, controller, obj, key: tuple,
                          exc: Exception, dirty: bool = False) -> None:
        RECONCILE_ERRORS.inc({"controller": controller.name})
        log.error("reconcile failed", controller=controller.name,
                  kind=key[1], namespace=key[2], name=key[3],
                  error=f"{type(exc).__name__}: {exc}")
        if isinstance(exc, TerminalError):
            # reconcile.TerminalError semantics: never retried. A later
            # watch event still re-reconciles (new input, new verdict) —
            # including one that arrived DURING this reconcile (dirty).
            self.backoff.forget(key)
            self._q_failures.pop(key, None)
            if dirty:
                self._enqueue(controller, obj)
            return
        delay = self.backoff.next_delay(key)
        if _never_quarantine(exc):
            # environmental error: the delay keeps escalating, but the
            # quarantine budget restarts — the item itself is healthy
            self._q_failures.pop(key, None)
            self.requeue(controller, obj, delay)
            return
        n = self._q_failures.get(key, 0) + 1
        self._q_failures[key] = n
        if n >= self.max_retries:
            if dirty:
                # the event that arrived mid-reconcile is fresh input that
                # restarts the failure budget: retry immediately instead of
                # dead-lettering past it (and never publish a quarantine
                # that would last zero time)
                self.backoff.forget(key)
                self._q_failures.pop(key, None)
                self._enqueue(controller, obj)
                return
            self._quarantine(controller, obj, key, exc, n)
            return
        # dirty folds into the armed retry: exactly-once requeue
        self.requeue(controller, obj, delay)

    def _quarantine(self, controller, obj, key: tuple, exc: Exception,
                    failures: int) -> None:
        # `failures` is the quarantine budget actually consumed (consecutive
        # NON-exempt failures), not the raw backoff count — an exempt
        # capacity streak beforehand must not inflate what operators read
        self.deadletter[key] = {
            "controller": controller.name, "kind": key[1],
            "namespace": key[2], "name": key[3],
            "error": f"{type(exc).__name__}: {exc}",
            "failures": failures,
            "at": self.clock.now(), "obj": obj,
        }
        self.backoff.forget(key)
        self._q_failures.pop(key, None)
        # cancel any pre-quarantine requeue intent (a periodic recheck armed
        # by an earlier success): only a FRESH watch event may release the
        # quarantine, not a stale timer. Heap entries go stale and are
        # skipped by the _timer_pending fire check.
        self._timer_pending.pop(key, None)
        self._timer_deferred.pop(key, None)
        self._set_quarantine_gauge(controller.name)
        log.error("work item quarantined to the dead-letter set",
                  controller=controller.name, kind=key[1], name=key[3],
                  failures=self.deadletter[key]["failures"])
        if self.recorder is not None:
            from ..events import catalog as events_catalog
            self.recorder.publish(events_catalog.reconcile_quarantined(
                key[1], key[3], key[2], controller.name, str(exc)))

    def _release(self, key: tuple) -> None:
        info = self.deadletter.pop(key, None)
        if info is not None:
            self.backoff.forget(key)
            self._q_failures.pop(key, None)
            self._set_quarantine_gauge(info["controller"])

    def _set_quarantine_gauge(self, controller_name: str) -> None:
        RECONCILE_QUARANTINED.set(
            sum(1 for i in self.deadletter.values()
                if i["controller"] == controller_name),
            {"controller": controller_name})

    def _run_singleton(self, s: SingletonController) -> None:
        """One singleton pass under recovery: a raising singleton backs off
        (skipped until its retry delay elapses) instead of crashing the
        loop — the provisioner and disruption engines degrade to a slower
        cadence under faults, they do not take the operator down."""
        next_try = self._singleton_next.get(s.name)
        if next_try is not None and self.clock.now() < next_try:
            return
        try:
            with with_controller(s.name):
                s.reconcile()
        except Exception as exc:  # noqa: BLE001 — crash isolation
            RECONCILE_ERRORS.inc({"controller": s.name})
            key = (s.name, "__singleton__")
            if isinstance(exc, TerminalError):
                # a singleton is an engine — it can't be dead-lettered and
                # "never retry" would silently kill it, so terminal means
                # the SLOWEST cadence (straight to the cap, no escalation)
                self.backoff.forget(key)
                delay = RETRY_CAP_SECONDS
            else:
                delay = self.backoff.next_delay(key)
            self._singleton_next[s.name] = self.clock.now() + delay
            log.error("singleton reconcile failed", controller=s.name,
                      retry_in=delay, error=f"{type(exc).__name__}: {exc}")
        else:
            self._singleton_next.pop(s.name, None)
            self.backoff.forget((s.name, "__singleton__"))

    def tick(self) -> None:
        """Run every singleton once, then drain the fallout."""
        for s in self.singletons:
            self._run_singleton(s)
            self.drain()

    def run_until_quiet(self, max_rounds: int = 16) -> bool:
        """Drain + tick until no controller produces new work, for tests and
        the simulated operator loop. Returns True when the system quiesced,
        False on livelock (still producing work after max_rounds) — test
        callers assert the return so livelock regressions fail loudly."""
        for _ in range(max_rounds):
            moved = self.drain()
            for s in self.singletons:
                self._run_singleton(s)
            moved += self.drain()
            if moved == 0:
                return True
        log.warning("manager did not quiesce", max_rounds=max_rounds)
        return False

    def next_timer_at(self) -> Optional[float]:
        """Earliest LIVE requeue-timer fire time (None when no timer is
        armed). The fleet simulator's adaptive stepping asks this before
        each clock jump so an accelerated advance never overshoots a
        controller's scheduled recheck — eviction backoffs, liveness TTLs,
        kubelet ready delays all fire at their exact simulated instant."""
        # every deferred intent re-arms only after its key's LIVE timer
        # fires (and is never earlier than it), so the pending map alone
        # carries the earliest fire time
        pending = self._timer_pending.values()
        return min(pending) if pending else None

    def advance(self, seconds: float) -> None:
        """Step a FakeClock and fire due timers (test helper)."""
        step = getattr(self.clock, "step", None)
        if step is None:
            raise TypeError("advance() needs a FakeClock")
        step(seconds)
        self._fire_due_timers()
        self.drain()
