"""Controller manager: the runtime that drives all reconcilers.

The reference uses controller-runtime (operator.go:105-206): watch-driven
per-object reconcilers plus singleton controllers (provisioner, disruption)
on their own loops. This manager reproduces that model on a deterministic
single dispatch queue:

- watch controllers subscribe to object kinds; store events enqueue
  (controller, object-ref) work items, deduped the way controller-runtime's
  workqueue dedupes;
- singleton controllers run on tick() — the test harness calls them
  explicitly (the reference's ExpectSingletonReconciled), the operator loop
  calls them on their poll cadence;
- requeue-after is honored via the injected clock, so fake clocks drive
  time-based reconciles in tests exactly like the reference's fake
  clock.Clock.

Determinism over parallelism is intentional: the reference needs 1000-way
reconcile concurrency because each reconcile blocks on API round-trips
(lifecycle/controller.go:102); here store ops are in-memory and the heavy
math lives in batched device programs, so a single dispatch loop keeps
ordering reproducible without sacrificing throughput.
"""

from __future__ import annotations

import heapq
import itertools
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..kube.store import DELETED, Event, Store
from ..logging import get_logger
from ..utils.clock import Clock
from ..utils.injection import with_controller

log = get_logger("manager")


class Result:
    """Reconcile result: optional requeue delay in seconds."""

    def __init__(self, requeue_after: Optional[float] = None):
        self.requeue_after = requeue_after


class Controller:
    """Watch-driven reconciler. Subclasses set `kinds` and implement
    reconcile(obj) -> Optional[Result]."""

    name: str = "controller"
    kinds: tuple = ()

    def reconcile(self, obj) -> Optional[Result]:
        raise NotImplementedError

    def interested(self, ev: Event) -> bool:
        """Event filter; default = any event for a watched kind."""
        return True


class SingletonController:
    """Poll-loop reconciler (provisioner, disruption). reconcile() returns an
    optional Result whose requeue_after sets the next poll delay."""

    name: str = "singleton"

    def reconcile(self) -> Optional[Result]:
        raise NotImplementedError


class Manager:
    def __init__(self, store: Store, clock: Optional[Clock] = None):
        self.store = store
        self.clock = clock or store.clock
        self.controllers: List[Controller] = []
        self.singletons: List[SingletonController] = []
        self._queue: Deque[Tuple[Controller, object]] = deque()
        self._queued: set = set()
        self._timers: list = []  # heap of (fire_at, seq, controller, obj)
        self._timer_seq = itertools.count()
        # AddAfter dedup, bounded per (controller, object): one LIVE heap
        # entry (the earliest fire time) plus at most one DEFERRED later
        # intent — the LATEST requested fire time — re-armed when the live
        # timer fires. client-go's delaying queue keeps a single entry per
        # item and only moves it earlier — but silently dropping a later
        # requeue loses a controller's periodic recheck when the earlier
        # reconcile returns no requeue (ADVICE r3); keeping the latest
        # intent preserves the final recheck (intermediate intents are
        # subsumed by the earlier fire's reconcile) while still preventing
        # per-event perpetual timer chains
        self._timer_pending: Dict[tuple, float] = {}
        self._timer_deferred: Dict[tuple, tuple] = {}  # key -> (fire_at, c, obj)
        store.watch(self._on_event)

    # -- registration -------------------------------------------------------

    def register(self, *controllers) -> "Manager":
        for c in controllers:
            if isinstance(c, SingletonController):
                self.singletons.append(c)
            else:
                self.controllers.append(c)
        return self

    # -- event plumbing -----------------------------------------------------

    def _on_event(self, ev: Event) -> None:
        for c in self.controllers:
            if ev.kind in c.kinds and c.interested(ev):
                self._enqueue(c, ev.obj)

    def _enqueue(self, controller: Controller, obj) -> None:
        key = (controller.name, type(obj).__name__,
               obj.metadata.namespace, obj.metadata.name)
        if key in self._queued:
            return
        self._queued.add(key)
        self._queue.append((controller, obj))

    def requeue(self, controller: Controller, obj, after: float) -> None:
        key = (controller.name, type(obj).__name__,
               obj.metadata.namespace, obj.metadata.name)
        fire_at = self.clock.now() + after
        pending = self._timer_pending.get(key)
        if pending is not None:
            if fire_at >= pending:
                # keep the LATEST intent to re-arm after the live timer
                # fires: earlier intermediate intents are subsumed by the
                # live timer's reconcile (which sees newer state and re-arms
                # as needed), but the final periodic recheck must survive
                if fire_at > pending:
                    deferred = self._timer_deferred.get(key)
                    if deferred is None or fire_at > deferred[0]:
                        self._timer_deferred[key] = (fire_at, controller, obj)
                return
            # earlier than the live timer: move it up (old entry goes stale);
            # the displaced time stays pending as the deferred later intent
            deferred = self._timer_deferred.get(key)
            if deferred is None or pending > deferred[0]:
                self._timer_deferred[key] = (pending, controller, obj)
        self._timer_pending[key] = fire_at
        heapq.heappush(self._timers,
                       (fire_at, next(self._timer_seq), controller, obj))

    # -- dispatch -----------------------------------------------------------

    def _fire_due_timers(self) -> None:
        now = self.clock.now()
        while self._timers and self._timers[0][0] <= now:
            fire_at, _, c, obj = heapq.heappop(self._timers)
            key = (c.name, type(obj).__name__,
                   obj.metadata.namespace, obj.metadata.name)
            if self._timer_pending.get(key) != fire_at:
                continue  # superseded by an earlier requeue; stale heap entry
            del self._timer_pending[key]
            deferred = self._timer_deferred.pop(key, None)
            if deferred is not None:
                d_at, d_c, d_obj = deferred
                self._timer_pending[key] = d_at
                heapq.heappush(self._timers,
                               (d_at, next(self._timer_seq), d_c, d_obj))
            self._enqueue(c, obj)

    def drain(self, max_items: int = 100_000) -> int:
        """Dispatch queued work until quiet. Returns items processed."""
        n = 0
        self._fire_due_timers()
        while self._queue and n < max_items:
            controller, obj = self._queue.popleft()
            self._queued.discard((controller.name, type(obj).__name__,
                                  obj.metadata.namespace, obj.metadata.name))
            # re-fetch: reconcile the current state, not the event snapshot
            live = self.store.get(type(obj), obj.metadata.name,
                                  obj.metadata.namespace)
            target = live if live is not None else obj
            with with_controller(controller.name):
                result = controller.reconcile(target)
            if result is not None and result.requeue_after is not None:
                self.requeue(controller, target, result.requeue_after)
            n += 1
            self._fire_due_timers()
        return n

    def tick(self) -> None:
        """Run every singleton once, then drain the fallout."""
        for s in self.singletons:
            with with_controller(s.name):
                s.reconcile()
            self.drain()

    def run_until_quiet(self, max_rounds: int = 16) -> None:
        """Drain + tick until no controller produces new work, for tests and
        the simulated operator loop."""
        for _ in range(max_rounds):
            moved = self.drain()
            for s in self.singletons:
                with with_controller(s.name):
                    s.reconcile()
            moved += self.drain()
            if moved == 0:
                return
        log.warning("manager did not quiesce", max_rounds=max_rounds)

    def advance(self, seconds: float) -> None:
        """Step a FakeClock and fire due timers (test helper)."""
        step = getattr(self.clock, "step", None)
        if step is None:
            raise TypeError("advance() needs a FakeClock")
        step(seconds)
        self._fire_due_timers()
        self.drain()
