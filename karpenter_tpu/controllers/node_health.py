"""Node auto-repair (feature-gated).

Mirrors /root/reference/pkg/controllers/node/health/controller.go:74-203:
match cloudprovider RepairPolicies against node conditions, force-delete
unhealthy nodes once the toleration elapses, and trip a circuit breaker when
more than 20% of the cluster is unhealthy.
"""

from __future__ import annotations

from typing import Optional

from ..api.objects import Node
from ..kube.store import Store
from ..state.cluster import Cluster
from ..utils import node as node_utils
from ..utils.clock import Clock
from .manager import Controller, Result

UNHEALTHY_CLUSTER_THRESHOLD = 0.2  # health/controller.go circuit breaker


class NodeHealth(Controller):
    name = "node.health"
    kinds = (Node,)

    def __init__(self, store: Store, cluster: Cluster, cloud_provider,
                 clock: Optional[Clock] = None, recorder=None):
        from ..events.recorder import Recorder
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock or store.clock
        self.recorder = recorder or Recorder(self.clock)

    def reconcile(self, node: Node) -> Optional[Result]:
        if node.metadata.deletion_timestamp is not None:
            return None
        policies = self.cloud_provider.repair_policies()
        if not policies:
            return None
        matched = None
        for p in policies:
            cond = node_utils.get_condition(node, p.condition_type)
            if cond is not None and cond[0] == p.condition_status:
                matched = (p, cond[1])
                break
        if matched is None:
            return None
        policy, since = matched
        elapsed = self.clock.now() - since
        if elapsed < policy.toleration_duration:
            return Result(requeue_after=policy.toleration_duration - elapsed)
        from ..api.nodeclaim import NodeClaim
        nc = next((c for c in self.store.list(NodeClaim)
                   if c.status.node_name == node.name), None)
        if self._circuit_broken():
            # controller.go:207-210: tell the operator WHY repair stalled
            from ..events import catalog as events_catalog
            self.recorder.publish(*events_catalog.node_repair_blocked(
                node.name, nc.name if nc is not None else "",
                "more than 20% nodes are unhealthy in the cluster"))
            return Result(requeue_after=60.0)
        # delete the backing claim (controller.go:121-126); bare nodes delete
        # directly
        if nc is not None:
            if nc.metadata.deletion_timestamp is None:
                self.store.delete(nc)
        else:
            self.store.delete(node)
        return None

    def _circuit_broken(self) -> bool:
        """Unhealthy count above ceil(20% of nodes) blocks repair
        (controller.go:168-201: up to 20%, rounded up, may be unhealthy)."""
        import math
        nodes = self.store.list(Node)
        if not nodes:
            return False
        policies = self.cloud_provider.repair_policies()
        unhealthy = 0
        for n in nodes:
            for p in policies:
                cond = node_utils.get_condition(n, p.condition_type)
                if cond is not None and cond[0] == p.condition_status:
                    unhealthy += 1
                    break
        threshold = math.ceil(UNHEALTHY_CLUSTER_THRESHOLD * len(nodes))
        return unhealthy > threshold
