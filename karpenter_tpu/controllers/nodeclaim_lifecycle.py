"""NodeClaim lifecycle state machine: launch -> register -> initialize, with
liveness TTL and the termination finalizer flow.

Mirrors /root/reference/pkg/controllers/nodeclaim/lifecycle/:
- Launch (launch.go:45-121): cloudProvider.Create, Launched condition, status
  capacity/allocatable; insufficient-capacity errors delete the claim.
- Registration (registration.go:43-114): match the Node by providerID, sync
  labels/taints, drop the unregistered:NoExecute taint, stamp the registered
  label, record status.node_name.
- Initialization (initialization.go:47-136): node present with ephemeral +
  startup taints cleared and capacity registered -> initialized label +
  condition.
- Liveness (liveness.go:41-66): claims not registered within the TTL are
  deleted.
- Termination (controller.go:171-285): on deletionTimestamp, delete the cloud
  instance, delete the Node, then drop the finalizer.
"""

from __future__ import annotations

from typing import Optional

from ..api import labels as api_labels
from ..api.nodeclaim import (COND_INITIALIZED, COND_LAUNCHED, COND_REGISTERED,
                             NodeClaim)
from ..api.objects import Node
from ..cloudprovider.types import (CloudProviderError, InsufficientCapacityError,
                                   NodeClaimNotFoundError)
from ..kube.store import NotFoundError, Store
from ..logging import get_logger
from ..scheduling.taints import KNOWN_EPHEMERAL_TAINTS
from ..state.cluster import Cluster
from ..utils.clock import Clock
from .manager import Controller, Result

REGISTRATION_TTL_SECONDS = 15 * 60  # liveness.go registrationTTL
LAUNCH_RETRY_SECONDS = 15.0

log = get_logger("nodeclaim.lifecycle")


class NodeClaimLifecycle(Controller):
    name = "nodeclaim.lifecycle"
    kinds = (NodeClaim,)

    def __init__(self, store: Store, cluster: Cluster, cloud_provider,
                 clock: Optional[Clock] = None,
                 registration_ttl: float = REGISTRATION_TTL_SECONDS,
                 recorder=None, unavailable=None, trigger=None):
        from ..events.recorder import Recorder
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock or store.clock
        self.recorder = recorder or Recorder(self.clock)
        self.registration_ttl = registration_ttl
        # UnavailableOfferings registry: ICE launch failures record their
        # exhausted offering keys here so the next solve routes around them
        self.unavailable = unavailable
        # provisioner.trigger: an ICE-deleted claim is pre-registration (no
        # Node exists), so NodeDeletionTrigger can never fire for it — the
        # stranded pods must re-provision NOW, not on the next unrelated
        # batch window
        self.trigger = trigger

    def reconcile(self, nc: NodeClaim) -> Optional[Result]:
        if self.store.get(NodeClaim, nc.metadata.name,
                          nc.metadata.namespace) is None:
            # already fully deleted (finalizer dropped); the manager hands us
            # the stale event snapshot — controller-runtime's NotFound->ignore
            return None
        if nc.metadata.deletion_timestamp is not None:
            return self._finalize(nc)
        if api_labels.TERMINATION_FINALIZER not in nc.metadata.finalizers:
            nc.metadata.finalizers.append(api_labels.TERMINATION_FINALIZER)
            self.store.update(nc)
        if not nc.launched():
            r = self._launch(nc)
            if r is not None:
                return r
        if not nc.registered():
            self._register(nc)
        if not nc.registered():
            return self._liveness(nc)
        if not nc.initialized():
            self._initialize(nc)
            if not nc.initialized():
                return Result(requeue_after=5.0)
        return None

    # -- launch -------------------------------------------------------------

    def _launch(self, nc: NodeClaim) -> Optional[Result]:
        try:
            self.cloud_provider.create(nc)
        except InsufficientCapacityError as e:
            # launch.go:78-86: ICE deletes the claim so the provisioner
            # retries — but first the exhausted offering keys feed the
            # registry (escalating TTL per repeated key) so the retry
            # solves AROUND the drought instead of re-picking it
            from ..events import catalog as events_catalog
            keys = getattr(e, "offerings", ())
            if self.unavailable is not None:
                for it_name, zone, capacity_type in keys:
                    ttl = self.unavailable.mark(
                        it_name, zone, capacity_type,
                        reason="insufficient_capacity")
                    log.warning("offering marked unavailable",
                                instance_type=it_name, zone=zone,
                                capacity_type=capacity_type, ttl=ttl)
            log.warning("insufficient capacity, deleting nodeclaim",
                        nodeclaim=nc.name, error=str(e))
            self.recorder.publish(
                events_catalog.insufficient_capacity(nc, str(e)))
            self.store.delete(nc)
            if self.trigger is not None:
                self.trigger()
            return Result()
        except CloudProviderError as e:
            log.error("launching nodeclaim failed", nodeclaim=nc.name,
                      error=str(e))
            # only write when the condition actually flips: an unconditional
            # status update fires a watch event that re-reconciles this very
            # claim immediately, turning the requeue_after backoff into a
            # hot retry storm
            prev = nc.conditions.get(COND_LAUNCHED)
            if prev is None or prev.status != "False" or \
                    prev.message != str(e):
                nc.conditions.set_false(COND_LAUNCHED, reason="LaunchFailed",
                                        message=str(e), now=self.clock.now())
                self.store.update(nc)
            return Result(requeue_after=LAUNCH_RETRY_SECONDS)
        log.info("launched nodeclaim", nodeclaim=nc.name,
                 nodepool=nc.nodepool_name,
                 provider_id=nc.status.provider_id)
        nc.conditions.set_true(COND_LAUNCHED, reason="Launched",
                               now=self.clock.now())
        self.store.update(nc)
        self.cluster.update_nodeclaim(nc)
        return None

    # -- registration -------------------------------------------------------

    def _node_for(self, nc: NodeClaim) -> Optional[Node]:
        pid = nc.status.provider_id
        if not pid:
            return None
        for node in self.store.list(Node):
            if node.spec.provider_id == pid:
                return node
        return None

    def _register(self, nc: NodeClaim) -> None:
        from ..api.objects import OwnerReference
        node = self._node_for(nc)
        if node is None:
            return
        # invariant (registration.go:55-61): a Karpenter-managed node must
        # come up with the unregistered NoExecute taint — workloads would
        # otherwise race onto it before its labels/taints are synced. A node
        # missing both the taint and the registered label fails registration.
        has_unregistered = any(t.key == api_labels.UNREGISTERED_TAINT_KEY
                               for t in node.spec.taints)
        if not has_unregistered and \
                api_labels.NODE_REGISTERED_LABEL_KEY not in node.metadata.labels:
            prev = nc.conditions.get(COND_REGISTERED)
            # update only on transition — an unconditional write would fire
            # a watch event that re-reconciles this claim in a hot loop
            if prev is None or prev.status != "False" or \
                    prev.reason != "UnregisteredTaintNotFound":
                nc.conditions.set_false(
                    COND_REGISTERED, reason="UnregisteredTaintNotFound",
                    message=(f"invariant violated, "
                             f"{api_labels.UNREGISTERED_TAINT_KEY} taint must "
                             "be present on Karpenter-managed nodes"),
                    now=self.clock.now())
                self.store.update(nc)
            return
        # sync: claim labels/annotations/taints win (registration.go:74-101);
        # startup taints sync only HERE — their later removal by the workload
        # must not be undone by a re-sync
        node.metadata.labels.update(nc.metadata.labels)
        node.metadata.labels[api_labels.NODE_REGISTERED_LABEL_KEY] = "true"
        node.metadata.annotations.update(nc.metadata.annotations)
        from ..scheduling.taints import merge as merge_taints
        node.spec.taints = [
            t for t in merge_taints(node.spec.taints,
                                    list(nc.spec.taints)
                                    + list(nc.spec.startup_taints))
            if t.key != api_labels.UNREGISTERED_TAINT_KEY]
        if not any(r.kind == "NodeClaim" for r in node.metadata.owner_refs):
            node.metadata.owner_refs.append(OwnerReference(
                kind="NodeClaim", name=nc.name, uid=nc.uid,
                block_owner_deletion=True))
        if api_labels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(api_labels.TERMINATION_FINALIZER)
        self.store.update(node)
        nc.status.node_name = node.name
        log.info("registered nodeclaim", nodeclaim=nc.name, node=node.name)
        nc.conditions.set_true(COND_REGISTERED, reason="Registered",
                               now=self.clock.now())
        self.store.update(nc)
        from ..metrics import registry as metrics
        metrics.NODES_CREATED.inc({"nodepool": nc.nodepool_name})

    # -- initialization -----------------------------------------------------

    def _initialize(self, nc: NodeClaim) -> None:
        from ..utils import node as node_utils
        node = self._node_for(nc)
        if node is None:
            return
        # a NotReady kubelet blocks initialization (initialization.go:75-80);
        # absent Ready condition = simulated node, treated healthy
        ready = node_utils.get_condition(node, "Ready")
        if ready is not None and ready[0] != "True":
            return
        startup = list(nc.spec.startup_taints)
        for t in node.spec.taints:
            if any(t.matches(e) for e in KNOWN_EPHEMERAL_TAINTS):
                return  # still starting up
            if any(t.matches(s) for s in startup):
                return
        # resources registered (initialization.go:103-121)
        for rname, req in nc.status.allocatable.items():
            if node.status.allocatable.get(rname, 0) <= 0 < req:
                return
        node.metadata.labels[api_labels.NODE_INITIALIZED_LABEL_KEY] = "true"
        self.store.update(node)
        log.info("initialized nodeclaim", nodeclaim=nc.name, node=node.name)
        nc.conditions.set_true(COND_INITIALIZED, reason="Initialized",
                               now=self.clock.now())
        self.store.update(nc)

    # -- liveness -----------------------------------------------------------

    def _liveness(self, nc: NodeClaim) -> Optional[Result]:
        age = self.clock.now() - nc.metadata.creation_timestamp
        if age >= self.registration_ttl:
            from ..events import catalog as events_catalog
            from ..metrics import registry as metrics
            log.warning("nodeclaim not registered within TTL, deleting",
                        nodeclaim=nc.name, ttl=self.registration_ttl)
            # observable, not silent: registration droughts show up as a
            # warning event + counter, not just vanishing claims
            self.recorder.publish(
                events_catalog.registration_timeout(nc, self.registration_ttl))
            metrics.NODECLAIMS_LIVENESS_TERMINATED.inc(
                {"nodepool": nc.nodepool_name})
            self.store.delete(nc)  # liveness.go:55-62
            return Result()
        return Result(requeue_after=self.registration_ttl - age)

    # -- termination --------------------------------------------------------

    def _finalize(self, nc: NodeClaim) -> Optional[Result]:
        node = self._node_for(nc)
        if node is not None and node.metadata.deletion_timestamp is None:
            self.store.delete(node)
            return Result(requeue_after=1.0)
        if node is not None:
            # node termination controller is still draining
            return Result(requeue_after=1.0)
        try:
            self.cloud_provider.delete(nc)
        except NodeClaimNotFoundError:
            pass
        from ..metrics import registry as metrics
        metrics.NODECLAIMS_TERMINATED.inc({"nodepool": nc.nodepool_name})
        self.store.remove_finalizer(nc, api_labels.TERMINATION_FINALIZER)
        return None
