"""NodePool controllers: hash maintenance, resource counting, readiness,
validation.

Mirrors /root/reference/pkg/controllers/nodepool/{hash,counter,readiness,
validation}/.
"""

from __future__ import annotations

from typing import Optional

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim
from ..api.nodepool import NODEPOOL_HASH_VERSION, NodePool
from ..kube.store import Store
from ..metrics import registry as metrics
from ..state.cluster import Cluster
from ..utils import resources as res
from ..utils.clock import Clock
from .manager import Controller, Result

COND_VALIDATION_SUCCEEDED = "ValidationSucceeded"
COND_NODECLASS_READY = "NodeClassReady"


class NodePoolHash(Controller):
    """hash/controller.go:54-118: keep the static-drift hash annotation
    current on the pool and backfill claims across hash-version bumps."""

    name = "nodepool.hash"
    kinds = (NodePool,)

    def __init__(self, store: Store):
        self.store = store

    def reconcile(self, pool: NodePool) -> Optional[Result]:
        h = pool.static_hash()
        ann = pool.metadata.annotations
        if ann.get(api_labels.NODEPOOL_HASH_ANNOTATION_KEY) != h or \
                ann.get(api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY) != \
                NODEPOOL_HASH_VERSION:
            ann[api_labels.NODEPOOL_HASH_ANNOTATION_KEY] = h
            ann[api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = \
                NODEPOOL_HASH_VERSION
            self.store.update(pool)
        # version-bump backfill: claims at an older hash version adopt the
        # pool's current hash instead of being treated as drifted
        for nc in self.store.list(NodeClaim):
            if nc.nodepool_name != pool.name:
                continue
            nc_ann = nc.metadata.annotations
            if nc_ann.get(api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY) != \
                    NODEPOOL_HASH_VERSION:
                nc_ann[api_labels.NODEPOOL_HASH_ANNOTATION_KEY] = h
                nc_ann[api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = \
                    NODEPOOL_HASH_VERSION
                self.store.update(nc)
        return None


class NodePoolCounter(Controller):
    """counter/controller.go:69-113: aggregate in-use resources of the pool's
    nodes into NodePool.status.resources (+ usage/limit gauges)."""

    name = "nodepool.counter"
    kinds = (NodePool, NodeClaim)

    def __init__(self, store: Store, cluster: Cluster):
        self.store = store
        self.cluster = cluster

    def reconcile(self, obj) -> Optional[Result]:
        pools = ([obj] if isinstance(obj, NodePool)
                 else self.store.list(NodePool))
        for pool in pools:
            total: dict = {}
            count = 0
            for sn in self.cluster.state_nodes(deep_copy=False):
                if sn.nodepool_name() != pool.name or sn.deleting():
                    continue
                total = res.merge(total, sn.capacity())
                count += 1
            total["nodes"] = count * 1000  # milliunit convention
            if pool.status.resources != total:
                pool.status.resources = total
                self.store.update(pool)
            for rname, v in total.items():
                metrics.NODEPOOL_USAGE.set(
                    v, {"nodepool": pool.name, "resource_type": rname})
            for rname, v in pool.spec.limits.items():
                metrics.NODEPOOL_LIMIT.set(
                    v, {"nodepool": pool.name, "resource_type": rname})
        return None


class NodePoolValidation(Controller):
    """validation/controller.go:51-76: runtime validation -> condition."""

    name = "nodepool.validation"
    kinds = (NodePool,)

    def __init__(self, store: Store):
        self.store = store

    def reconcile(self, pool: NodePool) -> Optional[Result]:
        from ..api.validation import validate_nodeclaim_template_spec
        errs = []
        for b in pool.spec.disruption.budgets:
            v = b.nodes.strip()
            if v.endswith("%"):
                v = v[:-1]
            if not v.isdigit():
                errs.append(f"invalid budget nodes {b.nodes!r}")
        # the webhook battery (nodeclaim_validation.go:62-151): operators,
        # restricted labels, qualified names, minValues, Gt/Lt, taint shape
        errs.extend(validate_nodeclaim_template_spec(pool.spec.template.spec))
        status = "False" if errs else "True"
        self._set_condition(pool, COND_VALIDATION_SUCCEEDED, status,
                            "; ".join(errs))
        return None

    def _set_condition(self, pool: NodePool, ctype: str, status: str,
                       message: str = "") -> None:
        for c in pool.status.conditions:
            if c.get("type") == ctype:
                # message alone can change (e.g. one of several validation
                # errors fixed while others remain) — stale text misleads
                if c.get("status") != status or c.get("message") != message:
                    c["status"] = status
                    c["message"] = message
                    self.store.update(pool)
                return
        pool.status.conditions.append(
            {"type": ctype, "status": status, "message": message})
        self.store.update(pool)


class NodePoolReadiness(NodePoolValidation):
    """readiness/controller.go:54-103: NodePool Ready from NodeClass
    readiness. Without a NodeClass CRD system, a pool referencing no class is
    ready; one naming a class is ready when the provider says so."""

    name = "nodepool.readiness"
    kinds = (NodePool,)

    def __init__(self, store: Store, cloud_provider=None):
        super().__init__(store)
        self.cloud_provider = cloud_provider

    def reconcile(self, pool: NodePool) -> Optional[Result]:
        ready = True
        ref = pool.spec.template.spec.node_class_ref
        checker = getattr(self.cloud_provider, "node_class_ready", None)
        if ref.name and checker is not None:
            ready = bool(checker(ref))
        self._set_condition(pool, "Ready", "True" if ready else "False")
        return None
