"""Metric-exporter controllers for pods and nodes.

Mirrors /root/reference/pkg/controllers/metrics/{pod,node}/: pod phase
gauges and scheduling latency histograms (pod/controller.go:208-404), node
allocatable/used utilization gauges (node/controller.go:162-260). The
nodepool exporter lives in nodepool_aux.NodePoolCounter.
"""

from __future__ import annotations

from typing import Optional

from ..api import labels as api_labels
from ..api.objects import Node, Pod
from ..kube.store import Store
from ..metrics.registry import REGISTRY
from ..state.cluster import Cluster
from ..utils.clock import Clock
from .manager import Controller, Result

POD_STATE = REGISTRY.gauge(
    "karpenter_pods_state", "Pod count by phase/binding",
    ("phase", "scheduled"))
POD_SCHEDULING_DECISION = REGISTRY.histogram(
    "karpenter_pods_provisioning_scheduling_decision_duration_seconds",
    "Time from pod ack to scheduling decision")
POD_BOUND_DURATION = REGISTRY.histogram(
    "karpenter_pods_bound_duration_seconds",
    "Time from pod creation to binding")
NODE_ALLOCATABLE = REGISTRY.gauge(
    "karpenter_nodes_allocatable", "Node allocatable per resource",
    ("node_name", "nodepool", "resource_type"))
NODE_USED = REGISTRY.gauge(
    "karpenter_nodes_total_pod_requests", "Requested resources per node",
    ("node_name", "nodepool", "resource_type"))


class PodMetrics(Controller):
    name = "metrics.pod"
    kinds = (Pod,)

    def __init__(self, store: Store, cluster: Cluster,
                 clock: Optional[Clock] = None):
        self.store = store
        self.cluster = cluster
        self.clock = clock or store.clock
        self._bound_seen: set = set()

    def reconcile(self, pod: Pod) -> Optional[Result]:
        self._refresh_state_gauge()
        key = f"{pod.namespace}/{pod.name}"
        if pod.spec.node_name and pod.uid not in self._bound_seen:
            self._bound_seen.add(pod.uid)
            POD_BOUND_DURATION.observe(
                self.clock.now() - pod.metadata.creation_timestamp)
            decided = self.cluster.pod_scheduling_decisions.get(key)
            acked = self.cluster.pod_acks.get(key)
            if decided is not None and acked is not None:
                POD_SCHEDULING_DECISION.observe(max(0.0, decided - acked))
        return None

    def _refresh_state_gauge(self) -> None:
        counts: dict = {}
        for p in self.store.list(Pod):
            k = (p.status.phase, str(bool(p.spec.node_name)).lower())
            counts[k] = counts.get(k, 0) + 1
        for (phase, scheduled), n in counts.items():
            POD_STATE.set(n, {"phase": phase, "scheduled": scheduled})
        # combos that emptied out are deleted, not left at their last value
        # (metrics/pod suite: the state metric disappears with the pod);
        # pruning against the gauge's own series also clears leftovers from
        # a previous controller instance on the shared registry object
        POD_STATE.prune([{"phase": p, "scheduled": s} for p, s in counts])


class NodeMetrics(Controller):
    name = "metrics.node"
    kinds = (Node, Pod)

    def __init__(self, store: Store, cluster: Cluster):
        self.store = store
        self.cluster = cluster

    def reconcile(self, obj) -> Optional[Result]:
        alloc_live: list = []
        used_live: list = []
        for sn in self.cluster.state_nodes(deep_copy=False):
            labels = {"node_name": sn.name(),
                      "nodepool": sn.nodepool_name()}
            for rname, v in sn.allocatable().items():
                series = {**labels, "resource_type": rname}
                NODE_ALLOCATABLE.set(v, series)
                alloc_live.append(series)
            for rname, v in sn.pod_request_total().items():
                series = {**labels, "resource_type": rname}
                NODE_USED.set(v, series)
                used_live.append(series)
        # deleted/consolidated nodes' series go away with them
        NODE_ALLOCATABLE.prune(alloc_live)
        NODE_USED.prune(used_live)
        return None
