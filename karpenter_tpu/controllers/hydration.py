"""Hydration: backfill labels/fields on objects created by older versions.

Mirrors /root/reference/pkg/controllers/{nodeclaim,node}/hydration/: objects
from before a label/scheme change get the current fields stamped so the rest
of the controllers can assume the invariants (e.g. every managed node
carries the nodepool label and the termination finalizer).
"""

from __future__ import annotations

from typing import Optional

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim
from ..api.objects import Node
from ..kube.store import Store
from .manager import Controller, Result


class NodeClaimHydration(Controller):
    name = "nodeclaim.hydration"
    kinds = (NodeClaim,)

    def __init__(self, store: Store):
        self.store = store

    def reconcile(self, nc: NodeClaim) -> Optional[Result]:
        if nc.metadata.deletion_timestamp is not None:
            return None
        changed = False
        pool = nc.metadata.labels.get(api_labels.NODEPOOL_LABEL_KEY)
        for ref in nc.metadata.owner_refs:
            if ref.kind == "NodePool" and not pool:
                nc.metadata.labels[api_labels.NODEPOOL_LABEL_KEY] = ref.name
                changed = True
        if api_labels.TERMINATION_FINALIZER not in nc.metadata.finalizers:
            nc.metadata.finalizers.append(api_labels.TERMINATION_FINALIZER)
            changed = True
        if changed:
            self.store.update(nc)
        return None


class NodeHydration(Controller):
    name = "node.hydration"
    kinds = (Node,)

    def __init__(self, store: Store):
        self.store = store

    def reconcile(self, node: Node) -> Optional[Result]:
        if node.metadata.deletion_timestamp is not None:
            return None
        nc = next((c for c in self.store.list(NodeClaim)
                   if c.status.node_name == node.name
                   or (c.status.provider_id
                       and c.status.provider_id == node.spec.provider_id)),
                  None)
        if nc is None:
            return None
        changed = False
        for key in (api_labels.NODEPOOL_LABEL_KEY,
                    api_labels.CAPACITY_TYPE_LABEL_KEY):
            v = nc.metadata.labels.get(key)
            if v and key not in node.metadata.labels:
                node.metadata.labels[key] = v
                changed = True
        if changed:
            self.store.update(node)
        return None
