"""NodeClaim disruption markers: the status conditions the disruption solver
consumes.

Mirrors /root/reference/pkg/controllers/nodeclaim/disruption/:
- Consolidatable (consolidation.go:41-100): set once consolidateAfter has
  elapsed since the last pod event; cleared while pods churn.
- Drifted (drift.go:46-110): static drift via the nodepool-hash annotation
  diff, requirements drift via nodepool requirements vs claim labels, plus
  cloudProvider.IsDrifted.
"""

from __future__ import annotations

from typing import Optional

from ..api import labels as api_labels
from ..api.nodeclaim import (COND_CONSOLIDATABLE, COND_DRIFTED, COND_INITIALIZED,
                             COND_LAUNCHED, NodeClaim)
from ..api.nodepool import NodePool
from ..kube.store import Store
from ..scheduling.requirements import label_requirements, node_selector_requirements
from ..state.cluster import Cluster
from ..utils.clock import Clock
from .manager import Controller, Result

DRIFT_RECHECK_SECONDS = 300.0  # drift.go:68,76 — 5 min cache TTL


class NodeClaimDisruptionMarker(Controller):
    name = "nodeclaim.disruption"
    kinds = (NodeClaim,)

    def __init__(self, store: Store, cluster: Cluster, cloud_provider,
                 clock: Optional[Clock] = None):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.clock = clock or store.clock

    def reconcile(self, nc: NodeClaim) -> Optional[Result]:
        if nc.metadata.deletion_timestamp is not None:
            return None
        requeue = None
        if nc.initialized():
            requeue = self._consolidatable(nc)
        # Drift only needs Launched, not Initialized; an unlaunched claim
        # sheds any stale Drifted condition (drift.go:46-57)
        if not nc.conditions.is_true(COND_LAUNCHED):
            if nc.conditions.get(COND_DRIFTED) is not None:
                nc.conditions.clear(COND_DRIFTED)
                self.store.update(nc)
        else:
            self._drifted(nc)
        # drift inputs are external (catalog, cloud provider): re-check on a
        # timer even with no claim events (drift.go:68,76 — 5 min cache TTL)
        return Result(requeue_after=min(requeue or DRIFT_RECHECK_SECONDS,
                                        DRIFT_RECHECK_SECONDS))

    # -- Consolidatable -----------------------------------------------------

    def _consolidatable(self, nc: NodeClaim) -> Optional[float]:
        pool = self.store.get(NodePool, nc.nodepool_name)
        if pool is None:
            return None
        after = pool.spec.disruption.consolidate_after
        if after is None:  # Never
            if nc.conditions.is_true(COND_CONSOLIDATABLE):
                nc.conditions.clear(COND_CONSOLIDATABLE)
                self.store.update(nc)
            return None
        last_event = nc.status.last_pod_event_time or \
            nc.metadata.creation_timestamp
        elapsed = self.clock.now() - last_event
        if elapsed >= after:
            if not nc.conditions.is_true(COND_CONSOLIDATABLE):
                nc.conditions.set_true(COND_CONSOLIDATABLE,
                                       reason="PodsHaveSettled",
                                       now=self.clock.now())
                self.store.update(nc)
                self.cluster.mark_unconsolidated()
            return None
        if nc.conditions.is_true(COND_CONSOLIDATABLE):
            nc.conditions.clear(COND_CONSOLIDATABLE)
            self.store.update(nc)
        return after - elapsed

    # -- Drifted ------------------------------------------------------------

    def _drifted(self, nc: NodeClaim) -> None:
        pool = self.store.get(NodePool, nc.nodepool_name)
        if pool is None:
            return
        reason = self._static_drift(nc, pool) or \
            self._requirements_drift(nc, pool) or \
            self._instance_type_drift(nc, pool) or \
            self.cloud_provider.is_drifted(nc)
        if reason:
            if not nc.conditions.is_true(COND_DRIFTED):
                nc.conditions.set_true(COND_DRIFTED, reason=reason,
                                       now=self.clock.now())
                self.store.update(nc)
                self.cluster.mark_unconsolidated()
        elif nc.conditions.is_true(COND_DRIFTED):
            nc.conditions.clear(COND_DRIFTED)
            self.store.update(nc)

    def _static_drift(self, nc: NodeClaim, pool: NodePool) -> str:
        """drift.go NodePoolHash: annotation hash mismatch at same hash
        version."""
        nc_hash = nc.metadata.annotations.get(
            api_labels.NODEPOOL_HASH_ANNOTATION_KEY)
        nc_ver = nc.metadata.annotations.get(
            api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY)
        from ..api.nodepool import NODEPOOL_HASH_VERSION
        if nc_hash is None or nc_ver != NODEPOOL_HASH_VERSION:
            return ""
        return "NodePoolDrifted" if nc_hash != pool.static_hash() else ""

    def _instance_type_drift(self, nc: NodeClaim, pool: NodePool) -> str:
        """drift.go instanceTypeNotFound (:104-135): the claim's instance
        type — or any offering matching its zone/capacity-type labels, over
        the FULL offering list including temporarily-unavailable ones — no
        longer exists in the provider catalog."""
        it_name = nc.metadata.labels.get(api_labels.LABEL_INSTANCE_TYPE)
        if not it_name:
            return "InstanceTypeNotFound"
        its = self.cloud_provider.get_instance_types(pool)
        it = next((i for i in its if i.name == it_name), None)
        if it is None:
            return "InstanceTypeNotFound"
        if not it.offerings.has_compatible(
                label_requirements(nc.metadata.labels)):
            return "InstanceTypeNotFound"
        return ""

    def _requirements_drift(self, nc: NodeClaim, pool: NodePool) -> str:
        """drift.go RequirementsDrifted: pool requirements no longer admit the
        claim's labels."""
        pool_reqs = node_selector_requirements(
            pool.spec.template.spec.requirements)
        claim_reqs = label_requirements(nc.metadata.labels)
        # Compatible (not Intersects): a pool requirement on a key the claim
        # has no label for is drift too (drift.go:144-154)
        if claim_reqs.compatible(pool_reqs):
            return "RequirementsDrifted"
        return ""
