"""Node termination: finalizer-driven graceful drain.

Mirrors /root/reference/pkg/controllers/node/termination/: on
deletionTimestamp, delete owning NodeClaims (controller.go:178-188), taint
disrupted:NoSchedule (terminator.go:55-92), drain pods in priority groups —
noncritical non-daemonset first (terminator.go:119-138) — wait for
VolumeAttachments of drainable pods to detach unless past the termination
grace deadline (controller.go:141-150,190-240), then remove the finalizer
(controller.go:242-270).

Eviction runs through a per-pod exponential-backoff queue
(terminator/eviction.go:49-50,94: 100ms base / 10s cap): a PDB-blocked
eviction (the Eviction API's 429) backs that pod off instead of hammering
the budget every pass.

Standalone-runtime deviation: the reference evicts via the Eviction API and
relies on workload controllers (Deployments) to recreate pods, with the
kube-scheduler re-binding them. Here eviction of a reschedulable pod *unbinds*
it (clears spec.node_name), returning it to the provisionable pool the
Provisioner watches; non-reschedulable pods are deleted. Net behavior matches:
disrupted pods land on replacement capacity.
"""

from __future__ import annotations

from typing import List, Optional

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim
from ..api.objects import Node, Pod
from ..api.storage import PersistentVolumeClaim, VolumeAttachment
from ..events import catalog as events_catalog
from ..kube.store import Store
from ..logging import get_logger
from ..scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT
from ..state.cluster import Cluster
from ..utils import pod as pod_utils
from ..utils.backoff import ItemBackoff
from ..utils.clock import Clock
from .manager import Controller, Result

CRITICAL_PRIORITY = 2_000_000_000  # system-cluster-critical floor

EVICTION_BASE_DELAY = 0.1   # terminator/eviction.go:49
EVICTION_MAX_DELAY = 10.0   # terminator/eviction.go:50
DEFAULT_POD_GRACE_SECONDS = 30.0  # core/v1 terminationGracePeriodSeconds default

log = get_logger("node.termination")


def _fmt_time(ts: float) -> str:
    """RFC3339 rendering of a runtime timestamp for event messages."""
    from ..kube.k8s_codec import ts_to_k8s
    return ts_to_k8s(ts) or ""


class NodeTermination(Controller):
    name = "node.termination"
    kinds = (Node,)

    def __init__(self, store: Store, cluster: Cluster,
                 clock: Optional[Clock] = None, cloud_provider=None,
                 recorder=None):
        from ..events.recorder import Recorder
        self.store = store
        self.cluster = cluster
        self.clock = clock or store.clock
        self.recorder = recorder or Recorder(self.clock)
        # for the instance-already-gone shortcut; None skips the check
        self.cloud_provider = cloud_provider
        # pod key -> eviction backoff state (the eviction queue's rate
        # limiter); next_try gates when a blocked pod may be retried
        self._backoff = ItemBackoff(EVICTION_BASE_DELAY, EVICTION_MAX_DELAY)
        self._next_try: dict = {}

    def _node_ready(self, node: Node) -> bool:
        from ..utils import node as node_utils
        cond = node_utils.get_condition(node, "Ready")
        # absent Ready = simulated/condition-less node: treat as healthy so
        # the instance-gone shortcut NEVER skips the drain without explicit
        # NotReady evidence (consistent with nodeclaim_lifecycle._initialize)
        return cond is None or cond[0] == "True"

    def _release_pods(self, node: Node) -> None:
        """The node is going away without a drain (instance already gone):
        reschedulable pods unbind so the provisioner replaces their
        capacity; everything else is deleted (the reference leans on kube
        pod-GC + workload controllers here; this runtime has no analog)."""
        for p in self._pods_on(node):
            self._force_delete(p)

    def reconcile(self, node: Node) -> Optional[Result]:
        if node.metadata.deletion_timestamp is None:
            return None
        if api_labels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return None
        # delete owning NodeClaims so instance teardown starts in parallel
        owning = None
        for nc in self.store.list(NodeClaim):
            if nc.status.node_name == node.name:
                owning = nc
                if nc.metadata.deletion_timestamp is None:
                    self.store.delete(nc)
        # the cloud instance is already gone (manual delete, spot reclaim):
        # draining waits on evictions that can never make progress on a dead
        # kubelet — finalize immediately, UNLESS the node still reports
        # Ready (the kubelet is heartbeating, so the instance plainly
        # exists; trust the drain) (controller.go:151-176)
        if self.cloud_provider is not None and not self._node_ready(node):
            from ..cloudprovider.types import NodeClaimNotFoundError
            try:
                pid = node.spec.provider_id
                if pid:
                    self.cloud_provider.get(pid)
            except NodeClaimNotFoundError:
                log.info("instance already terminated; releasing node",
                         node=node.name)
                self._release_pods(node)
                self._record_terminated(node)
                self.store.remove_finalizer(
                    node, api_labels.TERMINATION_FINALIZER)
                return None
        self._taint(node)
        self._annotate_termination_time(node, owning)
        term_ts = self._termination_time(node)
        if term_ts is not None:
            # controller.go:272-280: surface the hard deadline every pass
            # (the recorder's dedupe collapses repeats)
            self.recorder.publish(events_catalog.node_tgp_expiring(
                node.name, _fmt_time(term_ts)))
            if owning is not None:
                self.recorder.publish(events_catalog.nodeclaim_tgp_expiring(
                    owning.name, _fmt_time(term_ts)))
        remaining = self._drain(node)
        if remaining:
            # controller.go:115-119: a drain pass that leaves pods behind is
            # a NodeDrainError -> FailedDraining warning
            self.recorder.publish(events_catalog.node_failed_to_drain(
                node.name, f"{remaining} pods are waiting to be evicted"))
            log.debug("draining node", node=node.name, pods_remaining=remaining)
            return Result(requeue_after=1.0)
        # drained: wait for volumes to detach unless past the TGP deadline
        # (controller.go:141-150)
        term_time = self._termination_time(node)
        if term_time is None or self.clock.now() < term_time:
            attached = self._attached_volumes(node)
            if attached:
                log.debug("waiting on volume detach", node=node.name,
                          volume_attachments=attached)
                return Result(requeue_after=1.0)
        log.info("terminated node", node=node.name)
        self._record_terminated(node)
        self.store.remove_finalizer(node, api_labels.TERMINATION_FINALIZER)
        return None

    def _record_terminated(self, node: Node) -> None:
        """termination/metrics.go:30-62: counter + drain-duration summary +
        node-lifetime histogram, all by nodepool."""
        from ..metrics import registry as metrics
        labels = {"nodepool": node.metadata.labels.get(
            api_labels.NODEPOOL_LABEL_KEY, "")}
        now = self.clock.now()
        metrics.NODES_TERMINATED.inc(labels)
        if node.metadata.deletion_timestamp is not None:
            metrics.NODE_TERMINATION_DURATION.observe(
                max(0.0, now - node.metadata.deletion_timestamp), labels)
        if node.metadata.creation_timestamp:
            metrics.NODE_LIFETIME_DURATION.observe(
                max(0.0, now - node.metadata.creation_timestamp), labels)

    def _annotate_termination_time(self, node: Node, nc) -> None:
        """controller.go: stamp the hard deadline from the claim's
        terminationGracePeriod so the drain can force-expire."""
        key = api_labels.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY
        if key in node.metadata.annotations:
            return
        tgp = nc.spec.termination_grace_period if nc is not None else None
        if tgp is not None:
            node.metadata.annotations[key] = str(
                node.metadata.deletion_timestamp + tgp)
            self.store.update(node)

    def _termination_time(self, node: Node) -> Optional[float]:
        raw = node.metadata.annotations.get(
            api_labels.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY)
        return float(raw) if raw else None

    def _taint(self, node: Node) -> None:
        if not any(t.matches(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints):
            node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
            self.store.update(node)

    def _pods_on(self, node: Node) -> List[Pod]:
        return self.store.list(Pod, predicate=lambda p: p.spec.node_name == node.name)

    def _drain(self, node: Node) -> int:
        """Evict in priority groups; returns evictable pods still bound.

        PDB-blocked and do-not-disrupt pods are retried with per-pod
        exponential backoff (the Eviction API's 429 path,
        terminator/eviction.go) until the TerminationGracePeriod deadline,
        after which everything is force-deleted (terminator.go:140-177)."""
        now = self.clock.now()
        term_time = self._termination_time(node)
        expired = term_time is not None and now >= term_time

        # kubelet-sim: a pod already terminating finishes dying once its own
        # grace period elapses (nothing else removes it in the standalone
        # runtime; with a real kubelet this is its SIGKILL)
        for p in self._pods_on(node):
            if p.metadata.deletion_timestamp is not None:
                grace = p.spec.termination_grace_period_seconds
                grace = DEFAULT_POD_GRACE_SECONDS if grace is None else grace
                # the node's terminationGracePeriod is a HARD deadline: past
                # it, even a long pod grace is cut short (terminator.go
                # :140-177 force-deletes everything after expiry)
                if expired or now >= p.metadata.deletion_timestamp + grace:
                    self.store.delete(p)

        pods = [p for p in self._pods_on(node) if self._drainable(p)]

        # TGP preemptive deletes: pods whose own grace period no longer fits
        # before the node deadline start terminating immediately
        if term_time is not None and not expired:
            for p in list(pods):
                grace = p.spec.termination_grace_period_seconds or 0
                if now + grace >= term_time:
                    # terminator.go:140-157: proactive delete with clamped
                    # grace, bypassing PDB + do-not-disrupt
                    self.recorder.publish(events_catalog.disrupt_pod_delete(
                        p, int(max(0.0, term_time - now)),
                        _fmt_time(term_time)))
                    self._force_delete(p)
                    pods.remove(p)

        from ..utils.pdb import Limits
        from ..api.policy import PodDisruptionBudget
        limits = Limits(self.store.list(PodDisruptionBudget),
                        self.store.list(Pod))
        groups = ([p for p in pods if not self._critical(p) and not p.is_daemonset_pod],
                  [p for p in pods if not self._critical(p) and p.is_daemonset_pod],
                  [p for p in pods if self._critical(p) and not p.is_daemonset_pod],
                  [p for p in pods if self._critical(p) and p.is_daemonset_pod])
        for group in groups:
            if not group:
                continue
            for p in group:
                if expired:
                    self._force_delete(p)
                    continue
                key = (p.namespace, p.name, p.uid)
                if not pod_utils.is_disruptable(p):
                    continue  # do-not-disrupt: wait for the TGP deadline
                if self._next_try.get(key, 0.0) > now:
                    continue  # backing off after a PDB rejection
                ok, pdb = limits.can_evict(p)
                if not ok:
                    # 429: exponential backoff before the next attempt
                    delay = self._backoff.next_delay(key)
                    self._next_try[key] = now + delay
                    log.debug("eviction blocked by PDB", node=node.name,
                              pod=f"{p.namespace}/{p.name}",
                              pdb=f"{pdb.namespace}/{pdb.name}",
                              retry_in=round(delay, 3))
                    continue
                self._evict(p)
                limits.record_eviction(p)
            # one priority group per pass (terminator.go:119-138)
            break
        # the node is drained only when nothing is still WAITING on it:
        # evictable pods AND already-terminating (non-daemonset) pods that
        # haven't finished dying (IsWaitingEviction — the reference keeps
        # the node alive while a terminating StatefulSet pod lingers, which
        # is exactly the window the provisioner uses to model its
        # replacement capacity)
        return len([p for p in self._pods_on(node)
                    if self._drainable(p)
                    or (p.metadata.deletion_timestamp is not None
                        and not pod_utils.is_terminal(p)
                        and not pod_utils.is_owned_by_daemonset(p)
                        and not pod_utils.is_owned_by_node(p))])

    def _attached_volumes(self, node: Node) -> List[str]:
        """VolumeAttachments that must detach before instance delete
        (controller.go:190-240): attachments whose PV belongs to a
        NON-drainable pod are filtered out — they will never detach, so they
        must not block termination."""
        vas = self.store.list(
            VolumeAttachment,
            predicate=lambda va: va.spec.node_name == node.name)
        if not vas:
            return []
        blocked_pvs = set()
        for p in self._pods_on(node):
            # same drainable predicate as _drain: a disrupted-taint-
            # tolerating pod is never evicted, so its attachments will never
            # detach and must not hold the node (controller.go:216)
            if self._drainable(p) and pod_utils.is_disruptable(p):
                continue
            for ref in p.spec.volumes:
                pvc = self.store.get(PersistentVolumeClaim, ref.claim_name,
                                     p.namespace)
                if pvc is not None and pvc.spec.volume_name:
                    blocked_pvs.add(pvc.spec.volume_name)
        return [va.name for va in vas
                if va.spec.persistent_volume_name
                and va.spec.persistent_volume_name not in blocked_pvs]

    def _force_delete(self, pod: Pod) -> None:
        # the pod leaves the node either way: drop its eviction-queue state
        key = (pod.namespace, pod.name, pod.uid)
        self._backoff.forget(key)
        self._next_try.pop(key, None)
        if pod_utils.is_reschedulable(pod):
            pod.spec.node_name = ""
            pod.status.nominated_node_name = ""
            self.store.update(pod)
        else:
            self.store.delete(pod)

    def _drainable(self, pod: Pod) -> bool:
        """Evictable AND does NOT tolerate the disrupted taint — a
        tolerating pod opted into riding the node down: never evicted,
        never blocks the drain (terminator.go:86-99). (tolerates() returns
        untolerated-taint errors: non-empty = does not tolerate.)"""
        from ..scheduling import taints as scheduling_taints
        return pod_utils.is_evictable(pod) and bool(
            scheduling_taints.tolerates([DISRUPTED_NO_SCHEDULE_TAINT], pod))

    def _critical(self, pod: Pod) -> bool:
        return (pod.spec.priority or 0) >= CRITICAL_PRIORITY or \
            pod.spec.priority_class_name in ("system-cluster-critical",
                                             "system-node-critical")

    def _evict(self, pod: Pod) -> None:
        # mechanically identical to force-delete in the standalone runtime;
        # the distinction is the caller's gates (PDB / do-not-disrupt)
        self.recorder.publish(events_catalog.evict_pod(pod))  # eviction.go:208
        self._force_delete(pod)
