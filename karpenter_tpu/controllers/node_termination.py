"""Node termination: finalizer-driven graceful drain.

Mirrors /root/reference/pkg/controllers/node/termination/: on
deletionTimestamp, delete owning NodeClaims (controller.go:178-188), taint
disrupted:NoSchedule (terminator.go:55-92), drain pods in priority groups —
noncritical non-daemonset first (terminator.go:119-138) — then remove the
finalizer (controller.go:242-270).

Standalone-runtime deviation: the reference evicts via the Eviction API and
relies on workload controllers (Deployments) to recreate pods, with the
kube-scheduler re-binding them. Here eviction of a reschedulable pod *unbinds*
it (clears spec.node_name), returning it to the provisionable pool the
Provisioner watches; non-reschedulable pods are deleted. Net behavior matches:
disrupted pods land on replacement capacity.
"""

from __future__ import annotations

from typing import List, Optional

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim
from ..api.objects import Node, Pod
from ..kube.store import Store
from ..scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT
from ..state.cluster import Cluster
from ..utils import pod as pod_utils
from ..utils.clock import Clock
from .manager import Controller, Result

CRITICAL_PRIORITY = 2_000_000_000  # system-cluster-critical floor


class NodeTermination(Controller):
    name = "node.termination"
    kinds = (Node,)

    def __init__(self, store: Store, cluster: Cluster,
                 clock: Optional[Clock] = None):
        self.store = store
        self.cluster = cluster
        self.clock = clock or store.clock

    def reconcile(self, node: Node) -> Optional[Result]:
        if node.metadata.deletion_timestamp is None:
            return None
        if api_labels.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return None
        # delete owning NodeClaims so instance teardown starts in parallel
        owning = None
        for nc in self.store.list(NodeClaim):
            if nc.status.node_name == node.name:
                owning = nc
                if nc.metadata.deletion_timestamp is None:
                    self.store.delete(nc)
        self._taint(node)
        self._annotate_termination_time(node, owning)
        remaining = self._drain(node)
        if remaining:
            return Result(requeue_after=1.0)
        self.store.remove_finalizer(node, api_labels.TERMINATION_FINALIZER)
        return None

    def _annotate_termination_time(self, node: Node, nc) -> None:
        """controller.go: stamp the hard deadline from the claim's
        terminationGracePeriod so the drain can force-expire."""
        key = api_labels.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY
        if key in node.metadata.annotations:
            return
        tgp = nc.spec.termination_grace_period if nc is not None else None
        if tgp is not None:
            node.metadata.annotations[key] = str(
                node.metadata.deletion_timestamp + tgp)
            self.store.update(node)

    def _termination_time(self, node: Node) -> Optional[float]:
        raw = node.metadata.annotations.get(
            api_labels.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY)
        return float(raw) if raw else None

    def _taint(self, node: Node) -> None:
        if not any(t.matches(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints):
            node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
            self.store.update(node)

    def _pods_on(self, node: Node) -> List[Pod]:
        return self.store.list(Pod, predicate=lambda p: p.spec.node_name == node.name)

    def _drain(self, node: Node) -> int:
        """Evict in priority groups; returns evictable pods still bound.

        PDB-blocked and do-not-disrupt pods are retried (the Eviction API's
        429 path, terminator/eviction.go) until the TerminationGracePeriod
        deadline, after which everything is force-deleted
        (terminator.go:140-177)."""
        now = self.clock.now()
        term_time = self._termination_time(node)
        expired = term_time is not None and now >= term_time
        pods = [p for p in self._pods_on(node) if pod_utils.is_evictable(p)]

        # TGP preemptive deletes: pods whose own grace period no longer fits
        # before the node deadline start terminating immediately
        if term_time is not None and not expired:
            for p in list(pods):
                grace = p.spec.termination_grace_period_seconds or 0
                if now + grace >= term_time:
                    self._force_delete(p)
                    pods.remove(p)

        from ..utils.pdb import Limits
        from ..api.policy import PodDisruptionBudget
        limits = Limits(self.store.list(PodDisruptionBudget),
                        self.store.list(Pod))
        groups = ([p for p in pods if not self._critical(p) and not p.is_daemonset_pod],
                  [p for p in pods if not self._critical(p) and p.is_daemonset_pod],
                  [p for p in pods if self._critical(p) and not p.is_daemonset_pod],
                  [p for p in pods if self._critical(p) and p.is_daemonset_pod])
        for group in groups:
            if not group:
                continue
            for p in group:
                if expired:
                    self._force_delete(p)
                    continue
                if not pod_utils.is_disruptable(p):
                    continue  # do-not-disrupt: wait for the TGP deadline
                ok, _ = limits.can_evict(p)
                if not ok:
                    continue  # PDB 429: retry next pass
                self._evict(p)
            # one priority group per pass (terminator.go:119-138)
            break
        return len([p for p in self._pods_on(node) if pod_utils.is_evictable(p)])

    def _force_delete(self, pod: Pod) -> None:
        if pod_utils.is_reschedulable(pod):
            pod.spec.node_name = ""
            pod.status.nominated_node_name = ""
            self.store.update(pod)
        else:
            self.store.delete(pod)

    def _critical(self, pod: Pod) -> bool:
        return (pod.spec.priority or 0) >= CRITICAL_PRIORITY or \
            pod.spec.priority_class_name in ("system-cluster-critical",
                                             "system-node-critical")

    def _evict(self, pod: Pod) -> None:
        # mechanically identical to force-delete in the standalone runtime;
        # the distinction is the caller's gates (PDB / do-not-disrupt)
        self._force_delete(pod)
