"""Auxiliary NodeClaim controllers: expiration, garbage collection, pod
events, consistency.

Mirrors /root/reference/pkg/controllers/nodeclaim/{expiration,
garbagecollection,podevents,consistency}/.
"""

from __future__ import annotations

from typing import Optional

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim
from ..api.objects import Node, Pod
from ..cloudprovider.types import NodeClaimNotFoundError
from ..events.recorder import Event, Recorder
from ..kube.store import Store
from ..state.cluster import Cluster
from ..utils.clock import Clock
from .manager import Controller, Result, SingletonController

GC_POLL_SECONDS = 120.0          # garbagecollection/controller.go:59 (2 min)
POD_EVENT_DEDUPE_SECONDS = 5.0   # podevents/controller.go:63


class Expiration(Controller):
    """expiration/controller.go:54-89: forcefully delete claims older than
    expireAfter (no sim, no budget — expiration is a contract)."""

    name = "nodeclaim.expiration"
    kinds = (NodeClaim,)

    def __init__(self, store: Store, clock: Optional[Clock] = None):
        self.store = store
        self.clock = clock or store.clock

    def reconcile(self, nc: NodeClaim) -> Optional[Result]:
        if nc.metadata.deletion_timestamp is not None:
            return None
        expire_after = nc.spec.expire_after
        if not expire_after:
            return None
        age = self.clock.now() - nc.metadata.creation_timestamp
        if age >= expire_after:
            self.store.delete(nc)
            return None
        return Result(requeue_after=expire_after - age)


class GarbageCollection(SingletonController):
    """garbagecollection/controller.go:59-118: 2-minute poll deleting
    (a) claims whose cloud instance vanished after launch, and (b) untracked
    cloud instances with no matching claim."""

    name = "nodeclaim.garbagecollection"

    def __init__(self, store: Store, cloud_provider,
                 clock: Optional[Clock] = None):
        self.store = store
        self.cloud_provider = cloud_provider
        self.clock = clock or store.clock

    def reconcile(self) -> Optional[Result]:
        cloud_ids = {nc.status.provider_id for nc in self.cloud_provider.list()}
        tracked_ids = set()
        for nc in self.store.list(NodeClaim):
            pid = nc.status.provider_id
            tracked_ids.add(pid)
            if nc.launched() and pid and pid not in cloud_ids \
                    and nc.metadata.deletion_timestamp is None:
                self.store.delete(nc)
        for cloud_nc in self.cloud_provider.list():
            pid = cloud_nc.status.provider_id
            if pid and pid not in tracked_ids:
                try:
                    self.cloud_provider.delete(cloud_nc)
                except NodeClaimNotFoundError:
                    pass
        return Result(requeue_after=GC_POLL_SECONDS)


class PodEvents(Controller):
    """podevents/controller.go:63-98: stamp status.lastPodEventTime on the
    claim backing a pod's node (5 s dedupe) to drive consolidateAfter."""

    name = "nodeclaim.podevents"
    kinds = (Pod,)

    def __init__(self, store: Store, cluster: Cluster,
                 clock: Optional[Clock] = None):
        self.store = store
        self.cluster = cluster
        self.clock = clock or store.clock

    def reconcile(self, pod: Pod) -> Optional[Result]:
        node_name = pod.spec.node_name
        if not node_name:
            return None
        for nc in self.store.list(NodeClaim):
            if nc.status.node_name == node_name:
                now = self.clock.now()
                if now - nc.status.last_pod_event_time >= POD_EVENT_DEDUPE_SECONDS:
                    nc.status.last_pod_event_time = now
                    self.store.update(nc)
                break
        return None


class Consistency(Controller):
    """consistency/controller.go:78-145: sanity invariants between claim and
    node, surfaced as events rather than mutations."""

    name = "nodeclaim.consistency"
    kinds = (NodeClaim,)

    def __init__(self, store: Store, recorder: Recorder,
                 clock: Optional[Clock] = None):
        self.store = store
        self.recorder = recorder
        self.clock = clock or store.clock

    def reconcile(self, nc: NodeClaim) -> Optional[Result]:
        if nc.metadata.deletion_timestamp is not None or not nc.registered():
            return None
        node = self.store.get(Node, nc.status.node_name) \
            if nc.status.node_name else None
        if node is None:
            return None
        # node shape must cover what the claim promised
        for rname, req in nc.status.allocatable.items():
            if req > 0 and node.status.allocatable.get(rname, 0) <= 0:
                self.recorder.publish(Event(
                    object_kind="NodeClaim", object_name=nc.name,
                    type="Warning", reason="FailedConsistencyCheck",
                    message=f"expected resource \"{rname}\" didn't register "
                            "on the node"))
        # claim taints the node never observed (post-registration)
        if nc.initialized():
            node_taints = {(t.key, t.effect) for t in node.spec.taints}
            for t in nc.spec.taints:
                if (t.key, t.effect) not in node_taints:
                    self.recorder.publish(Event(
                        object_kind="NodeClaim", object_name=nc.name,
                        type="Warning", reason="FailedConsistencyCheck",
                        message=f"expected taint \"{t.key}:{t.effect}\" "
                                "didn't register on the node"))
        return None
