"""Per-item exponential failure backoff.

The workqueue.NewItemExponentialFailureRateLimiter analog the reference's
queues are built on: each failing item's retry delay doubles from `base` up
to `cap`; success forgets the item (orchestration/queue.go:128-132 with
1s/10s, terminator/eviction.go:49-50,94 with 100ms/10s)."""

from __future__ import annotations

from typing import Dict, Hashable


class TerminalError(Exception):
    """Non-retryable failure (reconcile.TerminalError mirror): the retry
    machinery must not re-attempt it — retrying cannot help (bad spec,
    permanent rejection). Lives here with the retry policy so leaf modules
    (utils/chaos.py) can raise it without importing the controller runtime;
    controllers.manager re-exports it as its public home."""


class ItemBackoff:
    def __init__(self, base: float, cap: float):
        self.base = base
        self.cap = cap
        self._failures: Dict[Hashable, int] = {}

    def next_delay(self, key: Hashable) -> float:
        """Record a failure for key and return the delay before its retry."""
        n = self._failures.get(key, 0)
        self._failures[key] = n + 1
        return min(self.base * (2 ** n), self.cap)

    def failures(self, key: Hashable) -> int:
        return self._failures.get(key, 0)

    def forget(self, key: Hashable) -> None:
        self._failures.pop(key, None)
