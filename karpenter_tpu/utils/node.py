"""Node helpers (/root/reference/pkg/utils/node/node.go)."""

from __future__ import annotations

from typing import Optional, Tuple


def get_condition(node, ctype: str) -> Optional[Tuple[str, float]]:
    """(status, lastTransitionTime) of a node condition; conditions may be
    dicts (codec/test-seeded) or objects (node.go GetCondition)."""
    for cond in node.status.conditions:
        is_dict = isinstance(cond, dict)
        t = cond.get("type") if is_dict else cond.type
        if t != ctype:
            continue
        status = cond.get("status") if is_dict else cond.status
        when = (cond.get("last_transition_time", 0.0) if is_dict
                else getattr(cond, "last_transition_time", 0.0))
        return status, when
    return None
