"""Node helpers (/root/reference/pkg/utils/node/node.go)."""

from __future__ import annotations

from typing import Optional, Tuple


def set_condition(node, ctype: str, status: str, now: float = 0.0) -> None:
    """Replace-by-type (apiserver semantics: one condition per type).
    Appending a second entry of the same type would be unrepresentable in
    Kubernetes and silently masked by get_condition."""
    node.status.conditions = [
        c for c in node.status.conditions
        if (c.get("type") if isinstance(c, dict) else c.type) != ctype]
    node.status.conditions.append(
        {"type": ctype, "status": status, "last_transition_time": now})


def get_condition(node, ctype: str) -> Optional[Tuple[str, float]]:
    """(status, lastTransitionTime) of a node condition; conditions may be
    dicts (codec/test-seeded) or objects (node.go GetCondition)."""
    for cond in node.status.conditions:
        is_dict = isinstance(cond, dict)
        t = cond.get("type") if is_dict else cond.type
        if t != ctype:
            continue
        status = cond.get("status") if is_dict else cond.status
        when = (cond.get("last_transition_time", 0.0) if is_dict
                else getattr(cond, "last_transition_time", 0.0))
        return status, when
    return None
