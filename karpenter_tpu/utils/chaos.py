"""Seeded fault injection: the chaos-engineering substrate.

The reference operator's resilience is exercised by real clusters being
flaky at it; the standalone framework needs the flakiness injected. One
`FaultInjector` is shared by every chaos surface — the store wrapper
(kube/chaos.py), the cloudprovider wrapper (cloudprovider/chaos.py), and
the FakeCloudProvider hooks — so a single seeded RNG drives the whole
fault schedule deterministically (Basiri et al., "Chaos Engineering":
reproducible experiments, not random vandalism).

Faults fire only while a controller is reconciling (utils/injection.py
contextvar set by the Manager dispatch): test setup and assertions talk to
the store/provider unperturbed, exactly like a chaos experiment that spares
the control plane's own tooling.
"""

from __future__ import annotations

import contextlib
import random
from collections import Counter
from typing import Optional

from .backoff import TerminalError
from .injection import controller_name


class InjectedFault(Exception):
    """Transient injected failure (apiserver 500 / provider throttle
    analog): reconcilers are expected to surface it and the manager to
    retry it through the item backoff."""


class InjectedTerminalFault(TerminalError):
    """Terminal injected failure: the manager must NOT retry it."""


class FaultInjector:
    """Seeded fault schedule shared across chaos surfaces.

    - ``rate``: probability that any gated operation raises.
    - ``terminal_rate``: fraction of fired faults that are terminal
      (InjectedTerminalFault) instead of transient.
    - ``poison(name)``: operations touching that object name ALWAYS raise
      transiently — the deliberately-unreconcilable object whose landing
      in the dead-letter set the soak test asserts.
    - ``reconcile_only`` (default True): faults fire only inside a
      reconcile (controller-name contextvar set), so harness setup code
      is never perturbed.

    ``counts`` records fired faults per operation label for assertions
    ("faults actually fired") and experiment reports.
    """

    def __init__(self, seed: int = 0, rate: float = 0.05,
                 terminal_rate: float = 0.0, reconcile_only: bool = True):
        self.rng = random.Random(seed)
        self.rate = rate
        self.terminal_rate = terminal_rate
        self.reconcile_only = reconcile_only
        self.enabled = True
        self.poisoned: set = set()
        self.counts: Counter = Counter()

    def poison(self, name: str) -> None:
        self.poisoned.add(name)

    def maybe_raise(self, op: str, name: str = "") -> None:
        """Fault gate: called at the top of every wrapped operation."""
        if not self.enabled:
            return
        if self.reconcile_only and not controller_name():
            return
        if name and name in self.poisoned:
            self.counts[op] += 1
            raise InjectedFault(f"poisoned object {name!r} in {op}")
        if self.rate and self.rng.random() < self.rate:
            self.counts[op] += 1
            if self.terminal_rate \
                    and self.rng.random() < self.terminal_rate:
                raise InjectedTerminalFault(f"injected terminal fault "
                                            f"in {op} ({name or 'op'})")
            raise InjectedFault(f"injected fault in {op} "
                                f"({name or 'op'})")

    def fired(self) -> int:
        return sum(self.counts.values())


class CapacityDrought:
    """Scheduled capacity-exhaustion windows for the simulated providers —
    the chaos substrate behind the unavailable-offerings feedback loop.

    A window is an ``(instance_type, zone, capacity_type)`` pattern ("*"
    wildcard per position) with an optional expiry: while live, any create
    whose CHOSEN offering matches raises InsufficientCapacityError carrying
    the matched pattern — exactly the zone-running-dry / capacity-type-
    exhausted failure every production autoscaler hits, recovering on its
    own once the window lapses. Clock-injected (FakeClock in tests) so the
    drought-and-recovery timeline is deterministic; ``hits`` counts fired
    exhaustions per pattern for assertions ("zero repeat create calls
    against a cached-unavailable offering" is ``hits`` staying flat while
    the registry TTL lives).
    """

    def __init__(self, clock=None):
        self.clock = clock
        self._windows: list = []  # ((it, zone, ct), until_or_None)
        self.hits: Counter = Counter()

    def exhaust(self, instance_type: str = "*", zone: str = "*",
                capacity_type: str = "*",
                duration: Optional[float] = None) -> None:
        until = None
        if duration is not None:
            if self.clock is None:
                raise ValueError("duration needs an injected clock")
            until = self.clock.now() + duration
        self._windows.append(((instance_type, zone, capacity_type), until))

    def clear(self) -> None:
        self._windows.clear()

    def match(self, instance_type: str, zone: str,
              capacity_type: str) -> Optional[tuple]:
        """First live pattern covering the offering (pruning lapsed
        windows), or None. Counts the hit."""
        now = self.clock.now() if self.clock is not None else None
        live, hit = [], None
        for pat, until in self._windows:
            if until is not None and now is not None and now >= until:
                continue
            live.append((pat, until))
            pit, pz, pct = pat
            if hit is None and pit in ("*", instance_type) \
                    and pz in ("*", zone) and pct in ("*", capacity_type):
                hit = pat
        self._windows = live
        if hit is not None:
            self.hits["/".join(hit)] += 1
        return hit


@contextlib.contextmanager
def chaos_pause(injector: Optional[FaultInjector]):
    """Context manager: suspend fault injection (convergence checks)."""
    if injector is None:
        yield
        return
    prev, injector.enabled = injector.enabled, False
    try:
        yield
    finally:
        injector.enabled = prev
