"""Seeded fault injection: the chaos-engineering substrate.

The reference operator's resilience is exercised by real clusters being
flaky at it; the standalone framework needs the flakiness injected. One
`FaultInjector` is shared by every chaos surface — the store wrapper
(kube/chaos.py), the cloudprovider wrapper (cloudprovider/chaos.py), and
the FakeCloudProvider hooks — so a single seeded RNG drives the whole
fault schedule deterministically (Basiri et al., "Chaos Engineering":
reproducible experiments, not random vandalism).

Faults fire only while a controller is reconciling (utils/injection.py
contextvar set by the Manager dispatch): test setup and assertions talk to
the store/provider unperturbed, exactly like a chaos experiment that spares
the control plane's own tooling.
"""

from __future__ import annotations

import contextlib
import random
from collections import Counter, deque
from typing import Optional

from .backoff import TerminalError
from .injection import controller_name


class InjectedFault(Exception):
    """Transient injected failure (apiserver 500 / provider throttle
    analog): reconcilers are expected to surface it and the manager to
    retry it through the item backoff."""


class InjectedTerminalFault(TerminalError):
    """Terminal injected failure: the manager must NOT retry it."""


class FaultInjector:
    """Seeded fault schedule shared across chaos surfaces.

    - ``rate``: probability that any gated operation raises.
    - ``terminal_rate``: fraction of fired faults that are terminal
      (InjectedTerminalFault) instead of transient.
    - ``poison(name)``: operations touching that object name ALWAYS raise
      transiently — the deliberately-unreconcilable object whose landing
      in the dead-letter set the soak test asserts.
    - ``reconcile_only`` (default True): faults fire only inside a
      reconcile (controller-name contextvar set), so harness setup code
      is never perturbed.

    ``counts`` records fired faults per operation label for assertions
    ("faults actually fired") and experiment reports.
    """

    def __init__(self, seed: int = 0, rate: float = 0.05,
                 terminal_rate: float = 0.0, reconcile_only: bool = True):
        self.rng = random.Random(seed)
        self.rate = rate
        self.terminal_rate = terminal_rate
        self.reconcile_only = reconcile_only
        self.enabled = True
        self.poisoned: set = set()
        self.counts: Counter = Counter()

    def poison(self, name: str) -> None:
        self.poisoned.add(name)

    def maybe_raise(self, op: str, name: str = "") -> None:
        """Fault gate: called at the top of every wrapped operation."""
        if not self.enabled:
            return
        if self.reconcile_only and not controller_name():
            return
        if name and name in self.poisoned:
            self.counts[op] += 1
            raise InjectedFault(f"poisoned object {name!r} in {op}")
        if self.rate and self.rng.random() < self.rate:
            self.counts[op] += 1
            if self.terminal_rate \
                    and self.rng.random() < self.terminal_rate:
                raise InjectedTerminalFault(f"injected terminal fault "
                                            f"in {op} ({name or 'op'})")
            raise InjectedFault(f"injected fault in {op} "
                                f"({name or 'op'})")

    def fired(self) -> int:
        return sum(self.counts.values())


class CapacityDrought:
    """Scheduled capacity-exhaustion windows for the simulated providers —
    the chaos substrate behind the unavailable-offerings feedback loop.

    A window is an ``(instance_type, zone, capacity_type)`` pattern ("*"
    wildcard per position) with an optional expiry: while live, any create
    whose CHOSEN offering matches raises InsufficientCapacityError carrying
    the matched pattern — exactly the zone-running-dry / capacity-type-
    exhausted failure every production autoscaler hits, recovering on its
    own once the window lapses. Clock-injected (FakeClock in tests) so the
    drought-and-recovery timeline is deterministic; ``hits`` counts fired
    exhaustions per pattern for assertions ("zero repeat create calls
    against a cached-unavailable offering" is ``hits`` staying flat while
    the registry TTL lives).
    """

    def __init__(self, clock=None):
        self.clock = clock
        self._windows: list = []  # ((it, zone, ct), until_or_None)
        self.hits: Counter = Counter()

    def exhaust(self, instance_type: str = "*", zone: str = "*",
                capacity_type: str = "*",
                duration: Optional[float] = None) -> None:
        until = None
        if duration is not None:
            if self.clock is None:
                raise ValueError("duration needs an injected clock")
            until = self.clock.now() + duration
        self._windows.append(((instance_type, zone, capacity_type), until))

    def clear(self) -> None:
        self._windows.clear()

    def match(self, instance_type: str, zone: str,
              capacity_type: str) -> Optional[tuple]:
        """First live pattern covering the offering (pruning lapsed
        windows), or None. Counts the hit."""
        now = self.clock.now() if self.clock is not None else None
        live, hit = [], None
        for pat, until in self._windows:
            if until is not None and now is not None and now >= until:
                continue
            live.append((pat, until))
            pit, pz, pct = pat
            if hit is None and pit in ("*", instance_type) \
                    and pz in ("*", zone) and pct in ("*", capacity_type):
                hit = pat
        self._windows = live
        if hit is not None:
            self.hits["/".join(hit)] += 1
        return hit


class WireFaultInjector:
    """Seeded fault schedule for the gRPC wire (the service-path chaos
    substrate ISSUE 11 adds below the process boundary the FaultInjector
    stops at). One injector drives a chaos-wrapped channel
    (sidecar/wire_chaos.ChaosChannel); per RPC *attempt* it draws one
    verdict from the seeded RNG:

    - ``drop``: the request never reaches the server (connection reset /
      blackholed packet) — the client sees UNAVAILABLE, the server sees
      nothing.
    - ``disconnect``: the request IS delivered and applied, the response
      is lost mid-stream — the client sees UNAVAILABLE while the server
      state advanced (the desync case the request-digest dedupe cache
      must make retry-safe).
    - ``duplicate``: the request is delivered twice back to back (a
      retransmit racing its original) — the second delivery must be
      served from the dedupe cache, not re-applied.
    - ``delay``: ``delay_seconds`` of added latency before delivery (a
      congested wire; with a short client deadline this manufactures
      DEADLINE_EXCEEDED).

    Draw order is fixed (delay, then drop, then duplicate, then
    disconnect — at most one delivery-altering fault per attempt) so the
    same seed yields the same fault schedule for the same RPC sequence;
    ``counts`` records fired faults per kind for "faults actually fired"
    assertions. ``enabled=False`` short-circuits to zero overhead — the
    chaos-off bench line wraps the channel and asserts the wrapper costs
    nothing."""

    KINDS = ("drop", "delay", "duplicate", "disconnect")

    def __init__(self, seed: int = 0, drop: float = 0.0, delay: float = 0.0,
                 duplicate: float = 0.0, disconnect: float = 0.0,
                 delay_seconds: float = 0.02):
        self.rng = random.Random(seed)
        self.drop = drop
        self.delay = delay
        self.duplicate = duplicate
        self.disconnect = disconnect
        self.delay_seconds = delay_seconds
        self.enabled = True
        self.counts: Counter = Counter()
        # one-shot forced faults consumed before any random draw: the
        # deterministic "this exact fault WILL happen on the next attempt"
        # primitive harnesses use to pin each recovery path regardless of
        # what the background rates roll
        self._forced: deque = deque()

    def inject_next(self, *kinds: str) -> None:
        """Queue a forced fault verdict for the next attempt (e.g.
        inject_next("drop"), inject_next("delay", "disconnect"))."""
        for k in kinds:
            if k not in self.KINDS:
                raise ValueError(f"unknown wire fault kind {k!r} "
                                 f"(known: {', '.join(self.KINDS)})")
        self._forced.append(list(kinds))

    def set_rates(self, drop: float = 0.0, delay: float = 0.0,
                  duplicate: float = 0.0, disconnect: float = 0.0,
                  delay_seconds: Optional[float] = None) -> None:
        self.drop = drop
        self.delay = delay
        self.duplicate = duplicate
        self.disconnect = disconnect
        if delay_seconds is not None:
            self.delay_seconds = delay_seconds

    def rates(self) -> dict:
        return {"drop": self.drop, "delay": self.delay,
                "duplicate": self.duplicate, "disconnect": self.disconnect,
                "delay_seconds": self.delay_seconds}

    def draw(self) -> list:
        """Fault verdict for one RPC attempt: a (possibly empty) list of
        kind names, ``delay`` optionally preceding ONE delivery-altering
        fault. Always consumes the same number of RNG draws per call so
        the schedule depends only on the attempt sequence, not on which
        faults happen to fire."""
        if not self.enabled:
            return []
        # the draws are burned even when a forced verdict overrides them:
        # a run using inject_next() must see the SAME background schedule
        # as a same-seed run without it, or forced-vs-baseline comparisons
        # diverge from the forced attempt onward
        draws = [self.rng.random() for _ in range(4)]
        if self._forced:
            out = self._forced.popleft()
            for kind in out:
                self.counts[kind] += 1
            return out
        out = []
        if self.delay and draws[0] < self.delay:
            out.append("delay")
        if self.drop and draws[1] < self.drop:
            out.append("drop")
        elif self.duplicate and draws[2] < self.duplicate:
            out.append("duplicate")
        elif self.disconnect and draws[3] < self.disconnect:
            out.append("disconnect")
        for kind in out:
            self.counts[kind] += 1
        return out

    def fired(self) -> int:
        return sum(self.counts.values())


@contextlib.contextmanager
def chaos_pause(injector: Optional[FaultInjector]):
    """Context manager: suspend fault injection (convergence checks)."""
    if injector is None:
        yield
        return
    prev, injector.enabled = injector.enabled, False
    try:
        yield
    finally:
        injector.enabled = prev


class DeviceKiller:
    """Seeded device-kill verdict source for the mesh dispatch path
    (installed via ops/binpack.install_device_chaos). ``kill``/``revive``
    toggle a device's liveness; ``verdict(ids)`` returns the first dead
    device among a dispatch's participants (counting the hit) or None —
    the dispatch then raises DeviceLossError for it, driving the
    degradation ladder exactly the way a real mid-solve chip loss would."""

    def __init__(self):
        self.dead: set = set()
        self.counts: Counter = Counter()
        self.enabled = True

    def kill(self, device_id: int) -> None:
        self.dead.add(int(device_id))

    def revive(self, device_id: int) -> None:
        self.dead.discard(int(device_id))

    def verdict(self, device_ids) -> Optional[int]:
        if not self.enabled or not self.dead:
            return None
        for did in device_ids:
            if int(did) in self.dead:
                self.counts[int(did)] += 1
                return int(did)
        return None


class StateCorruptor:
    """Seeded corruption of the warm solver state: the chaos half of the
    anti-entropy loop (state/audit.py detects, quarantines, heals what
    this injects). Targets the live caches of one EncodePlane (and the
    warm-pack seed of one ProblemState handle) with three fault kinds:

    - ``bit_flip``  — one byte of a cached ndarray flipped IN PLACE;
    - ``stale_value`` — an entry's content replaced while its validity
      token (and any recorded digest) is kept, the silently-stale-row
      failure mode token checks alone can never catch;
    - ``truncate`` — an array shortened, the torn-write analog.

    Every fault lands on the CURRENT serve path (cur-generation node rows,
    resident stacks, live memo entries, the live seed) so an attached
    auditor must detect 100% of them before the entry is served; the
    prev-generation and dead-token cases are pinned by directed tests.
    ``corrupt`` returns the injected records; with no candidates in a
    layer nothing is injected (and nothing counted)."""

    LAYERS = ("node_rows", "group_rows", "exist_stack", "topo_memo",
              "warm_checkpoint")
    KINDS = ("bit_flip", "stale_value", "truncate")

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.counts: Counter = Counter()
        self.injected: list = []

    # -- array mutation helpers ----------------------------------------------

    def _flip(self, arr) -> bool:
        import numpy as np
        try:
            flat = arr.view(np.uint8).reshape(-1)
        except (ValueError, AttributeError):
            return False
        if not flat.size:
            return False
        flat[self.rng.randrange(flat.size)] ^= 0xFF
        return True

    def _arrays_in(self, obj, out) -> None:
        import numpy as np
        if isinstance(obj, np.ndarray):
            if obj.size:
                out.append(obj)
        elif isinstance(obj, (tuple, list)):
            for item in obj:
                self._arrays_in(item, out)
        elif isinstance(obj, dict):
            for item in obj.values():
                self._arrays_in(item, out)
        elif hasattr(obj, "__dict__"):
            for item in vars(obj).values():
                self._arrays_in(item, out)

    # -- per-layer injections ------------------------------------------------

    def _corrupt_node_rows(self, plane, kind: str) -> Optional[dict]:
        caches = [c for c in plane._node_caches.values() if c.cur]
        if not caches:
            return None
        cache = self.rng.choice(caches)
        key = self.rng.choice(sorted(cache.cur, key=repr))
        row = cache.cur[key]
        if kind == "bit_flip":
            if not self._flip(row[2]):
                return None
        elif kind == "stale_value":
            # zone index perturbed; rev (row[0]) and any digest kept
            cache.cur[key] = row[:3] + (int(row[3]) + 1,) + row[4:]
        else:
            cache.cur[key] = row[:2] + (row[2][:-1],) + row[3:]
        return {"layer": "node_rows", "kind": kind, "key": key[0]}

    def _corrupt_group_rows(self, plane, kind: str) -> Optional[dict]:
        import numpy as np
        tables = [t for t in plane._group_caches.values() if t]
        if not tables:
            return None
        rows = self.rng.choice(tables)
        sig = self.rng.choice(sorted(rows, key=repr))
        enc_row, req_vec = rows[sig]
        if kind == "bit_flip":
            if not self._flip(req_vec):
                return None
        elif kind == "stale_value":
            rows[sig] = (enc_row, req_vec + np.float64(1.0))
        else:
            rows[sig] = (enc_row, req_vec[:-1])
        return {"layer": "group_rows", "kind": kind}

    def _corrupt_exist_stack(self, plane, kind: str) -> Optional[dict]:
        caches = [c for c in plane._node_caches.values() if c.stacks]
        if not caches:
            return None
        stacks = self.rng.choice(caches).stacks
        token = next(reversed(stacks))  # the most recently served slot
        exist_enc, exist_avail, exist_zone, taints = stacks[token]
        if kind == "bit_flip":
            if not self._flip(exist_avail):
                return None
        elif kind == "stale_value":
            stacks[token] = (exist_enc, exist_avail + 1.0, exist_zone,
                             taints)
        else:
            stacks[token] = (exist_enc, exist_avail[:-1], exist_zone,
                             taints)
        return {"layer": "exist_stack", "kind": kind}

    def _corrupt_topo_memo(self, plane, kind: str) -> Optional[dict]:
        memos = [m for m in plane._topo_memos.values() if m]
        if not memos:
            return None
        memo = memos[-1]  # the most recently proven token's entries
        sig = self.rng.choice(sorted(memo, key=repr))
        entry = memo[sig]
        if kind == "bit_flip":
            if not self._flip(entry[0]):
                return None
        elif kind == "stale_value":
            memo[sig] = entry[:2] + (int(entry[2]) + 1,) + entry[3:]
        else:
            memo[sig] = (entry[0][:-1],) + entry[1:]
        return {"layer": "topo_memo", "kind": kind}

    def _corrupt_warm_checkpoint(self, handle, kind: str) -> Optional[dict]:
        if handle is None:
            return None
        arrays: list = []
        for seed in [handle.seed] + list(handle.shard_seeds or []):
            if seed is None:
                continue
            for ck in getattr(seed, "checkpoints", ()) or ():
                self._arrays_in(ck.rows, arrays)
                self._arrays_in(ck.exist_avail, arrays)
        if not arrays:
            return None
        # every warm fault is an in-place flip: the seed's digest was
        # recorded by finish_pack, so any content change is detectable —
        # the kind only varies which failure mode produced it
        if not self._flip(self.rng.choice(arrays)):
            return None
        return {"layer": "warm_checkpoint", "kind": "bit_flip"}

    # -- driver --------------------------------------------------------------

    def corrupt(self, plane, handle=None, layer: str = "all",
                count: int = 1) -> list:
        """Inject up to ``count`` seeded faults into ``plane`` (and
        ``handle``'s warm seed for the warm_checkpoint layer). Returns the
        records actually injected; layers with no live candidates are
        skipped (nothing counted), so detection assertions can compare
        against the return value exactly."""
        injectors = {
            "node_rows": lambda k: self._corrupt_node_rows(plane, k),
            "group_rows": lambda k: self._corrupt_group_rows(plane, k),
            "exist_stack": lambda k: self._corrupt_exist_stack(plane, k),
            "topo_memo": lambda k: self._corrupt_topo_memo(plane, k),
            "warm_checkpoint":
                lambda k: self._corrupt_warm_checkpoint(handle, k),
        }
        out = []
        for _ in range(count):
            layers = list(self.LAYERS) if layer == "all" else [layer]
            self.rng.shuffle(layers)
            kind = self.rng.choice(self.KINDS)
            for name in layers:
                rec = injectors[name](kind)
                if rec is not None:
                    self.counts[rec["layer"]] += 1
                    self.injected.append(rec)
                    out.append(rec)
                    break
        return out
