"""ResourceList arithmetic over exact integer milliunits.

A ResourceList here is a plain ``dict[str, int]`` mapping resource name ("cpu",
"memory", "pods", ...) to integer milliunits (see utils/quantity.py).

Mirrors the semantics of the reference helpers in
/root/reference/pkg/utils/resources/resources.go (Merge, Subtract, Fits:217-231,
MaxResources, RequestsForPods) without the apimachinery Quantity machinery.
"""

from __future__ import annotations

from typing import Iterable, Mapping

from . import quantity

ResourceList = dict

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"


def parse_list(spec: Mapping[str, "int | float | str"]) -> ResourceList:
    return {k: quantity.parse(v) for k, v in spec.items()}


def add(*lists: Mapping[str, int]) -> ResourceList:
    out: ResourceList = {}
    for rl in lists:
        for k, v in rl.items():
            out[k] = out.get(k, 0) + v
    return out


def merge(*lists: Mapping[str, int]) -> ResourceList:
    """Alias used where the reference calls resources.Merge (summing requests)."""
    return add(*lists)


def subtract(a: Mapping[str, int], b: Mapping[str, int]) -> ResourceList:
    """a - b over the union of keys (missing treated as zero). May go negative,
    matching the reference's Subtract which lets callers observe deficits."""
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) - v
    return out


def max_resources(lists: Iterable[Mapping[str, int]]) -> ResourceList:
    """Element-wise max — reference resources.MaxResources, used by subtractMax
    pessimism in scheduler.go:388-405."""
    out: ResourceList = {}
    for rl in lists:
        for k, v in rl.items():
            if v > out.get(k, 0):
                out[k] = v
    return out


def fits(requests: Mapping[str, int], available: Mapping[str, int]) -> bool:
    """True if every requested resource fits in available (missing available == 0,
    but zero-valued requests always fit). Reference resources.Fits:217-231."""
    for k, v in requests.items():
        if v <= 0:
            continue
        if v > available.get(k, 0):
            return False
    return True


def any_positive(rl: Mapping[str, int]) -> bool:
    return any(v > 0 for v in rl.values())


def exceeds(usage: Mapping[str, int], limits: Mapping[str, int]) -> "list[str]":
    """Resource names whose usage strictly exceeds the limit (only keys present in
    limits are checked) — reference Limits.ExceededBy (apis/v1/nodepool.go:140-154)."""
    return [k for k, lim in limits.items() if usage.get(k, 0) > lim]


def init_entry(entry) -> "tuple[ResourceList, bool]":
    """Normalize a pod.init_container_requests entry to
    (requests, restart_always)."""
    if isinstance(entry, tuple):
        return entry
    return entry, False


def pod_requests(pod) -> ResourceList:
    """Total requests for a pod (reference resources.podRequests:95-125):
    sum of containers plus native sidecars (init containers with
    restartPolicy=Always), element-wise maxed against each regular init
    container combined with the sidecars declared BEFORE it (sidecars are
    already running while later init containers execute — order matters),
    plus one 'pods' slot.

    Entries in pod.init_container_requests are either a plain ResourceList
    (regular init container) or a (ResourceList, restart_always) tuple."""
    requests = add(*(c for c in pod.container_requests)) if pod.container_requests else {}
    restartable: ResourceList = {}
    max_init: ResourceList = {}
    for entry in pod.init_container_requests:
        req, always = init_entry(entry)
        if always:
            requests = add(requests, req)
            restartable = add(restartable, req)
            max_init = max_resources([max_init, restartable])
        else:
            max_init = max_resources([max_init, add(req, restartable)])
    out = max_resources([requests, max_init])
    out[PODS] = out.get(PODS, 0) + 1000  # one pod slot, in milliunits
    return out
