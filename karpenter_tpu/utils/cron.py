"""Minimal standard 5-field cron schedule (UTC), with Next() semantics.

Used by disruption budget windows (reference: robfig/cron via
pkg/apis/v1/nodepool.go:353-367).
"""

from __future__ import annotations

import calendar
from datetime import datetime, timedelta, timezone

_ALIASES = {
    "@yearly": "0 0 1 1 *", "@annually": "0 0 1 1 *", "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0", "@daily": "0 0 * * *", "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}

_DOW_NAMES = {"sun": 0, "mon": 1, "tue": 2, "wed": 3, "thu": 4, "fri": 5, "sat": 6}
_MON_NAMES = {m.lower(): i for i, m in enumerate(calendar.month_abbr) if m}


class Schedule:
    def __init__(self, expr: str):
        expr = expr.strip()
        expr = _ALIASES.get(expr, expr)
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"invalid cron expression {expr!r}")
        self.minutes = _parse_field(fields[0], 0, 59)
        self.hours = _parse_field(fields[1], 0, 23)
        self.dom = _parse_field(fields[2], 1, 31, _MON_NAMES)
        self.months = _parse_field(fields[3], 1, 12, _MON_NAMES)
        self.dow = _parse_field(fields[4], 0, 6, _DOW_NAMES, dow=True)
        self.dom_star = fields[2] == "*"
        self.dow_star = fields[4] == "*"

    def matches(self, t: datetime) -> bool:
        if t.minute not in self.minutes or t.hour not in self.hours or t.month not in self.months:
            return False
        dom_ok = t.day in self.dom
        dow_ok = ((t.weekday() + 1) % 7) in self.dow  # python Mon=0 -> cron Sun=0
        # standard cron: if both dom and dow are restricted, either may match
        if not self.dom_star and not self.dow_star:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def next(self, after: datetime) -> datetime:
        """First matching time strictly after `after` (minute granularity), UTC."""
        t = after.astimezone(timezone.utc).replace(second=0, microsecond=0) + timedelta(minutes=1)
        for _ in range(366 * 24 * 60):  # bounded scan: a year of minutes
            if self.matches(t):
                return t
            # skip forward coarsely when month/day/hour don't match
            if t.month not in self.months:
                if t.month == 12:
                    t = t.replace(year=t.year + 1, month=1, day=1, hour=0, minute=0)
                else:
                    t = t.replace(month=t.month + 1, day=1, hour=0, minute=0)
                continue
            dom_ok = t.day in self.dom
            dow_ok = ((t.weekday() + 1) % 7) in self.dow
            day_ok = (dom_ok or dow_ok) if (not self.dom_star and not self.dow_star) else (dom_ok and dow_ok)
            if not day_ok:
                t = (t + timedelta(days=1)).replace(hour=0, minute=0)
                continue
            if t.hour not in self.hours:
                t = (t + timedelta(hours=1)).replace(minute=0)
                continue
            t += timedelta(minutes=1)
        raise ValueError("no matching time found within a year")


def _parse_field(field: str, lo: int, hi: int, names=None, dow: bool = False) -> frozenset:
    out = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part == "*" or part == "?":
            start, end = lo, hi
        elif "-" in part and not part.lstrip("-").isdigit():
            a, b = part.split("-", 1)
            start, end = _val(a, names), _val(b, names)
        else:
            start = end = _val(part, names)
            if "/" in field and "-" not in field.split("/")[0] and field.split("/")[0] != "*":
                end = hi  # "5/2" means start at 5, every 2
        if dow:
            start, end = start % 7, end % 7  # cron allows 7 == Sunday
        if start > end:
            out.update(range(start, hi + 1), range(lo, end + 1))
        else:
            out.update(range(start, end + 1, step))
    return frozenset(out)


def _val(s: str, names) -> int:
    s = s.strip().lower()
    if names and s in names:
        return names[s]
    return int(s)
