"""PDB limit evaluation (/root/reference/pkg/utils/pdb/pdb.go:33-112).

Limits answers: can this pod be evicted right now, and which PDB blocks it?
A pod is blocked when any matching PDB has disruptionsAllowed == 0. The
reference reads status computed by the disruption controller; standalone we
compute it live from current pod health.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

from ..api.objects import Pod
from ..api.policy import PodDisruptionBudget
from . import pod as pod_utils


def _parse_intstr(v: str, total: int) -> int:
    v = v.strip()
    if v.endswith("%"):
        return int(math.ceil(total * int(v[:-1]) / 100.0))
    return int(v)


class Limits:
    def __init__(self, pdbs: List[PodDisruptionBudget], pods: List[Pod]):
        self.pdbs = pdbs
        self.pods = pods
        # evictions granted THROUGH this Limits instance, counted against
        # each PDB's headroom: the API server sees each eviction reflected in
        # PDB status before the next one, so a one-shot snapshot must track
        # its own grants to avoid over-evicting within a single drain pass
        self._granted: dict = {}
        # selector-match memo per PDB: a Limits instance snapshots one
        # pass, and pod labels/namespaces don't move within it — without
        # the memo a disruption pass over N candidates re-scans every pod
        # per (candidate pod, PDB), O(pdbs x pods^2) (the fleet simulator
        # surfaced this at ~90 ms per pass on a 200-pod cluster). Health
        # is still recomputed per call: in-pass evictions mutate bindings.
        self._matching: dict = {}

    def _matching_pods(self, pdb: PodDisruptionBudget) -> List[Pod]:
        cached = self._matching.get(id(pdb))
        if cached is not None:
            return cached
        sel = pdb.spec.selector
        out = [p for p in self.pods
               if p.namespace == pdb.namespace
               and sel is not None and sel.matches(p.labels)]
        self._matching[id(pdb)] = out
        return out

    def disruptions_allowed(self, pdb: PodDisruptionBudget) -> int:
        matching = self._matching_pods(pdb)
        expected = len(matching)
        healthy = len([p for p in matching
                       if pod_utils.is_active(p) and p.spec.node_name])
        if pdb.spec.max_unavailable is not None:
            max_unavail = _parse_intstr(pdb.spec.max_unavailable, expected)
            unhealthy = expected - healthy
            return max(0, max_unavail - unhealthy)
        if pdb.spec.min_available is not None:
            min_avail = _parse_intstr(pdb.spec.min_available, expected)
            return max(0, healthy - min_avail)
        return expected

    def can_evict(self, pod: Pod) -> Tuple[bool, Optional[PodDisruptionBudget]]:
        """pdb.go CanEvictPods: blocked when ANY matching PDB has no headroom
        (pdb.go:56-86) — a pod covered by several PDBs must clear all of them.
        Fully-blocking PDBs (maxUnavailable 0/0%) block even unhealthy pods."""
        for pdb in self.pdbs:
            if pdb.namespace != pod.namespace:
                continue
            sel = pdb.spec.selector
            if sel is None or not sel.matches(pod.labels):
                continue
            allowed = self.disruptions_allowed(pdb) - \
                self._granted.get(id(pdb), 0)
            if allowed <= 0:
                return False, pdb
        return True, None

    def record_eviction(self, pod: Pod) -> None:
        """Count a granted eviction against every matching PDB so the next
        can_evict in the same pass sees the reduced headroom."""
        for pdb in self.pdbs:
            if pdb.namespace != pod.namespace:
                continue
            sel = pdb.spec.selector
            if sel is not None and sel.matches(pod.labels):
                self._granted[id(pdb)] = self._granted.get(id(pdb), 0) + 1
