"""Kubernetes-style resource quantity parsing and formatting.

All quantities are held as exact integers in *milliunits* (1 unit == 1000 milli),
mirroring how apimachinery's resource.Quantity canonicalizes to milli scale. This
keeps host-side arithmetic exact (no float drift when summing "100m" cpu requests)
while staying trivially convertible to the scaled int32 tensors the TPU kernels use.

Reference behavior: k8s.io/apimachinery resource.Quantity as used throughout
/root/reference (e.g. pkg/utils/resources/resources.go).
"""

from __future__ import annotations

import math
import re
from fractions import Fraction

# Binary suffixes (powers of 1024) and decimal suffixes (powers of 1000).
_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {
    "n": Fraction(1, 10**9), "u": Fraction(1, 10**6), "m": Fraction(1, 10**3), "": 1,
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18,
}

_QTY_RE = re.compile(r"^([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)$")


def parse(value: "int | float | str") -> int:
    """Parse a quantity into integer milliunits. "100m" -> 100, "1" -> 1000, "1Gi" -> 1073741824000."""
    if isinstance(value, bool):
        raise ValueError(f"invalid quantity {value!r}")
    if isinstance(value, int):
        return value * 1000
    if isinstance(value, float):
        return math.ceil(value * 1000)
    m = _QTY_RE.match(value.strip())
    if not m:
        raise ValueError(f"invalid quantity {value!r}")
    num_s, suffix = m.groups()
    if suffix in _BINARY:
        scale = _BINARY[suffix] * 1000
    elif suffix in _DECIMAL:
        scale = _DECIMAL[suffix] * 1000
    else:
        raise ValueError(f"invalid quantity suffix {suffix!r} in {value!r}")
    # Exact arithmetic throughout; fractional milli rounds up (k8s canonicalizes
    # sub-milli to the next milli for cpu-style resources).
    if "e" in num_s or "E" in num_s:
        num = Fraction(num_s)
    elif "." in num_s:
        whole, frac = num_s.split(".")
        sign = -1 if whole.startswith("-") else 1
        whole = whole.lstrip("+-") or "0"
        num = sign * Fraction(int(whole) * 10 ** len(frac) + int(frac), 10 ** len(frac))
    else:
        num = Fraction(int(num_s))
    return math.ceil(num * scale)


def format_milli(milli: int) -> str:
    """Render milliunits back to a human string ("1500m" style for fractional, plain int otherwise)."""
    if milli % 1000 == 0:
        return str(milli // 1000)
    return f"{milli}m"


def to_unit_float(milli: int) -> float:
    """Milliunits -> float units (for pricing/metrics, not for fits checks)."""
    return milli / 1000.0
