"""Scoped cyclic-GC suppression for latency-critical sections.

A 50k-pod solve allocates ~10^5 short-lived container objects; CPython's
generational collector fires unpredictably inside the solve and costs
50-400 ms per pause (measured on the north-star shape). Refcounting
reclaims essentially all of the solve's garbage, so suppressing the
cyclic collector for the duration moves the (much smaller) sweep to
whenever the process is next idle. The sidecar server goes further and
disables collection process-wide (sidecar/server.py _idle_gc_loop);
there this guard is a no-op.
"""

from __future__ import annotations

import gc
import threading
from contextlib import contextmanager

_lock = threading.Lock()
_count = 0
_was_enabled = False


@contextmanager
def no_gc():
    """Disable cyclic GC for the duration; reentrant and thread-safe (the
    collector resumes when the LAST overlapping section exits)."""
    global _count, _was_enabled
    with _lock:
        if _count == 0:
            _was_enabled = gc.isenabled()
            if _was_enabled:
                gc.disable()
        _count += 1
    try:
        yield
    finally:
        with _lock:
            _count -= 1
            if _count == 0 and _was_enabled:
                gc.enable()
