"""Disruption cost functions (/root/reference/pkg/utils/disruption/disruption.go).

disruptionCost(candidate) = ReschedulingCost(all pods) x LifetimeRemaining:
cheap-to-move, soon-to-expire nodes are disrupted first.
"""

from __future__ import annotations

from typing import List, Optional

from ..api.objects import Pod

POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


def lifetime_remaining(now: float, nodeclaim) -> float:
    """Fraction of node lifetime left in [0, 1]; 1.0 without expireAfter
    (disruption.go:37-47)."""
    expire_after = nodeclaim.spec.expire_after if nodeclaim is not None else None
    if not expire_after:
        return 1.0
    age = now - nodeclaim.metadata.creation_timestamp
    return min(max((expire_after - age) / expire_after, 0.0), 1.0)


def eviction_cost(pod: Pod) -> float:
    """disruption.go:50-72: 1.0 base, deletion-cost annotation / 2^27,
    priority / 2^25, clamped to [-10, 10]."""
    cost = 1.0
    raw = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / (2 ** 27)
        except ValueError:
            pass
    if pod.spec.priority is not None:
        cost += pod.spec.priority / (2 ** 25)
    return min(max(cost, -10.0), 10.0)


def rescheduling_cost(pods: List[Pod]) -> float:
    return sum(eviction_cost(p) for p in pods)
