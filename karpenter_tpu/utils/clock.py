"""Injectable clock, mirroring the reference's clock.Clock injection
(/root/reference uses k8s.io/utils/clock everywhere; fake clocks drive
time-dependent behavior in tests — SURVEY.md §4 determinism note).

The fake clock is thread-safe and supports SLEEPERS: a thread calling
``sleep(seconds)`` blocks on a condition variable until another thread
advances the fake time past its deadline (``step``/``set_time`` wake all
sleepers; no busy-polling). This is what lets the fleet simulator (sim/)
and the real-time ``Operator.run`` loop share one code path — under a real
Clock ``sleep`` is ``time.sleep``, under a FakeClock the simulator's
accelerated advance wakes the loop instantly.
"""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        return time.time()

    def since(self, t: float) -> float:
        return self.now() - t

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock for tests and simulations: starts at a fixed
    epoch, moves only via step()/set_time(). Safe to read and advance from
    multiple threads; ``sleep`` parks the calling thread on a condition
    variable until the fake time crosses its deadline (every advance
    notifies — a sleeper is woken at most once per advance, never polled).
    """

    def __init__(self, start: float = 1_000_000.0):
        self._now = start
        self._cond = threading.Condition()
        # threads currently parked in sleep(): observable so tests can pin
        # "the sleeper is blocked on the condition variable, not spinning"
        self._sleepers = 0

    def now(self) -> float:
        with self._cond:
            return self._now

    def step(self, seconds: float) -> float:
        with self._cond:
            self._now += seconds
            self._cond.notify_all()
            return self._now

    def set_time(self, t: float) -> None:
        with self._cond:
            self._now = t
            self._cond.notify_all()

    @property
    def sleepers(self) -> int:
        with self._cond:
            return self._sleepers

    def sleep(self, seconds: float) -> None:
        """Block until the fake time advances to now + seconds (condition-
        variable wakeup from step/set_time — never a busy-poll). A zero or
        negative duration returns immediately without taking a ticket."""
        with self._cond:
            deadline = self._now + seconds
            if self._now >= deadline:
                return
            self._sleepers += 1
            try:
                while self._now < deadline:
                    self._cond.wait()
            finally:
                self._sleepers -= 1
