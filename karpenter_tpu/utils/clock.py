"""Injectable clock, mirroring the reference's clock.Clock injection
(/root/reference uses k8s.io/utils/clock everywhere; fake clocks drive
time-dependent behavior in tests — SURVEY.md §4 determinism note)."""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        return time.time()

    def since(self, t: float) -> float:
        return self.now() - t


class FakeClock(Clock):
    """Deterministic clock for tests: starts at a fixed epoch, moves only via
    step()/set_time()."""

    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def step(self, seconds: float) -> float:
        self._now += seconds
        return self._now

    def set_time(self, t: float) -> None:
        self._now = t
