"""Pod classification predicates.

Mirrors /root/reference/pkg/utils/pod/scheduling.go. In the standalone runtime
there is no kube-scheduler stamping Unschedulable conditions, so
"provisionable" reduces to: unbound, not terminating, not a daemonset pod,
and not preempting (IsProvisionable / IsReschedulable / IsEvictable /
IsWaitingEviction / IsOwnedByDaemonSet analogs)."""

from __future__ import annotations

from ..api import labels as api_labels
from ..api.objects import Pod

TERMINAL_PHASES = ("Succeeded", "Failed")


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in TERMINAL_PHASES


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_active(pod: Pod) -> bool:
    return not is_terminal(pod) and not is_terminating(pod)


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.spec.node_name)


def is_provisionable(pod: Pod) -> bool:
    """utils/pod IsProvisionable: pending, unbound, not terminating, not
    preempting, not owned by a daemonset/node."""
    return (is_active(pod)
            and not is_scheduled(pod)
            and not pod.is_daemonset_pod
            and not pod.status.nominated_node_name)


def is_reschedulable(pod: Pod) -> bool:
    """Pods that must be re-placed when their node is disrupted
    (pod.go IsReschedulable). Terminating STATEFULSET pods still count:
    their sticky identity means the replacement pod can't be created until
    the old one dies, so capacity must be modeled for it now — higher
    availability than waiting for the recreate."""
    return ((is_active(pod)
             or (is_terminating(pod) and is_owned_by_statefulset(pod)))
            and not is_owned_by_daemonset(pod)
            and not is_owned_by_node(pod))


def is_evictable(pod: Pod) -> bool:
    return is_active(pod) and not is_owned_by_node(pod)


def is_disruptable(pod: Pod) -> bool:
    """Blocks node disruption when annotated do-not-disrupt
    (pod.go IsDisruptable)."""
    return pod.metadata.annotations.get(
        api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY) != "true"


def is_owned_by_node(pod: Pod) -> bool:
    return any(ref.kind == "Node" for ref in pod.metadata.owner_refs)


def is_owned_by_daemonset(pod: Pod) -> bool:
    return pod.is_daemonset_pod or any(
        ref.kind == "DaemonSet" for ref in pod.metadata.owner_refs)


def is_owned_by_statefulset(pod: Pod) -> bool:
    return any(ref.kind == "StatefulSet" for ref in pod.metadata.owner_refs)
