"""Controller-name injection (pkg/operator/injection/injection.go analog).

The reference stores the reconciling controller's name in the context so
cross-cutting layers (the cloudprovider metrics decorator, loggers) can label
by caller without threading a parameter through every signature. A
contextvar plays the role of context.Context here; the Manager sets it
around every reconcile dispatch."""

from __future__ import annotations

import contextlib
import contextvars

_controller: contextvars.ContextVar = contextvars.ContextVar(
    "karpenter_controller", default="")


def controller_name() -> str:
    return _controller.get()


@contextlib.contextmanager
def with_controller(name: str):
    token = _controller.set(name)
    try:
        yield
    finally:
        _controller.reset(token)
