"""Disruption methods: Drift, Emptiness, Multi/Single-node consolidation.

Mirrors /root/reference/pkg/controllers/disruption/{drift,emptiness,
multinodeconsolidation,singlenodeconsolidation,consolidation}.go. The compute
order, ≤1-replacement rule, price filter, spot-to-spot floor, and budget
handling match the reference; the multi-node prefix search differs in
mechanics (see MultiNodeConsolidation docstring) while preserving the
decision rule: the largest low-disruption-cost candidate prefix replaceable
by at most one cheaper node.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import labels as api_labels
from ..api.nodeclaim import COND_CONSOLIDATABLE, COND_DRIFTED
from ..api.nodepool import (REASON_DRIFTED, REASON_EMPTY, REASON_UNDERUTILIZED,
                            WHEN_EMPTY, WHEN_EMPTY_OR_UNDERUTILIZED)
from ..events import catalog as events_catalog
from ..events.recorder import Recorder
from ..scheduling.requirement import IN, Requirement
from ..state.cluster import Cluster
from .helpers import simulate_scheduling
from .types import Candidate, CandidateError, Command


def format_sim_errors(sim_errors: Dict[str, str]) -> str:
    """Results.NonPendingPodSchedulingErrors() analog
    (scheduling/scheduler.go:163-177): one string naming every
    simulation-only pod that failed to reschedule."""
    if not sim_errors:
        return ""
    return "not all pods would schedule, " + "; ".join(
        sorted(sim_errors.values()))


def _nodeclaim_name(c: Candidate) -> str:
    nc = c.state_node.nodeclaim
    return nc.name if nc is not None else ""

MULTI_NODE_CONSOLIDATION_CANDIDATES = 100   # multinodeconsolidation.go:35
MIN_SPOT_TO_SPOT_INSTANCE_TYPES = 15        # consolidation.go:47
MULTI_NODE_CONSOLIDATION_TIMEOUT = 60.0     # multinodeconsolidation.go:35
SINGLE_NODE_CONSOLIDATION_TIMEOUT = 180.0   # singlenodeconsolidation.go:30


def _loo_min_candidates_from_env(default: int = 16) -> int:
    """KARPENTER_LOO_MIN_CANDIDATES: the eligible-candidate floor below
    which the batched leave-one-out engine's device encode costs more than
    the handful of serial probes it replaces. Rejects loudly at import —
    a typo'd knob must never silently fall back to the default."""
    import os
    raw = os.environ.get("KARPENTER_LOO_MIN_CANDIDATES")
    if raw is None:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise SystemExit(
            f"invalid KARPENTER_LOO_MIN_CANDIDATES={raw!r}: must be a "
            "non-negative integer")
    if value < 0:
        raise SystemExit(
            f"invalid KARPENTER_LOO_MIN_CANDIDATES={raw!r}: must be a "
            "non-negative integer")
    return value


# below this many eligible candidates the batched leave-one-out engine's
# device encode costs more than the handful of serial probes it replaces
# (env-overridable: KARPENTER_LOO_MIN_CANDIDATES)
SINGLE_NODE_BATCH_MIN_CANDIDATES = _loo_min_candidates_from_env()
# the closed-form multi-node subset engine is near-free (no device work on
# top of the prefix encode the search builds anyway); the floor exists for
# the fuzzer's engine-off oracle runs
MULTI_NODE_BATCH_MIN_CANDIDATES = 2


class Method:
    """types.go:46-52."""

    reason: str = ""
    consolidation_type: str = ""
    disruption_class: str = "graceful"

    def should_disrupt(self, candidate: Candidate) -> bool:
        raise NotImplementedError

    def compute_command(self, budgets: Dict[str, int],
                        candidates: List[Candidate]) -> Tuple[Command, object]:
        raise NotImplementedError


def _within_budget(budgets: Dict[str, int], candidates: List[Candidate]) -> List[Candidate]:
    """Trim a candidate list so no pool exceeds its allowed disruptions."""
    used: Dict[str, int] = {}
    out = []
    for c in candidates:
        pool = c.nodepool_name
        if used.get(pool, 0) >= budgets.get(pool, 0):
            continue
        used[pool] = used.get(pool, 0) + 1
        out.append(c)
    return out


class Emptiness(Method):
    """emptiness.go:57-122: nodes with zero reschedulable pods delete without
    simulation."""

    reason = REASON_EMPTY
    consolidation_type = "empty"

    def __init__(self, cluster: Cluster, provisioner=None, recorder=None):
        self.cluster = cluster
        self.recorder = recorder or Recorder(cluster.clock)

    def should_disrupt(self, c: Candidate) -> bool:
        policy = c.nodepool.spec.disruption.consolidation_policy
        if policy not in (WHEN_EMPTY, WHEN_EMPTY_OR_UNDERUTILIZED):
            return False
        if c.nodepool.spec.disruption.consolidate_after is None:
            # emptiness.go:46-49
            self.recorder.publish(*events_catalog.unconsolidatable(
                c.name, _nodeclaim_name(c),
                f'NodePool "{c.nodepool_name}" has consolidation disabled'))
            return False
        if c.state_node.nodeclaim is None or \
                not c.state_node.nodeclaim.conditions.is_true(COND_CONSOLIDATABLE):
            return False
        return not c.reschedulable_pods

    def compute_command(self, budgets, candidates):
        empty = [c for c in candidates if not c.reschedulable_pods]
        fitting = _within_budget(budgets, empty)
        return Command(candidates=fitting, reason=self.reason,
                       consolidation_type=self.consolidation_type), None


class Drift(Method):
    """drift.go:57-113: Drifted claims go first, oldest first; empty drifted
    nodes delete en masse, the rest one-at-a-time with a replacement sim."""

    reason = REASON_DRIFTED
    disruption_class = "eventual"

    def __init__(self, cluster: Cluster, provisioner, recorder=None):
        self.cluster = cluster
        self.provisioner = provisioner
        self.recorder = recorder or Recorder(cluster.clock)

    def should_disrupt(self, c: Candidate) -> bool:
        nc = c.state_node.nodeclaim
        return nc is not None and nc.conditions.is_true(COND_DRIFTED)

    def compute_command(self, budgets, candidates):
        candidates = sorted(
            candidates,
            key=lambda c: c.state_node.nodeclaim.metadata.creation_timestamp
            if c.state_node.nodeclaim is not None else 0.0)
        candidates = _within_budget(budgets, candidates)
        empty = [c for c in candidates if not c.reschedulable_pods]
        if empty:
            return Command(candidates=empty, reason=self.reason), None
        for c in candidates:
            try:
                results, sim_errors = simulate_scheduling(
                    self.cluster, self.provisioner, [c])
            except CandidateError:
                continue
            if sim_errors:
                # drift.go:101-106: report WHY the drifted node can't move
                self.recorder.publish(*events_catalog.disruption_blocked(
                    c.name, _nodeclaim_name(c),
                    format_sim_errors(sim_errors)))
                continue
            return Command(candidates=[c],
                           replacements=list(results.new_nodeclaims),
                           reason=self.reason), results
        return Command(reason=self.reason), None


def filter_out_same_type(replacement, candidates: List[Candidate]):
    """multinodeconsolidation.go:180-217: when the replacement's instance-type
    options include a type currently being deleted, drop every option at or
    above the cheapest such type's current price. Replacing [2xlarge, 2xlarge,
    small] with one `small` is really just deleting the two 2xlarges — the
    consolidation must be rejected (or constrained to strictly cheaper types).
    Returns the surviving instance-type options (possibly empty)."""
    from ..scheduling.requirements import label_requirements

    existing_types = set()
    price_by_type: Dict[str, float] = {}
    for c in candidates:
        if c.instance_type is None:
            continue
        existing_types.add(c.instance_type.name)
        offs = c.instance_type.offerings.compatible(
            label_requirements(c.state_node.labels()))
        if not offs:
            continue
        p = offs.cheapest().price
        if p < price_by_type.get(c.instance_type.name, float("inf")):
            price_by_type[c.instance_type.name] = p

    max_price = float("inf")
    for it in replacement.instance_type_options:
        if it.name in existing_types:
            # a candidate type with no compatible offering recorded (e.g. a
            # spot offering just pulled) prices at 0 in the reference's map
            # lookup, forcing rejection — mirror that, not +inf
            p = price_by_type.get(it.name, 0.0)
            if p < max_price:
                max_price = p
    filtered, err = replacement.remove_instance_types_by_price_and_min_values(
        replacement.requirements, max_price)
    if err is not None or filtered is None:
        return []
    return filtered.instance_type_options


class consolidation(Method):
    """consolidation.go:77-302 shared base."""

    reason = REASON_UNDERUTILIZED

    def __init__(self, cluster: Cluster, provisioner,
                 spot_to_spot_enabled: bool = False, clock=None,
                 recorder=None):
        self.cluster = cluster
        self.provisioner = provisioner
        self.spot_to_spot_enabled = spot_to_spot_enabled
        self.clock = clock or cluster.clock
        self.recorder = recorder or Recorder(self.clock)
        # per-method memoized cluster token (consolidation.go:60): each
        # method tracks the last cluster state IT found nothing in, so one
        # method marking consolidated never suppresses the others
        self._last_state: Optional[float] = None
        # the pass-shared DisruptionSnapshot, attached by the controller so
        # all methods of one pass share a single encode; None for standalone
        # callers (tests, direct use) — sims then build their own state
        self._pass_snapshot = None
        # closed-form multi-node subset engine stats of the last search
        self.last_multi_engine_stats = None

    def attach_snapshot(self, snapshot) -> None:
        self._pass_snapshot = snapshot

    def should_disrupt(self, c: Candidate) -> bool:
        """consolidation.go:85-117: the price-comparison prerequisites and
        policy gates publish Unconsolidatable so operators can see WHY a
        node never consolidates."""
        ncn = _nodeclaim_name(c)
        if c.instance_type is None:
            it_label = c.state_node.labels().get(
                api_labels.LABEL_INSTANCE_TYPE, "")
            self.recorder.publish(*events_catalog.unconsolidatable(
                c.name, ncn, f'Instance Type "{it_label}" not found'))
            return False
        if not c.capacity_type:
            self.recorder.publish(*events_catalog.unconsolidatable(
                c.name, ncn, 'Node does not have label '
                f'"{api_labels.CAPACITY_TYPE_LABEL_KEY}"'))
            return False
        if not c.zone:
            self.recorder.publish(*events_catalog.unconsolidatable(
                c.name, ncn, 'Node does not have label '
                f'"{api_labels.LABEL_TOPOLOGY_ZONE}"'))
            return False
        if c.nodepool.spec.disruption.consolidate_after is None:
            self.recorder.publish(*events_catalog.unconsolidatable(
                c.name, ncn,
                f'NodePool "{c.nodepool_name}" has consolidation disabled'))
            return False
        if c.nodepool.spec.disruption.consolidation_policy != \
                WHEN_EMPTY_OR_UNDERUTILIZED:
            self.recorder.publish(*events_catalog.unconsolidatable(
                c.name, ncn, f'NodePool "{c.nodepool_name}" has non-empty '
                'consolidation disabled'))
            return False
        nc = c.state_node.nodeclaim
        return nc is not None and nc.conditions.is_true(COND_CONSOLIDATABLE)

    def is_consolidated(self) -> bool:
        """True when nothing changed since this method last found nothing
        (consolidation.go:76-79)."""
        return self._last_state is not None and \
            self._last_state == self.cluster.consolidation_state()

    def mark_consolidated(self) -> None:
        """Record (not set) the cluster token (consolidation.go:81-84)."""
        self._last_state = self.cluster.consolidation_state()

    def _filter_disruptable(self, budgets: Dict[str, int],
                            candidates: List[Candidate]):
        """The shared pre-filter (multinodeconsolidation.go:59-77,
        singlenodeconsolidation.go:55-68): drop candidates whose nodepool
        budget is exhausted (order-preserving, decrementing as we go) and
        empty candidates (an empty node here means Emptiness was budget-
        blocked; consolidating it would bypass the `empty` budget). Returns
        (disruptable, constrained_by_budgets)."""
        remaining = dict(budgets)
        out: List[Candidate] = []
        constrained = False
        for c in candidates:
            if remaining.get(c.nodepool_name, 0) <= 0:
                constrained = True
                continue
            if not c.reschedulable_pods:
                continue
            remaining[c.nodepool_name] -= 1
            out.append(c)
        return out, constrained

    # -- core decision (consolidation.go:131-222) ---------------------------

    def compute_consolidation(self, candidates: List[Candidate]
                              ) -> Tuple[Command, object]:
        try:
            if self._pass_snapshot is not None:
                # pass-shared encode (falls back to the host solver inside
                # when the batch isn't expressible)
                results, sim_errors = self._pass_snapshot.simulate(candidates)
            else:
                results, sim_errors = simulate_scheduling(
                    self.cluster, self.provisioner, candidates)
        except CandidateError:
            return Command(reason=self.reason), None
        return self.decide(candidates, results, sim_errors)

    def _unconsolidatable_single(self, candidates: List[Candidate],
                                 reason: str) -> None:
        """consolidation.go publishes decide-stage events only in the
        single-candidate case (multi-node probes would spam every prefix)."""
        if len(candidates) == 1:
            self.recorder.publish(*events_catalog.unconsolidatable(
                candidates[0].name, _nodeclaim_name(candidates[0]), reason))

    def decide(self, candidates: List[Candidate], results, sim_errors
               ) -> Tuple[Command, object]:
        """The post-simulation decision (consolidation.go:144-222)."""
        if sim_errors:
            self._unconsolidatable_single(
                candidates, format_sim_errors(sim_errors))  # :146-149
            return Command(reason=self.reason), None
        if not results.new_nodeclaims:
            return Command(candidates=list(candidates), reason=self.reason,
                           consolidation_type=self.consolidation_type), results
        if len(results.new_nodeclaims) != 1:
            self._unconsolidatable_single(
                candidates, "Can't remove without creating "
                f"{len(results.new_nodeclaims)} candidates")  # :160-164
            return Command(reason=self.reason), None

        candidate_price = 0.0
        for c in candidates:
            p = c.price()
            if p is None:
                return Command(reason=self.reason), None
            candidate_price += p

        replacement = results.new_nodeclaims[0]
        # sort by price FIRST (consolidation.go:183): the ≥15-cheaper gate,
        # the minValues prefix, and the launch-list slice are all prefix
        # operations over a price-ordered list — host-path claims carry
        # catalog-ordered options (the tensor path happens to pre-sort)
        from ..cloudprovider.types import order_by_price
        replacement.instance_type_options = order_by_price(
            replacement.instance_type_options, replacement.requirements)
        all_spot = all(c.capacity_type == api_labels.CAPACITY_TYPE_SPOT
                       for c in candidates)
        ct_req = replacement.requirements.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        if all_spot and ct_req.has(api_labels.CAPACITY_TYPE_SPOT):
            return self._spot_to_spot(candidates, results, candidate_price)

        filtered, err = replacement.remove_instance_types_by_price_and_min_values(
            replacement.requirements, candidate_price)
        if err is not None or filtered is None:
            self._unconsolidatable_single(
                candidates, f"Filtering by price: {err}")  # :196-200
            return Command(reason=self.reason), None
        if not filtered.instance_type_options:
            self._unconsolidatable_single(
                candidates, "Can't replace with a cheaper node")  # :202-206
            return Command(reason=self.reason), None
        # OD->[OD,spot] must pin spot so a failed spot launch doesn't upgrade
        # to pricier on-demand (consolidation.go:212-219)
        ct_req = filtered.requirements.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        if ct_req.has(api_labels.CAPACITY_TYPE_SPOT) and \
                ct_req.has(api_labels.CAPACITY_TYPE_ON_DEMAND):
            filtered.requirements.add(Requirement(
                api_labels.CAPACITY_TYPE_LABEL_KEY, IN,
                [api_labels.CAPACITY_TYPE_SPOT]))
        return Command(candidates=list(candidates), replacements=[filtered],
                       reason=self.reason,
                       consolidation_type=self.consolidation_type), results

    def _spot_to_spot(self, candidates, results, candidate_price
                      ) -> Tuple[Command, object]:
        """consolidation.go:229-302."""
        if not self.spot_to_spot_enabled:
            self._unconsolidatable_single(
                candidates, "SpotToSpotConsolidation is disabled, can't "
                "replace a spot node with a spot node")  # :233-237
            return Command(reason=self.reason), None
        replacement = results.new_nodeclaims[0]
        replacement.requirements.add(Requirement(
            api_labels.CAPACITY_TYPE_LABEL_KEY, IN,
            [api_labels.CAPACITY_TYPE_SPOT]))
        filtered, err = replacement.remove_instance_types_by_price_and_min_values(
            replacement.requirements, candidate_price)
        if err is not None or filtered is None:
            self._unconsolidatable_single(
                candidates, f"Filtering by price: {err}")  # :248-252
            return Command(reason=self.reason), None
        if not filtered.instance_type_options:
            self._unconsolidatable_single(
                candidates, "Can't replace with a cheaper node")  # :254-258
            return Command(reason=self.reason), None
        if len(candidates) > 1:
            return Command(candidates=list(candidates), replacements=[filtered],
                           reason=self.reason,
                           consolidation_type=self.consolidation_type), results
        if len(filtered.instance_type_options) < MIN_SPOT_TO_SPOT_INSTANCE_TYPES:
            self._unconsolidatable_single(
                candidates, "SpotToSpotConsolidation requires "
                f"{MIN_SPOT_TO_SPOT_INSTANCE_TYPES} cheaper instance type "
                "options than the current candidate to consolidate, got "
                f"{len(filtered.instance_type_options)}")  # :274-278
            return Command(reason=self.reason), None
        # cap the launch list so the launched type is always inside it (no
        # continual-consolidation ping-pong); with minValues the cap is the
        # MAX of the default 15 and the prefix needed to satisfy minValues
        # (consolidation.go:281-296)
        cap = MIN_SPOT_TO_SPOT_INSTANCE_TYPES
        if filtered.requirements.has_min_values():
            from ..cloudprovider.types import satisfies_min_values
            needed, _ = satisfies_min_values(filtered.instance_type_options,
                                             filtered.requirements)
            cap = max(cap, needed)
        filtered.instance_type_options = filtered.instance_type_options[:cap]
        return Command(candidates=list(candidates), replacements=[filtered],
                       reason=self.reason,
                       consolidation_type=self.consolidation_type), results


class MultiNodeConsolidation(consolidation):
    """multinodeconsolidation.go:79-162.

    The reference binary-searches the largest prefix of cost-sorted candidates
    replaceable by ≤1 node, paying a full scheduling simulation per probe
    (O(log N) sims, each rebuilding scheduler state). Here the probes share
    ONE device feasibility program (disruption/prefix.py PrefixSimulator):
    prefixes differ only in which nodes are excluded and which pods are
    pending — host-side packer inputs — so the search costs one precompute
    plus O(log N) host greedy replays. Same decision, amortized device work;
    batches the kernel can't express fall back to per-probe simulation.
    """

    consolidation_type = "multi"

    def compute_command(self, budgets, candidates):
        candidates = sorted(candidates, key=lambda c: c.disruption_cost)
        candidates, constrained = self._filter_disruptable(budgets, candidates)
        candidates = candidates[:MULTI_NODE_CONSOLIDATION_CANDIDATES]
        cmd, results = self._first_n_consolidation_option(candidates)
        if cmd.is_empty() and not constrained:
            # budget-blocked candidates may free up next pass: only memoize
            # a genuine nothing-to-do (multinodeconsolidation.go:89-96)
            self.mark_consolidated()
        return cmd, results

    def _first_n_consolidation_option(self, candidates: List[Candidate]
                                      ) -> Tuple[Command, object]:
        """multinodeconsolidation.go:110-162 with shared-precompute probes
        and closed-form midpoint verdicts: a prefix the ranked subset
        engine PROVABLY rejects skips its replay entirely (the engine's
        exactness contract guarantees the replay's decide() would return
        an empty command), so the search replays only plausible prefixes
        — in the common ranked case, only the winner."""
        from ..metrics import registry as metrics
        from .prefix import PrefixFallback, PrefixSimulator

        # single candidates are SingleNodeConsolidation's job: always operate
        # on >= 2 at once (multinodeconsolidation.go:111-115)
        if len(candidates) < 2:
            return Command(reason=self.reason), None
        sim = None
        engine = None
        self.last_multi_engine_stats = None
        try:
            sim = PrefixSimulator(self.cluster, self.provisioner, candidates,
                                  snapshot=self._pass_snapshot)
        except PrefixFallback:
            pass
        except CandidateError:
            return Command(reason=self.reason), None
        if sim is not None and \
                len(candidates) >= MULTI_NODE_BATCH_MIN_CANDIDATES:
            from .batch import MultiNodeLooEngine
            from .prefix import SnapshotFallback
            try:
                engine = MultiNodeLooEngine(sim.snapshot, candidates,
                                            self.spot_to_spot_enabled)
            except (SnapshotFallback, CandidateError):
                engine = None
        deadline = self.clock.now() + MULTI_NODE_CONSOLIDATION_TIMEOUT
        # binary search on prefix size (multinodeconsolidation.go:110-162);
        # floor of 2 per the >= 2 rule above
        lo, hi = 2, len(candidates)
        best: Tuple[Command, object] = (Command(reason=self.reason), None)
        while lo <= hi:
            if self.clock.now() > deadline:
                # the shared-precompute probes are fast, but inexpressible
                # batches fall back to full per-probe simulation — bound it
                # (multinodeconsolidation.go:123-135)
                metrics.CONSOLIDATION_TIMEOUTS.inc(
                    {"consolidation_type": self.consolidation_type})
                return best
            mid = (lo + hi) // 2
            if engine is not None and engine.verdict(mid).kind == "reject":
                # provably empty without a replay (exactness contract)
                self.last_multi_engine_stats = dict(engine.stats)
                hi = mid - 1
                continue
            if sim is not None:
                results, sim_errors = sim.simulate(mid)
                cmd, results = self.decide(candidates[:mid], results,
                                           sim_errors)
            else:
                cmd, results = self.compute_consolidation(candidates[:mid])
            if not cmd.is_empty() and cmd.replacements:
                # a replacement whose type is already being deleted must be
                # strictly cheaper, else this "replace" is a worse "delete"
                cmd.replacements[0].instance_type_options = \
                    filter_out_same_type(cmd.replacements[0],
                                         candidates[:mid])
                if not cmd.replacements[0].instance_type_options:
                    cmd = Command(reason=self.reason)
            if cmd.is_empty():
                hi = mid - 1
                continue
            best = (cmd, results)
            lo = mid + 1
        if engine is not None:
            self.last_multi_engine_stats = dict(engine.stats)
        return best


class SingleNodeConsolidation(consolidation):
    """singlenodeconsolidation.go:44-101: linear scan, first win, 3-min
    timeout. Candidates are interleaved round-robin across nodepools (each
    pool's own candidates stay cost-ordered) so that when the timeout fires,
    every nodepool got a fair share of the evaluation window instead of the
    cheapest pool starving the rest."""

    consolidation_type = "single"

    @staticmethod
    def _fair_order(candidates: List[Candidate]) -> List[Candidate]:
        by_pool: Dict[str, List[Candidate]] = {}
        for c in sorted(candidates, key=lambda c: c.disruption_cost):
            by_pool.setdefault(c.nodepool_name, []).append(c)
        # pools ordered by their cheapest candidate; then round-robin
        pools = sorted(by_pool.values(), key=lambda cs: cs[0].disruption_cost)
        out: List[Candidate] = []
        for i in range(max((len(cs) for cs in pools), default=0)):
            out.extend(cs[i] for cs in pools if i < len(cs))
        return out

    def compute_command(self, budgets, candidates):
        from ..metrics import registry as metrics
        deadline = self.clock.now() + SINGLE_NODE_CONSOLIDATION_TIMEOUT
        # budget gate UP FRONT over the full fair order: the `constrained`
        # signal must cover pools the deadline would otherwise hide, so a
        # timed-out pass can never read as an exhaustive "nothing to do".
        # NOT _filter_disruptable: a single-node command disrupts exactly
        # one node, so the reference only skips zero-budget pools and never
        # decrements (singlenodeconsolidation.go:55-68) — decrementing
        # would cap the scan at B candidates per pool and starve wins
        # sitting past the cap
        eligible: List[Candidate] = []
        constrained = False
        for c in self._fair_order(candidates):
            if budgets.get(c.nodepool_name, 0) <= 0:
                constrained = True
                continue
            if not c.reschedulable_pods:
                # empty nodes are Emptiness' (budget-gated) job
                continue
            eligible.append(c)
        engine = None
        engine_tried = False
        self.last_engine_stats = None
        timed_out = False
        for idx, c in enumerate(eligible):
            if self.clock.now() > deadline:
                metrics.CONSOLIDATION_TIMEOUTS.inc(
                    {"consolidation_type": self.consolidation_type})
                timed_out = True
                break
            if not engine_tried:
                engine_tried = True
                engine = self._build_engine(eligible)
            if engine is not None:
                verdict = engine.verdict(idx)
                if verdict.kind == "reject":
                    # provably unconsolidatable without a simulation; the
                    # reason mirrors what decide() would have published
                    if verdict.reason:
                        self.recorder.publish(*events_catalog.unconsolidatable(
                            c.name, _nodeclaim_name(c), verdict.reason))
                    continue
                try:
                    results, sim_errors = engine.probe(idx)
                except CandidateError:
                    continue
                cmd, results = self.decide([c], results, sim_errors)
                self.last_engine_stats = dict(engine.stats)
                if not cmd.is_empty():
                    return cmd, results
                continue
            cmd, results = self.compute_consolidation([c])
            if not cmd.is_empty():
                return cmd, results
        if engine is not None:
            self.last_engine_stats = dict(engine.stats)
        if timed_out or constrained:
            # a timed-out or budget-constrained pass proved nothing about
            # the unseen candidates: memoizing would suppress a later pass
            # that could succeed against unchanged cluster state
            return Command(reason=self.reason), None
        self.mark_consolidated()
        return Command(reason=self.reason), None

    def _build_engine(self, eligible: List[Candidate]):
        """The batched leave-one-out classifier over the pass snapshot, or
        None when the candidate set is too small to amortize the encode or
        the batch isn't expressible (per-candidate sims take over)."""
        if len(eligible) < SINGLE_NODE_BATCH_MIN_CANDIDATES:
            return None
        from .batch import LeaveOneOutEngine
        from .prefix import DisruptionSnapshot, SnapshotFallback
        try:
            snapshot = self._pass_snapshot or DisruptionSnapshot(
                self.cluster, self.provisioner)
            return LeaveOneOutEngine(snapshot, eligible,
                                     self.spot_to_spot_enabled)
        except (SnapshotFallback, CandidateError):
            return None
