"""Shared-precompute prefix simulation for multi-node consolidation.

The reference's binary search (multinodeconsolidation.go:110-162) pays a
full scheduling simulation per probe — scheduler construction, per-pod
refiltering, the works. The TPU design runs ONE device feasibility program
covering every candidate's pods and every packable node, then evaluates each
prefix with a host-greedy replay over shared tensors:

- the feasibility tensors depend on group *signatures* and the node batch,
  both identical across prefixes — only the pod *counts* per group and the
  excluded-node set vary, and those live entirely on the host side of the
  packer;
- excluding candidates[0:mid] = dropping their indices from the packer's
  existing-node order; marking their pods pending = restricting each group's
  pod list to the prefix.

Net: O(log N) probes cost one device program + O(log N) host replays instead
of O(log N) full simulations (SURVEY.md §7 layer 7).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..api.nodepool import NodePool, order_by_weight
from ..ops import binpack
from ..provisioning.grouping import PodGroup, group_pods
from ..provisioning.provisioner import Provisioner, StateClusterView
from ..provisioning.tensor_scheduler import (TensorScheduler, _FallbackError,
                                             pad_exist_counts)
from ..state.cluster import Cluster
from ..utils import pod as pod_utils
from .types import Candidate, CandidateError


class PrefixFallback(Exception):
    """Batch not expressible in the tensor kernel: probe-per-sim instead."""


class PrefixSimulator:
    def __init__(self, cluster: Cluster, provisioner: Provisioner,
                 candidates: List[Candidate]):
        self.cluster = cluster
        self.provisioner = provisioner
        self.candidates = candidates
        for c in candidates:
            sn = cluster.nodes.get(c.provider_id)
            if sn is None or sn.deleting():
                raise CandidateError("candidate is deleting")

        base_pods = provisioner.get_pending_pods()
        from .helpers import pods_by_node
        by_node = pods_by_node(cluster)
        for sn in cluster.deleting_nodes():
            for p in by_node.get(sn.name(), []):
                if pod_utils.is_reschedulable(p):
                    base_pods.append(p)
        self.base_uids: Set[str] = {p.uid for p in base_pods}
        self.pod_uids_by_candidate = [
            {p.uid for p in c.reschedulable_pods} for c in candidates]
        sim_pods = [p for c in candidates for p in c.reschedulable_pods]
        all_pods = base_pods + sim_pods

        nodepools = order_by_weight(cluster.store.list(NodePool))
        instance_types = {
            np_.name: provisioner.cloud_provider.get_instance_types(np_)
            for np_ in nodepools}
        nodepools = [np_ for np_ in nodepools if instance_types.get(np_.name)]
        state_nodes = [sn for sn in cluster.state_nodes(deep_copy=False)
                       if not sn.deleting()]
        self.ts = TensorScheduler(
            nodepools, instance_types, state_nodes=state_nodes,
            daemonset_pods=cluster.daemonset_pod_list(),
            cluster=StateClusterView(cluster.store, cluster))

        groups, reason = group_pods(all_pods)
        if groups is None:
            raise PrefixFallback(reason)
        if any(g.has_relaxable for g in groups):
            # relaxation interplay is host-path territory
            raise PrefixFallback("relaxable preferences in batch")
        self.groups = groups
        try:
            self.problem, self.templates, self.catalog = \
                self.ts.build_problem(groups)
        except _FallbackError as e:
            raise PrefixFallback(str(e))
        self.tensors = self.ts.precompute(self.problem)
        self.node_index = {sn.name(): i
                           for i, sn in enumerate(self.ts.state_nodes)}
        self.zone_names = self.problem.vocab.values[self.problem.zone_key]

    # -- per-probe host replay ---------------------------------------------

    def simulate(self, prefix_len: int):
        """Evaluate candidates[:prefix_len]; returns (results, sim_errors)
        like helpers.simulate_scheduling."""
        prefix = self.candidates[:prefix_len]
        allowed: Set[str] = set(self.base_uids)
        excluded_nodes: Set[str] = set()
        for i, c in enumerate(prefix):
            allowed |= self.pod_uids_by_candidate[i]
            excluded_nodes.add(c.state_node.name())

        probe_groups: List[PodGroup] = []
        for g in self.groups:
            pods = [p for p in g.pods if p.uid in allowed]
            probe_groups.append(PodGroup(
                pods=pods, requirements=g.requirements, requests=g.requests,
                tolerations=g.tolerations, labels=g.labels, topo=g.topo,
                has_relaxable=g.has_relaxable))

        exist_order = [
            i for i in sorted(
                range(len(self.ts.state_nodes)),
                key=lambda i: (not self.ts.state_nodes[i].initialized(),
                               self.ts.state_nodes[i].name()))
            if self.ts.state_nodes[i].name() not in excluded_nodes]

        limits, limit_resources = self._limits(excluded_nodes)
        # per-probe domain occupancy: cluster pods matching each group's
        # topology selectors that are NOT pending in this probe still count
        # (non-prefix candidates' pods among them) — host countDomains parity
        izc, exist_counts, host_total = self.ts.cluster_topology_counts(
            probe_groups, self.zone_names, allowed)
        exist_counts = pad_exist_counts(self.problem, exist_counts)
        # CSI attach limits per probe: _volume_limit_state builds fresh
        # per-node budget dicts each call, so the packer's draw-down never
        # leaks across probes
        vol_group_counts, vol_node_remaining = \
            self.ts._volume_limit_state(probe_groups)
        packer = binpack.Packer(self.problem, self.tensors, probe_groups,
                                limits, limit_resources,
                                initial_zone_counts=izc,
                                exist_order=exist_order,
                                exist_counts=exist_counts,
                                host_match_total=host_total,
                                vol_group_counts=vol_group_counts,
                                vol_node_remaining=vol_node_remaining)
        pr = packer.pack()
        results = self.ts._materialize(
            pr, self.problem, probe_groups, self.templates, self.catalog,
            self.problem.vocab, self.problem.zone_key)
        sim_uids = allowed - self.base_uids
        sim_errors = {uid: e for uid, e in results.pod_errors.items()
                      if uid in sim_uids}
        return results, sim_errors

    def _limits(self, excluded_nodes: Set[str]):
        from ..api import labels as api_labels
        from ..ops import encode as enc
        from ..utils import resources as res
        limits: List[Optional[dict]] = []
        for nct in self.templates:
            np_obj = next(p for p in self.ts.nodepools
                          if p.name == nct.nodepool_name)
            if not np_obj.spec.limits:
                limits.append(None)
                continue
            rem = dict(np_obj.spec.limits)
            for sn in self.ts.state_nodes:
                if sn.name() in excluded_nodes:
                    continue
                if sn.labels().get(api_labels.NODEPOOL_LABEL_KEY) == \
                        nct.nodepool_name:
                    rem = res.subtract(rem, sn.capacity())
            limits.append({k: enc.scale_capacity(k, v)
                           for k, v in rem.items()})
        limit_resources = sorted({k for lm in limits if lm for k in lm})
        return limits, limit_resources
