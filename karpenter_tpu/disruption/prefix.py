"""Shared disruption snapshot + prefix simulation.

One disruption pass (controller.go:84-94) used to pay a full solver rebuild
per simulation probe: every `simulate_scheduling` call re-listed pods,
re-encoded all state nodes and the instance-type catalog, and re-ran the
device feasibility precompute from scratch. The pass-level inputs are
identical across probes — only WHICH candidates are excluded and WHICH pods
are pending change, and those live entirely on the host side of the packer.

`DisruptionSnapshot` captures the pass-level inputs ONCE:

- the pending-pod set plus the deleting-node ride-along pods (previously
  re-scanned inside every `simulate_scheduling` call, helpers.go:316-320);
- the packable (non-deleting) state nodes;
- the nodepool / instance-type / PDB context every method's candidate
  collection needs (`candidate context`);
- lazily, per candidate set: the encoded PackProblem + device feasibility
  tensors (`SnapshotEncoding`), memoized so Emptiness, MultiNode,
  SingleNode, and the validation re-check share one encode per pass
  instead of four independent `simulate_scheduling` entry points.

`SnapshotEncoding.simulate_subset` generalizes the round-3 PrefixSimulator:
any subset of the candidate set evaluates as a host-greedy replay over the
shared tensors — prefixes for the multi-node binary search, single indices
for leave-one-out single-node probes, the full set for validation. Batches
the kernel can't express raise `SnapshotFallback` and callers degrade to
per-probe `simulate_scheduling` (the round-3 fallback contract).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..api.nodepool import NodePool, order_by_weight
from ..api.objects import ObjectMeta, Pod, PodSpec
from ..obs.tracer import TRACER
from ..ops import binpack
from ..provisioning.grouping import PodGroup, group_pods
from ..provisioning.provisioner import Provisioner, StateClusterView
from ..provisioning.tensor_scheduler import (TensorScheduler, _FallbackError,
                                             _pow2_bucket, pad_exist_counts)
from ..scheduling.requirements import Requirements
from ..state.cluster import Cluster
from ..utils import pod as pod_utils
from .types import Candidate, CandidateError


class SnapshotFallback(Exception):
    """Batch not expressible in the tensor kernel: probe-per-sim instead."""


class PrefixFallback(SnapshotFallback):
    """Back-compat name for the multi-node prefix search callers."""


def exist_fill_order(state_nodes) -> List[int]:
    """THE packer existing-node fill order (initialized first, name
    tiebreak — scheduler.go:267-275 semantics): the snapshot replay walks
    it and the leave-one-out classifier's closed-form threshold math
    assumes it, so both read this one definition."""
    return sorted(range(len(state_nodes)),
                  key=lambda i: (not state_nodes[i].initialized(),
                                 state_nodes[i].name()))


def _pad_groups(groups: List[PodGroup]) -> List[PodGroup]:
    """Pad the group axis to a power-of-two bucket so successive disruption
    passes with slightly different deployment counts share compiled
    executable shapes (the solver_compile_cache hit condition). Pad groups
    carry one probe pod whose uid is never in any probe's allowed set, so
    their replayed count is always zero — build_problem sees a probe, the
    packer never places anything."""
    G = len(groups)
    bucket = _pow2_bucket(max(G, 1), 8)
    if bucket == G:
        return groups
    out = list(groups)
    for i in range(bucket - G):
        pad_pod = Pod(metadata=ObjectMeta(name=f"snapshot-pad-{i}",
                                          namespace="__snapshot_pad__"),
                      spec=PodSpec())
        out.append(PodGroup(pods=[pad_pod], requirements=Requirements(),
                            requests={}, tolerations=(), labels={}, topo=[]))
    return out


class DisruptionSnapshot:
    """Pass-level shared state for every disruption simulation.

    `stream` (disruption.stream.StreamingDisruptionState) makes the
    snapshot PERSISTENT: the stream keeps this object across passes and
    re-invokes individual layer builders (`_build_pods`, `_build_context`,
    `_build_scheduler`) only when their invalidation tokens changed,
    and threads its cross-pass ProblemState into the scheduler so node
    and group encodes are delta-applied. `prefetched` carries the
    (nodepools, instance-types, pending-pods, catalog-token) the stream
    already fetched for token capture so the layers don't re-list."""

    def __init__(self, cluster: Cluster, provisioner: Provisioner,
                 stream=None, prefetched=None):
        self.stream = stream
        self._prefetched = prefetched
        with TRACER.span("disruption.snapshot"):
            self._build(cluster, provisioner)
        self._prefetched = None

    def _build(self, cluster: Cluster, provisioner: Provisioner):
        self.cluster = cluster
        self.provisioner = provisioner
        self._build_pods(cluster, provisioner)
        self._build_context(cluster, provisioner)
        self._build_scheduler(cluster, provisioner)
        self._encodings: Dict[tuple, object] = {}

    def _build_pods(self, cluster: Cluster, provisioner: Provisioner):
        """Pod-derived layer: valid while Cluster.topo_revision, the node
        token, and the pending-pod token are unchanged."""
        from .helpers import pods_by_node
        # one store pass -> node name -> active pods (shared by candidate
        # collection AND the ride-along scan below)
        self.pods_by_node_map: Dict[str, List[Pod]] = pods_by_node(cluster)
        # the deleting-node ride-along scan, hoisted out of
        # simulate_scheduling (helpers.go:316-320): computed once per pass
        # instead of once per probe
        self.ride_along_pods: List[Pod] = []
        for sn in cluster.deleting_nodes():
            for p in self.pods_by_node_map.get(sn.name(), []):
                if pod_utils.is_reschedulable(p):
                    self.ride_along_pods.append(p)
        self.deleting_pod_uids: Set[str] = {p.uid for p in self.ride_along_pods}
        pending = (self._prefetched[2] if self._prefetched is not None
                   else provisioner.get_pending_pods())
        self.base_pods: List[Pod] = list(pending) + self.ride_along_pods
        self.base_uids: Set[str] = {p.uid for p in self.base_pods}
        self.state_nodes = [sn for sn in cluster.state_nodes(deep_copy=False)
                            if not sn.deleting()]

    def _build_context(self, cluster: Cluster, provisioner: Provisioner):
        """Candidate context: what get_candidates / validation need, built
        once per pass instead of once per method. Valid while the nodepool,
        catalog, PDB, and pod tokens are unchanged."""
        from .helpers import build_pdb_limits
        if self._prefetched is not None:
            pools, its_by_pool = self._prefetched[0], self._prefetched[1]
            self.all_nodepools = {np_.name: np_ for np_ in pools}
            self.instance_types_by_pool = dict(its_by_pool)
        else:
            self.all_nodepools = {
                np_.name: np_ for np_ in cluster.store.list(NodePool)}
            self.instance_types_by_pool = {
                name: provisioner.cloud_provider.get_instance_types(np_)
                for name, np_ in self.all_nodepools.items()}
        self.it_maps = {name: {it.name: it for it in its}
                        for name, its in self.instance_types_by_pool.items()}
        self.pdb_limits = build_pdb_limits(cluster)

    def _build_scheduler(self, cluster: Cluster, provisioner: Provisioner):
        """Solver layer: valid while the node, nodepool, catalog, and
        daemonset tokens are unchanged."""
        # solver-side nodepool view mirrors schedule_with: deleting pools
        # receive no new capacity, IT-less pools contribute nothing
        nodepools = order_by_weight(
            [np_ for np_ in self.all_nodepools.values()
             if np_.metadata.deletion_timestamp is None])
        self.nodepools = [np_ for np_ in nodepools
                          if self.instance_types_by_pool.get(np_.name)]
        # cold snapshots (validation / standalone prefix probes, no stream)
        # used to leave catalog_token unset, re-hashing ~2k instance types
        # inside EVERY build_problem the snapshot's encodings issue: compute
        # the content token ONCE per snapshot build here, over the exact
        # pool ordering handed to the scheduler (weight order, IT-less
        # dropped — the _ordered_union order contract)
        from ..provisioning.tensor_scheduler import catalog_cache_token
        catalog_token = (self._prefetched[3]
                         if self._prefetched is not None else
                         catalog_cache_token(self.nodepools,
                                             self.instance_types_by_pool))
        self.ts = TensorScheduler(
            self.nodepools,
            {np_.name: self.instance_types_by_pool[np_.name]
             for np_ in self.nodepools},
            state_nodes=self.state_nodes,
            daemonset_pods=cluster.daemonset_pod_list(),
            cluster=StateClusterView(cluster.store, cluster),
            # the unavailable-offerings mask rides into every disruption
            # encode too: consolidation must never plan a replacement onto
            # an offering a launch failure just proved dry
            unavailable=getattr(provisioner, "unavailable", None),
            # streaming: node/group encode rows are delta-applied across
            # passes through the stream's persistent ProblemState, and the
            # content-keyed catalog token computed during token capture is
            # pinned so repeated builds skip re-hashing 2k instance types
            problem_state=(self.stream.problem_state
                           if self.stream is not None else None),
            catalog_token=catalog_token)
        # candidate-build traffic: its fallback-ledger records must not
        # move the headline provisioning totals (explicit flag — the
        # tracing-based backstop is off when --trace-ring is 0)
        self.ts.ledger_subsystem = "disruption"

    # -- per-candidate-set encode (memoized) --------------------------------

    @staticmethod
    def _enc_key(candidates: Sequence[Candidate]) -> tuple:
        return tuple(sorted(
            (c.provider_id, tuple(sorted(p.uid for p in c.reschedulable_pods)))
            for c in candidates))

    def encoding_for(self, candidates: Sequence[Candidate]
                     ) -> "SnapshotEncoding":
        """Encoded problem + device tensors for base pods + these candidates'
        pods. Memoized per pod-identical candidate set; raises
        SnapshotFallback when the batch isn't expressible and CandidateError
        when a candidate's node is gone or deleting."""
        for c in candidates:
            sn = self.cluster.nodes.get(c.provider_id)
            if sn is None or sn.deleting():
                raise CandidateError("candidate is deleting")
        key = self._enc_key(candidates)
        cached = self._encodings.get(key)
        if cached is not None:
            if isinstance(cached, SnapshotFallback):
                raise cached
            cached.candidates = list(candidates)
            cached._rebind(candidates)
            return cached
        try:
            enc = SnapshotEncoding(self, candidates)
        except SnapshotFallback as e:
            self._encodings[key] = e
            raise
        self._encodings[key] = enc
        return enc

    def simulate(self, candidates: Sequence[Candidate]):
        """simulate_scheduling through the shared encode, with the host
        solver as fallback for inexpressible batches. Same (results,
        sim_errors) contract as helpers.simulate_scheduling; raises
        CandidateError on deleted/deleting candidates."""
        from .helpers import simulate_scheduling
        try:
            enc = self.encoding_for(candidates)
        except SnapshotFallback:
            return simulate_scheduling(self.cluster, self.provisioner,
                                       list(candidates),
                                       ride_along=self.ride_along_pods)
        return enc.simulate_subset(range(len(candidates)))


class SnapshotEncoding:
    """One candidate set's encoded problem over the snapshot's shared state.

    The feasibility tensors depend on group *signatures* and the node batch,
    both identical across probes — only the pod *counts* per group and the
    excluded-node set vary, and those live entirely on the host side of the
    packer (SURVEY.md §7 layer 7)."""

    def __init__(self, snapshot: DisruptionSnapshot,
                 candidates: Sequence[Candidate]):
        with TRACER.span("disruption.encode", candidates=len(candidates)):
            self._build(snapshot, candidates)

    def _build(self, snapshot: DisruptionSnapshot,
               candidates: Sequence[Candidate]):
        self.snapshot = snapshot
        self.candidates = list(candidates)
        self.pod_uids_by_candidate = [
            {p.uid for p in c.reschedulable_pods} for c in candidates]
        sim_pods = [p for c in candidates for p in c.reschedulable_pods]
        all_pods = snapshot.base_pods + sim_pods
        # PVC-carrying pods pick up their volume topology requirements
        # exactly like schedule_with does before solving
        from ..provisioning.volumetopology import \
            inject_volume_topology_requirements
        all_pods = [inject_volume_topology_requirements(
            snapshot.cluster.store, p) if p.spec.volumes else p
            for p in all_pods]

        groups, reason = group_pods(all_pods)
        if groups is None:
            raise SnapshotFallback(reason)
        if any(g.has_relaxable for g in groups):
            # relaxation interplay is host-path territory
            raise SnapshotFallback("relaxable preferences in batch")
        self.real_groups = len(groups)
        self.groups = _pad_groups(groups)
        ts = snapshot.ts
        try:
            self.problem, self.templates, self.catalog = \
                ts.build_problem(self.groups)
        except _FallbackError as e:
            raise SnapshotFallback(str(e))
        self.tensors = ts.precompute(self.problem)
        self.node_index = {sn.name(): i for i, sn in enumerate(ts.state_nodes)}
        self.zone_names = self.problem.vocab.values[self.problem.zone_key]
        self.uid_group = {p.uid: gi for gi, g in enumerate(self.groups)
                          for p in g.pods}

    def _rebind(self, candidates: Sequence[Candidate]) -> None:
        """A memo hit may carry pod-identical but object-distinct candidates
        (validation rebuilds them fresh): rebind the uid sets in order."""
        self.pod_uids_by_candidate = [
            {p.uid for p in c.reschedulable_pods} for c in candidates]

    # -- per-probe host replay ---------------------------------------------

    def simulate_subset(self, idxs) -> Tuple[object, Dict[str, str]]:
        """Evaluate the candidate subset `idxs` (positions into the encoded
        candidate list); returns (results, sim_errors) like
        helpers.simulate_scheduling, including the uninitialized-node
        rejection (helpers.go:93-111)."""
        idxs = list(idxs)
        with TRACER.span("disruption.sim", subset=len(idxs)):
            return self._simulate_subset(idxs)

    def _simulate_subset(self, idxs) -> Tuple[object, Dict[str, str]]:
        snap = self.snapshot
        ts = snap.ts
        allowed: Set[str] = set(snap.base_uids)
        excluded_nodes: Set[str] = set()
        for i in idxs:
            allowed |= self.pod_uids_by_candidate[i]
            excluded_nodes.add(self.candidates[i].state_node.name())

        probe_groups: List[PodGroup] = []
        for g in self.groups:
            pods = [p for p in g.pods if p.uid in allowed]
            probe_groups.append(PodGroup(
                pods=pods, requirements=g.requirements, requests=g.requests,
                tolerations=g.tolerations, labels=g.labels, topo=g.topo,
                has_relaxable=g.has_relaxable, host_ports=g.host_ports))

        exist_order = [i for i in exist_fill_order(ts.state_nodes)
                       if ts.state_nodes[i].name() not in excluded_nodes]

        limits, limit_resources = self._limits(excluded_nodes)
        # per-probe domain occupancy: cluster pods matching each group's
        # topology selectors that are NOT pending in this probe still count
        # (non-subset candidates' pods among them) — host countDomains parity
        izc, exist_counts, host_total = ts.cluster_topology_counts(
            probe_groups, self.zone_names, allowed)
        exist_counts = pad_exist_counts(self.problem, exist_counts)
        # CSI attach limits per probe: _volume_limit_state builds fresh
        # per-node budget dicts each call, so the packer's draw-down never
        # leaks across probes
        vol_group_counts, vol_node_remaining = \
            ts._volume_limit_state(probe_groups)
        packer = binpack.Packer(self.problem, self.tensors, probe_groups,
                                limits, limit_resources,
                                initial_zone_counts=izc,
                                exist_order=exist_order,
                                exist_counts=exist_counts,
                                host_match_total=host_total,
                                vol_group_counts=vol_group_counts,
                                vol_node_remaining=vol_node_remaining)
        pr = packer.pack()
        results = ts._materialize(
            pr, self.problem, probe_groups, self.templates, self.catalog,
            self.problem.vocab, self.problem.zone_key)
        from .helpers import stamp_uninitialized_errors
        stamp_uninitialized_errors(results, snap.deleting_pod_uids)
        sim_uids = allowed - snap.base_uids
        sim_errors = {uid: e for uid, e in results.pod_errors.items()
                      if uid in sim_uids}
        return results, sim_errors

    def _limits(self, excluded_nodes: Set[str]):
        from ..api import labels as api_labels
        from ..ops import encode as enc
        from ..utils import resources as res
        ts = self.snapshot.ts
        limits: List[Optional[dict]] = []
        for nct in self.templates:
            np_obj = next(p for p in ts.nodepools
                          if p.name == nct.nodepool_name)
            if not np_obj.spec.limits:
                limits.append(None)
                continue
            rem = dict(np_obj.spec.limits)
            for sn in ts.state_nodes:
                if sn.name() in excluded_nodes:
                    continue
                if sn.labels().get(api_labels.NODEPOOL_LABEL_KEY) == \
                        nct.nodepool_name:
                    rem = res.subtract(rem, sn.capacity())
            limits.append({k: enc.scale_capacity(k, v)
                           for k, v in rem.items()})
        limit_resources = sorted({k for lm in limits if lm for k in lm})
        return limits, limit_resources


class PrefixSimulator:
    """Prefix probes for the multi-node binary search
    (multinodeconsolidation.go:110-162) over the shared snapshot: O(log N)
    probes cost one device program + O(log N) host replays instead of
    O(log N) full simulations."""

    def __init__(self, cluster: Cluster, provisioner: Provisioner,
                 candidates: List[Candidate],
                 snapshot: Optional[DisruptionSnapshot] = None):
        self.snapshot = snapshot if snapshot is not None \
            else DisruptionSnapshot(cluster, provisioner)
        try:
            self.enc = self.snapshot.encoding_for(candidates)
        except SnapshotFallback as e:
            raise PrefixFallback(str(e))
        self.candidates = candidates

    def simulate(self, prefix_len: int):
        """Evaluate candidates[:prefix_len]; returns (results, sim_errors)
        like helpers.simulate_scheduling."""
        return self.enc.simulate_subset(range(prefix_len))
