"""Command validation after the consolidation TTL.

Mirrors /root/reference/pkg/controllers/disruption/validation.go:83-215: a
computed command executes only after a 15 s TTL (consolidation.go:44) and
re-validation: the candidates must still be disruptable, the budgets must
still admit them, and for replace commands a fresh simulation must produce
at most one replacement whose instance types are a subset of the original
options (so the cluster didn't move under the decision).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..provisioning.provisioner import Provisioner
from ..state.cluster import Cluster
from .helpers import build_disruption_budget_mapping
from .types import Candidate, CandidateError, Command, new_candidate

CONSOLIDATION_TTL_SECONDS = 15.0  # consolidation.go:44


def validate_command(cluster: Cluster, provisioner: Provisioner,
                     command: Command, reason: str,
                     disrupting_provider_ids=(), snapshot=None) -> bool:
    """validation.go ValidateCandidates + ValidateCommand.

    `snapshot` (disruption.prefix.DisruptionSnapshot) shares the validation
    pass's encode: the fresh-candidate context comes from one store pass
    and the re-check simulation replays over the shared tensors instead of
    rebuilding the solver; None builds one here."""
    from .prefix import DisruptionSnapshot

    now = cluster.clock.now()
    if snapshot is None:
        snapshot = DisruptionSnapshot(cluster, provisioner)

    fresh: List[Candidate] = []
    for c in command.candidates:
        sn = cluster.nodes.get(c.provider_id)
        if sn is None:
            return False
        try:
            fresh.append(new_candidate(
                now, sn, snapshot.pods_by_node_map.get(sn.name(), []),
                snapshot.pdb_limits, snapshot.all_nodepools,
                snapshot.it_maps, disrupting_provider_ids))
        except CandidateError:
            return False

    budgets = build_disruption_budget_mapping(cluster, reason)
    per_pool: Dict[str, int] = {}
    for c in fresh:
        per_pool[c.nodepool_name] = per_pool.get(c.nodepool_name, 0) + 1
    for pool, n in per_pool.items():
        if n > budgets.get(pool, 0):
            return False

    if not command.replacements:
        # delete-only: candidates must still pack onto the rest of the
        # cluster with zero new nodes (emptiness: zero reschedulable pods)
        if all(not c.reschedulable_pods for c in fresh):
            return True
        try:
            results, sim_errors = snapshot.simulate(fresh)
        except CandidateError:
            return False
        return not sim_errors and not results.new_nodeclaims

    # replace: the fresh sim must still want exactly one new node, and the
    # command's (price-filtered) instance types must be a subset of the fresh
    # (unfiltered) options — otherwise the cluster moved and the launch could
    # be as or more expensive (validation.go:155-215)
    try:
        results, sim_errors = snapshot.simulate(fresh)
    except CandidateError:
        return False
    if sim_errors:
        return False
    if len(results.new_nodeclaims) != 1:
        return False  # 0 => better option exists now; >1 => never valid
    command_names = {it.name for r in command.replacements
                     for it in r.instance_type_options}
    fresh_names = {it.name
                   for it in results.new_nodeclaims[0].instance_type_options}
    return bool(command_names) and command_names.issubset(fresh_names)
