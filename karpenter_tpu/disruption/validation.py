"""Command validation after the consolidation TTL.

Mirrors /root/reference/pkg/controllers/disruption/validation.go:83-215: a
computed command executes only after a 15 s TTL (consolidation.go:44) and
re-validation: the candidates must still be disruptable, the budgets must
still admit them, and for replace commands a fresh simulation must produce
at most one replacement whose instance types are a subset of the original
options (so the cluster didn't move under the decision).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api.nodepool import NodePool
from ..provisioning.provisioner import Provisioner
from ..state.cluster import Cluster
from .helpers import (build_disruption_budget_mapping, build_pdb_limits,
                      get_candidates, pods_on_node, simulate_scheduling)
from .types import Candidate, CandidateError, Command, new_candidate

CONSOLIDATION_TTL_SECONDS = 15.0  # consolidation.go:44


def validate_command(cluster: Cluster, provisioner: Provisioner,
                     command: Command, reason: str,
                     disrupting_provider_ids=()) -> bool:
    """validation.go ValidateCandidates + ValidateCommand."""
    now = cluster.clock.now()
    nodepools = {np.name: np for np in cluster.store.list(NodePool)}
    instance_types = {
        name: {it.name: it
               for it in provisioner.cloud_provider.get_instance_types(np)}
        for name, np in nodepools.items()}
    pdb_limits = build_pdb_limits(cluster)

    fresh: List[Candidate] = []
    for c in command.candidates:
        sn = cluster.nodes.get(c.provider_id)
        if sn is None:
            return False
        try:
            fresh.append(new_candidate(
                now, sn, pods_on_node(cluster, sn), pdb_limits, nodepools,
                instance_types, disrupting_provider_ids))
        except CandidateError:
            return False

    budgets = build_disruption_budget_mapping(cluster, reason)
    per_pool: Dict[str, int] = {}
    for c in fresh:
        per_pool[c.nodepool_name] = per_pool.get(c.nodepool_name, 0) + 1
    for pool, n in per_pool.items():
        if n > budgets.get(pool, 0):
            return False

    if not command.replacements:
        # delete-only: candidates must still pack onto the rest of the
        # cluster with zero new nodes (emptiness: zero reschedulable pods)
        if all(not c.reschedulable_pods for c in fresh):
            return True
        try:
            results, sim_errors = simulate_scheduling(cluster, provisioner,
                                                      fresh)
        except CandidateError:
            return False
        return not sim_errors and not results.new_nodeclaims

    # replace: the fresh sim must still want exactly one new node, and the
    # command's (price-filtered) instance types must be a subset of the fresh
    # (unfiltered) options — otherwise the cluster moved and the launch could
    # be as or more expensive (validation.go:155-215)
    try:
        results, sim_errors = simulate_scheduling(cluster, provisioner, fresh)
    except CandidateError:
        return False
    if sim_errors:
        return False
    if len(results.new_nodeclaims) != 1:
        return False  # 0 => better option exists now; >1 => never valid
    command_names = {it.name for r in command.replacements
                     for it in r.instance_type_options}
    fresh_names = {it.name
                   for it in results.new_nodeclaims[0].instance_type_options}
    return bool(command_names) and command_names.issubset(fresh_names)
