"""Streaming disruption engine: persistent delta-applied snapshots +
columnar candidate construction.

Every disruption pass used to rebuild the whole world from scratch: one
full `DisruptionSnapshot` (pod store scan, nodepool + catalog fetch, PDB
limits, a fresh TensorScheduler whose every encode re-encoded 50k node
label sets), then FOUR `get_candidates` sweeps — each one deep-copying
every state node and re-running the per-pod do-not-disrupt + PDB scans —
and one `build_disruption_budget_mapping` fleet scan per method. At fleet
scale the simulator's attribution (PR 12) shows this candidate build
dominating the pass.

`StreamingDisruptionState` lives across passes (owned by the
`DisruptionController`) and turns the pass into a delta application, keyed
on the same change signals the provisioning `ProblemState` already uses:

- **snapshot layers** — the pass-shared `DisruptionSnapshot` persists; its
  layers rebuild independently: the pod maps (pods-by-node, ride-along,
  base pods) against ``Cluster.topo_revision`` + the pending-pod token,
  the candidate context (nodepools, instance types, PDB limits) against
  store resource-version tokens + the content-keyed catalog token, and the
  TensorScheduler against the node/pool/catalog/daemonset tokens.
- **node-row encodes** — the snapshot's scheduler owns a persistent
  `provisioning.problem_state.ProblemState`: per-node encoded rows keyed
  by ``StateNode.revision`` bumps, group rows keyed by content-stable
  ``grouping.group_signature``, so a warm pass re-encodes only dirty rows
  and reuses the pow2-padded exist stack + its device upload.
- **encodings** — the per-candidate-set `SnapshotEncoding` memo (problem +
  device feasibility tensors) survives passes whose inputs are untouched:
  a fully idle 10s poll re-simulates over last pass's tensors at zero
  encode cost.
- **candidate rows** — the expensive per-node candidate work (the state
  node deep copy, the per-pod do-not-disrupt + PDB eviction scans, the
  rescheduling-cost fold, condition flags) is cached per node keyed on
  ``(identity, revision)`` + the node's pod token + the PDB token. The
  cheap, time-varying gates (nomination windows, deletion marks,
  already-disrupting membership) are evaluated live each pass as masks
  over the row columns, and per-pool budget accounting is one vectorized
  ``bincount`` over the pool-index column instead of a fleet scan per
  method.

Invalidation matrix — every delta a pass can carry, and what it re-derives
(DEVIATIONS 24; anything outside the matrix falls back to a cold rebuild,
which is always decision-equivalent by construction):

| delta                                | effect                            |
|--------------------------------------|-----------------------------------|
| nothing changed (idle poll)           | everything reused: pod maps,      |
|                                       | context, scheduler, encodings,    |
|                                       | candidate rows                    |
| scheduled-pod change (topo_revision)  | pod maps + PDB limits + encodings |
|                                       | rebuilt; only the bound node's    |
|                                       | candidate row + encode row        |
|                                       | re-derive (its available()        |
|                                       | moved); all other node encodes    |
|                                       | reused via ProblemState           |
| pending-pod change (pending token)    | base pods + encodings rebuilt;    |
|                                       | candidate rows untouched unless   |
|                                       | PDB-sensitive                     |
| node add/remove/update (revision)     | that node's candidate row +       |
|                                       | encode row re-derive; exist stack |
|                                       | restacks; encodings rebuilt       |
| PDB change (resource version)         | PDB limits rebuilt + every row's  |
|                                       | eviction verdict re-derives (a    |
|                                       | new PDB can block any node);      |
|                                       | encodings KEPT (sims never read   |
|                                       | PDBs)                             |
| nodepool edit / budget change         | context + scheduler + encodings   |
|                                       | rebuilt; budget columns re-derive |
|                                       | (budgets themselves are computed  |
|                                       | per pass — schedules are          |
|                                       | time-dependent)                   |
| catalog/vocab change (content token)  | cold: context + scheduler +       |
|                                       | encodings rebuilt, ProblemState   |
|                                       | node/group rows re-encode against |
|                                       | the new vocabulary                |
| daemonset set change                  | scheduler + encodings rebuilt     |
| unavailable-offerings version bump    | encodings rebuilt (drought masks  |
|                                       | ride every encode)                |
| nomination / deletion-mark flips      | never cached: evaluated live as   |
|                                       | per-pass mask columns             |

Decisions are bit-identical to a cold `DisruptionSnapshot` +
`helpers.get_candidates` rebuild BY CONTRACT: the streaming fuzzer
(tests/test_streaming_disruption.py) interleaves pod churn, node churn,
PDB edits, nodepool edits and drift marks and asserts command equality at
every step, and the disruption-scale bench samples cold-vs-warm parity
in-line.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from ..api import labels as api_labels
from ..api.nodeclaim import COND_INSTANCE_TERMINATING
from ..api.nodepool import NodePool
from ..api.policy import PodDisruptionBudget
from ..events import catalog as events_catalog
from ..obs.tracer import TRACER
from ..utils import disruption as disruption_utils
from ..utils import pod as pod_utils
from .types import (EVENTUAL, Candidate, CandidateError,
                    PodBlockEvictionError, _validate_pods_disruptable)


class _NodeRow:
    """Cached per-node candidate derivation: everything expensive about
    `types.new_candidate` that the row tokens can prove unchanged."""

    __slots__ = ("token", "static_err", "pool_name", "zone", "capacity_type",
                 "it_name", "sn_copy", "resched", "resched_cost", "pods_err",
                 "tgp", "managed_init", "terminating", "not_ready")

    def __init__(self):
        self.token = None


class StreamingDisruptionState:
    """Cross-pass disruption memory. NOT thread-safe: owned by the
    single-threaded disruption controller loop (or a bench/fuzzer driver).
    """

    def __init__(self, plane=None):
        # subscribe to the cluster's shared EncodePlane when the controller
        # hands one over (node/group rows encoded once for provisioning AND
        # disruption); a bare construction keeps a private plane, byte-
        # identical to the historical private ProblemState (standalone
        # drivers, fuzzers).
        from ..provisioning.problem_state import ProblemState
        self.problem_state = (plane.subscribe("disruption")
                              if plane is not None else ProblemState())
        self._snapshot = None
        self._cluster = None
        self._provisioner = None
        # layer tokens of the snapshot currently held
        self._tok: dict = {}
        # (name, identity) -> _NodeRow
        self._rows: Dict[tuple, _NodeRow] = {}
        # per-pass working state
        self._nodes: list = []                 # sorted live StateNodes
        self._deleting: Optional[np.ndarray] = None
        self._pods_tok_by_node: Dict[str, tuple] = {}
        self._col_tok = None
        self._pool_names: List[str] = []
        self._col_pool: Optional[np.ndarray] = None
        self._col_counted: Optional[np.ndarray] = None
        self._col_notready: Optional[np.ndarray] = None
        self.last: dict = {}
        self.stats = {
            "passes": 0, "rows_reused": 0, "rows_rebuilt": 0,
            "layer_pods_reused": 0, "layer_context_reused": 0,
            "layer_scheduler_reused": 0, "encodings_kept": 0,
        }

    # -- pass refresh --------------------------------------------------------

    def refresh(self, cluster, provisioner):
        """Per-pass entry point: delta-apply every layer and return the
        pass-shared DisruptionSnapshot."""
        with TRACER.span("disruption.stream") as sp:
            snap = self._refresh(cluster, provisioner, sp)
        return snap

    def _refresh(self, cluster, provisioner, sp):
        from ..metrics import registry as metrics
        from ..provisioning.problem_state import ProblemState
        from ..provisioning.tensor_scheduler import catalog_cache_token
        from .prefix import DisruptionSnapshot

        t0 = time.perf_counter()
        self.stats["passes"] += 1
        self.last = {"layers": {}, "rows_reused": 0, "rows_rebuilt": 0}

        nodes = cluster.state_nodes(deep_copy=False)
        deleting = np.fromiter((sn.deleting() for sn in nodes), dtype=bool,
                               count=len(nodes))
        node_tok = tuple(
            (sn.name(), sn.identity, sn.revision, bool(d))
            for sn, d in zip(nodes, deleting))
        pools = sorted(cluster.store.list(NodePool), key=lambda p: p.name)
        pool_tok = tuple((p.name, p.metadata.uid,
                          p.metadata.resource_version,
                          p.metadata.deletion_timestamp is None)
                         for p in pools)
        pdbs = cluster.store.list(PodDisruptionBudget)
        pdb_tok = tuple(sorted((p.metadata.uid, p.metadata.resource_version)
                               for p in pdbs))
        pending = provisioner.get_pending_pods()
        pending_tok = tuple((p.uid, p.metadata.resource_version)
                            for p in pending)
        ds_tok = ProblemState._daemon_token(cluster.daemonset_pod_list())
        topo = cluster.topo_revision
        ua = getattr(provisioner, "unavailable", None)
        # live() PRUNES lapsed TTL entries before reading: the token must
        # describe the pattern set an encode built right now would mask
        # with — the raw version counter only bumps when something prunes
        # it, so a lapsed entry with no intervening provisioner reconcile
        # would otherwise keep a stale drought mask alive in reused
        # encodings (diverging from a cold rebuild)
        ua_ver = ua.live() if ua is not None else None
        # the catalog is content-keyed every pass (providers may mutate
        # instance types in place — same contract as build_problem's
        # per-call hashing, computed once here and pinned on the
        # scheduler). The token MUST be computed over the SAME pool
        # ordering _build_scheduler hands the scheduler (weight order,
        # IT-less pools dropped): _ordered_union is order-sensitive, and a
        # token for a differently-ordered union would key the device
        # encoding cache with misaligned instance-type columns.
        from ..api.nodepool import order_by_weight
        its_by_pool = {p.name: provisioner.cloud_provider.get_instance_types(p)
                       for p in pools}
        solver_pools = [
            p for p in order_by_weight(
                [p for p in pools if p.metadata.deletion_timestamp is None])
            if its_by_pool.get(p.name)]
        catalog_tok = catalog_cache_token(solver_pools, its_by_pool)

        old = self._tok
        snap = self._snapshot
        cold = (snap is None or self._cluster is not cluster
                or self._provisioner is not provisioner)

        pods_valid = (not cold and old.get("topo") == topo
                      and old.get("node") == node_tok
                      and old.get("pending") == pending_tok)
        ctx_valid = (not cold and old.get("pool") == pool_tok
                     and old.get("catalog") == catalog_tok
                     and old.get("pdb") == pdb_tok
                     and old.get("topo") == topo
                     and old.get("pending") == pending_tok)
        ts_valid = (not cold and old.get("node") == node_tok
                    and old.get("pool") == pool_tok
                    and old.get("catalog") == catalog_tok
                    and old.get("ds") == ds_tok)
        enc_valid = (pods_valid and ts_valid
                     and old.get("ua") == ua_ver)

        self.problem_state.begin_solve()
        if cold:
            snap = DisruptionSnapshot(cluster, provisioner, stream=self,
                                      prefetched=(pools, its_by_pool,
                                                  pending, catalog_tok))
            self._snapshot = snap
            self._cluster = cluster
            self._provisioner = provisioner
            pods_valid = ctx_valid = ts_valid = enc_valid = False
        else:
            snap._prefetched = (pools, its_by_pool, pending, catalog_tok)
            if not pods_valid:
                snap._build_pods(cluster, provisioner)
            if not ctx_valid:
                snap._build_context(cluster, provisioner)
            if not ts_valid:
                snap._build_scheduler(cluster, provisioner)
            if not enc_valid:
                snap._encodings = {}
            snap._prefetched = None

        for layer, valid in (("pods", pods_valid), ("context", ctx_valid),
                             ("scheduler", ts_valid),
                             ("encodings", enc_valid)):
            outcome = "reused" if valid else "rebuilt"
            self.last["layers"][layer] = outcome
            metrics.DISRUPTION_STREAM_LAYERS.inc(
                {"layer": layer, "outcome": outcome})
            if valid:
                self.stats[f"layer_{layer}_reused" if layer != "encodings"
                           else "encodings_kept"] += 1

        self._nodes = nodes
        self._deleting = deleting
        self._refresh_rows(cluster, snap, node_tok, topo, pdb_tok,
                           pending_tok)
        self._tok = {"node": node_tok, "pool": pool_tok, "pdb": pdb_tok,
                     "pending": pending_tok, "ds": ds_tok, "topo": topo,
                     "catalog": catalog_tok, "ua": ua_ver}
        elapsed = time.perf_counter() - t0
        metrics.DISRUPTION_CANDIDATE_BUILD.observe(elapsed)
        self.last["seconds"] = elapsed
        sp.set(nodes=len(nodes), rows_rebuilt=self.last["rows_rebuilt"],
               rows_reused=self.last["rows_reused"],
               encodings="kept" if enc_valid else "cleared")
        return snap

    # -- candidate rows ------------------------------------------------------

    def _refresh_rows(self, cluster, snap, node_tok, topo, pdb_tok,
                      pending_tok) -> None:
        if self._tok.get("topo") == topo and self._pods_tok_by_node:
            pods_tok_by_node = self._pods_tok_by_node
        else:
            pods_tok_by_node = {
                name: tuple((p.uid, p.metadata.resource_version)
                            for p in pods)
                for name, pods in snap.pods_by_node_map.items()}
            self._pods_tok_by_node = pods_tok_by_node

        rebuilt = reused = 0
        fresh: Dict[tuple, _NodeRow] = {}
        rows = self._rows
        for sn in self._nodes:
            key = (sn.name(), sn.identity)
            ptok = pods_tok_by_node.get(sn.name(), ())
            row = rows.get(key)
            tok = (sn.revision, ptok, pdb_tok)
            if row is not None and row.token == tok:
                fresh[key] = row
                reused += 1
                continue
            row = self._build_row(sn, snap, tok)
            fresh[key] = row
            rebuilt += 1
        self._rows = fresh
        self.last["rows_rebuilt"] = rebuilt
        self.last["rows_reused"] = reused
        self.stats["rows_rebuilt"] += rebuilt
        self.stats["rows_reused"] += reused
        from ..metrics import registry as metrics
        if rebuilt:
            metrics.DISRUPTION_STREAM_ROWS.inc({"outcome": "rebuilt"},
                                               rebuilt)
        if reused:
            metrics.DISRUPTION_STREAM_ROWS.inc({"outcome": "reused"}, reused)
        self._assemble_columns()

    def _build_row(self, sn, snap, tok) -> _NodeRow:
        row = _NodeRow()
        row.token = tok
        labels = sn.labels()
        nc = sn.nodeclaim
        row.pool_name = sn.nodepool_name()
        row.zone = labels.get(api_labels.LABEL_TOPOLOGY_ZONE, "")
        row.capacity_type = labels.get(api_labels.CAPACITY_TYPE_LABEL_KEY, "")
        row.it_name = labels.get(api_labels.LABEL_INSTANCE_TYPE, "")
        # the static slice of validate_node_disruptable (statenode.go:183-
        # 208 order); nomination and deletion are time/mark-varying and
        # evaluated live each pass
        if nc is None:
            row.static_err = "node isn't managed by a nodeclaim"
        elif sn.annotations().get(
                api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true":
            row.static_err = (
                "disruption is blocked through the "
                f"{api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY} annotation")
        elif not sn.initialized():
            row.static_err = "node is not initialized"
        else:
            row.static_err = None
        pods = snap.pods_by_node_map.get(sn.name(), [])
        row.pods_err = _validate_pods_disruptable(pods, snap.pdb_limits)
        row.tgp = (nc.spec.termination_grace_period
                   if nc is not None else None)
        row.resched = [p for p in pods if pod_utils.is_reschedulable(p)]
        row.resched_cost = disruption_utils.rescheduling_cost(pods)
        row.sn_copy = sn.deep_copy()
        row.managed_init = bool(row.pool_name) and sn.managed() and \
            sn.initialized()
        row.terminating = nc is not None and \
            nc.conditions.is_true(COND_INSTANCE_TERMINATING)
        from .helpers import _node_not_ready
        row.not_ready = _node_not_ready(sn)
        return row

    def _assemble_columns(self) -> None:
        """The budget-accounting mask columns: pool index, counted
        (managed+initialized+not-terminating), and not-ready — one
        ``bincount`` replaces the per-method fleet scan."""
        nodes = self._nodes
        rows = self._rows
        pool_idx: Dict[str, int] = {}
        names: List[str] = []
        col_pool = np.empty(len(nodes), dtype=np.int64)
        col_counted = np.zeros(len(nodes), dtype=bool)
        col_notready = np.zeros(len(nodes), dtype=bool)
        for i, sn in enumerate(nodes):
            row = rows[(sn.name(), sn.identity)]
            pool = row.pool_name
            j = pool_idx.get(pool)
            if j is None:
                j = pool_idx[pool] = len(names)
                names.append(pool)
            col_pool[i] = j
            col_counted[i] = row.managed_init and not row.terminating
            col_notready[i] = row.not_ready
        self._pool_names = names
        self._col_pool = col_pool
        self._col_counted = col_counted
        self._col_notready = col_notready

    # -- columnar budget mapping --------------------------------------------

    def budget_mapping(self, reason: str, recorder=None) -> Dict[str, int]:
        """helpers.build_disruption_budget_mapping over the assembled
        columns: allowed = budget - already-disrupting per pool, with the
        node counting done as masked bincounts instead of a fleet scan."""
        cluster = self._cluster
        now = cluster.clock.now()
        P = len(self._pool_names)
        counted = self._col_counted
        disrupting_mask = counted & (self._deleting | self._col_notready)
        per_pool = np.bincount(self._col_pool[counted], minlength=P) \
            if counted.any() else np.zeros(P, dtype=np.int64)
        disrupting = np.bincount(self._col_pool[disrupting_mask],
                                 minlength=P) \
            if disrupting_mask.any() else np.zeros(P, dtype=np.int64)
        idx = {name: i for i, name in enumerate(self._pool_names)}
        allowed: Dict[str, int] = {}
        for np_ in cluster.store.list(NodePool):
            i = idx.get(np_.name)
            n_nodes = int(per_pool[i]) if i is not None else 0
            total = np_.allowed_disruptions(now, n_nodes, reason)
            dis = int(disrupting[i]) if i is not None else 0
            allowed[np_.name] = max(0, total - dis)
            if recorder is not None and n_nodes != 0 and total == 0:
                recorder.publish(
                    events_catalog.nodepool_blocked_for_reason(np_.name,
                                                               reason))
        return allowed

    # -- columnar candidate construction ------------------------------------

    def candidates_for(self, should_disrupt, disrupting_provider_ids=(),
                       disruption_class: str = "graceful",
                       recorder=None) -> List[Candidate]:
        """helpers.get_candidates over the cached rows: the per-node deep
        copies, pod scans and PDB verdicts come from the row cache; only
        the cheap time-varying gates evaluate live. Bit-identical output
        (candidates, order, blocked events) to the cold path."""
        snap = self._snapshot
        cluster = self._cluster
        now = cluster.clock.now()
        with TRACER.span("disruption.candidates") as sp:
            out = self._candidates(should_disrupt, disrupting_provider_ids,
                                   disruption_class, recorder, snap,
                                   cluster, now)
            sp.set(candidates=len(out))
        return out

    def _candidates(self, should_disrupt, disrupting_provider_ids,
                    disruption_class, recorder, snap, cluster, now):
        out: List[Candidate] = []
        rows = self._rows
        nodepools = snap.all_nodepools
        it_maps = snap.it_maps
        for i, sn in enumerate(self._nodes):
            row = rows[(sn.name(), sn.identity)]
            err = row.static_err
            if err is None:
                if sn.nominated(now):
                    err = "node is nominated for a pending pod"
                elif self._deleting[i]:
                    err = "node is deleting or marked for deletion"
                elif sn.provider_id in disrupting_provider_ids:
                    err = "candidate is already being disrupted"
                elif row.pool_name not in nodepools or \
                        row.pool_name not in it_maps:
                    err = (f'nodepool "{row.pool_name}" can\'t be resolved '
                           "for state node")
                elif row.pods_err is not None and not (
                        disruption_class == EVENTUAL
                        and row.tgp is not None
                        and isinstance(row.pods_err, PodBlockEvictionError)):
                    err = str(row.pods_err)
            if err is not None:
                if recorder is not None and sn.nodeclaim is not None:
                    recorder.publish(*events_catalog.disruption_blocked(
                        sn.name(), sn.nodeclaim.name, err))
                continue
            nc = sn.nodeclaim
            cand = Candidate(
                state_node=row.sn_copy,
                nodepool=nodepools[row.pool_name],
                instance_type=it_maps[row.pool_name].get(row.it_name),
                zone=row.zone,
                capacity_type=row.capacity_type,
                reschedulable_pods=row.resched,
                disruption_cost=(row.resched_cost *
                                 disruption_utils.lifetime_remaining(now, nc)))
            if should_disrupt(cand):
                out.append(cand)
        return out
