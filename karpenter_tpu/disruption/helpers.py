"""Candidate collection, budgets, and the simulation bridge.

Mirrors /root/reference/pkg/controllers/disruption/helpers.go:
- SimulateScheduling (:49-113): re-run the provisioning solver with the
  candidates' nodes removed and their reschedulable pods in the pending set;
- GetCandidates (:144-161): every disruptable StateNode as a Candidate;
- BuildDisruptionBudgetMapping (:197-245): per-nodepool allowed disruptions
  minus nodes already disrupting.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api.nodeclaim import COND_INSTANCE_TERMINATING
from ..api.nodepool import NodePool
from ..api.objects import Pod
from ..api.policy import PodDisruptionBudget
from ..events import catalog as events_catalog
from ..provisioning.provisioner import Provisioner
from ..state.cluster import Cluster
from ..utils import node as node_utils
from ..utils import pod as pod_utils
from ..utils.pdb import Limits
from .types import Candidate, CandidateError, new_candidate


def pods_by_node(cluster: Cluster) -> Dict[str, List[Pod]]:
    """One store pass -> node name -> active pods (avoids the O(nodes x pods)
    scan the per-node lookup would cost at 5k nodes)."""
    out: Dict[str, List[Pod]] = {}
    for p in cluster.store.list(Pod):
        if p.spec.node_name and pod_utils.is_active(p):
            out.setdefault(p.spec.node_name, []).append(p)
    return out


def pods_on_node(cluster: Cluster, sn) -> List[Pod]:
    from ..api.objects import Pod as PodKind
    return cluster.store.list(
        PodKind, predicate=lambda p: p.spec.node_name == sn.name()
        and pod_utils.is_active(p))


def build_pdb_limits(cluster: Cluster) -> Limits:
    store = cluster.store
    return Limits(store.list(PodDisruptionBudget), store.list(Pod))


def get_candidates(cluster: Cluster, provisioner: Provisioner,
                   should_disrupt, disrupting_provider_ids=(),
                   disruption_class: str = "graceful",
                   recorder=None, context=None) -> List[Candidate]:
    """helpers.go:144-161: candidates from disruptable cluster nodes that the
    method's ShouldDisrupt predicate accepts. Blocked candidates publish
    DisruptionBlocked for managed nodes (types.go:74-101: events only when
    NodeClaim != nil, so unmanaged nodes stay silent).

    `context` (a disruption.prefix.DisruptionSnapshot) supplies the
    pass-shared nodepool/instance-type/PDB/pod indexes so the four methods
    of one pass don't each re-list the store and re-fetch the catalog."""
    now = cluster.clock.now()
    if context is not None:
        nodepools = context.all_nodepools
        instance_types = context.it_maps
        pdb_limits = context.pdb_limits
        by_node = context.pods_by_node_map
    else:
        nodepools = {np.name: np for np in cluster.store.list(NodePool)}
        instance_types = {
            name: {it.name: it
                   for it in provisioner.cloud_provider.get_instance_types(np)}
            for name, np in nodepools.items()}
        pdb_limits = build_pdb_limits(cluster)
        by_node = pods_by_node(cluster)
    out: List[Candidate] = []
    # no deep copy here: new_candidate deep-copies the accepted nodes
    for sn in cluster.state_nodes(deep_copy=False):
        try:
            cand = new_candidate(now, sn, by_node.get(sn.name(), []),
                                 pdb_limits, nodepools, instance_types,
                                 disrupting_provider_ids, disruption_class)
        except CandidateError as err:
            if recorder is not None and sn.nodeclaim is not None:
                recorder.publish(*events_catalog.disruption_blocked(
                    sn.name(), sn.nodeclaim.name, str(err)))
            continue
        if should_disrupt(cand):
            out.append(cand)
    return out


def _node_not_ready(sn) -> bool:
    cond = node_utils.get_condition(sn.node, "Ready")
    # no Ready condition recorded: assume healthy (the in-process kubelet
    # sim doesn't stamp Ready; a real apiserver always does)
    return cond is not None and cond[0] != "True"


def build_disruption_budget_mapping(cluster: Cluster, reason: str,
                                    recorder=None) -> Dict[str, int]:
    """helpers.go:197-245: allowed = budget - already-disrupting, per pool.
    Only managed+initialized nodes count toward the total (uninitialized
    replacements must not inflate percentage budgets); claims with the
    InstanceTerminating condition are already gone; NotReady or
    marked-for-deletion nodes consume budget."""
    now = cluster.clock.now()
    allowed: Dict[str, int] = {}
    nodes_per_pool: Dict[str, int] = {}
    disrupting_per_pool: Dict[str, int] = {}
    for sn in cluster.state_nodes(deep_copy=False):
        pool = sn.nodepool_name()
        if not pool or not sn.managed() or not sn.initialized():
            continue
        if sn.nodeclaim is not None and \
                sn.nodeclaim.conditions.is_true(COND_INSTANCE_TERMINATING):
            continue
        nodes_per_pool[pool] = nodes_per_pool.get(pool, 0) + 1
        if sn.deleting() or _node_not_ready(sn):
            disrupting_per_pool[pool] = disrupting_per_pool.get(pool, 0) + 1
    for np in cluster.store.list(NodePool):
        total = np.allowed_disruptions(now, nodes_per_pool.get(np.name, 0), reason)
        allowed[np.name] = max(0, total - disrupting_per_pool.get(np.name, 0))
        # helpers.go:240-242: a populated pool whose budget is zero for this
        # reason tells the operator disruption is deliberately blocked
        if recorder is not None and nodes_per_pool.get(np.name, 0) != 0 \
                and total == 0:
            recorder.publish(
                events_catalog.nodepool_blocked_for_reason(np.name, reason))
    return allowed


def stamp_uninitialized_errors(results, exempt_uids) -> None:
    """helpers.go:93-111: a scheduling decision must not rest on managed
    nodes still mid-initialization — pods placed there become errors so the
    command is rejected, EXCEPT exempt pods (from deleting nodes, whose
    replacement node is assumed to come up). The ONE implementation of this
    rule: both the host-path simulate_scheduling and the snapshot replay
    (disruption/prefix.py) apply it, so they can never diverge."""
    for en in results.existing_nodes:
        sn = en.state_node if hasattr(en, "state_node") else None
        if sn is None or not sn.managed() or sn.initialized():
            continue
        for p in en.pods:
            if p.uid not in exempt_uids:
                results.pod_errors[p.uid] = (
                    f"would schedule against uninitialized node "
                    f"{sn.name()}")


def simulate_scheduling(cluster: Cluster, provisioner: Provisioner,
                        candidates: List[Candidate],
                        ride_along: Optional[List[Pod]] = None):
    """helpers.go:49-113: the bridge into the provisioning solver. Removes the
    candidates from the packable node set, marks their reschedulable pods
    pending, and solves. deleted-candidate races surface as CandidateError.

    `ride_along` is the deleting-node reschedulable-pod list when the caller
    already scanned it (the shared DisruptionSnapshot computes it once per
    disruption pass); None re-scans here for standalone callers."""
    candidate_ids = {c.provider_id for c in candidates}
    for c in candidates:
        sn = cluster.nodes.get(c.provider_id)
        if sn is None or sn.deleting():
            raise CandidateError("candidate is deleting")
    # read-only view: the solve never mutates StateNodes and the dispatch
    # loop is single-threaded, so the reference's defensive deep copy
    # (cluster.go:188-195) is unnecessary here — it costs O(nodes) per
    # consolidation probe
    state_nodes = [sn for sn in cluster.state_nodes(deep_copy=False)
                   if not sn.deleting() and sn.provider_id not in candidate_ids]
    pods = provisioner.get_pending_pods()
    # pods already being rescheduled from deleting nodes ride along
    if ride_along is None:
        ride_along = [p for sn in cluster.deleting_nodes()
                      for p in pods_on_node(cluster, sn)
                      if pod_utils.is_reschedulable(p)]
    deleting_pod_uids = set()
    for p in ride_along:
        pods.append(p)
        deleting_pod_uids.add(p.uid)
    reschedulable = [p for c in candidates for p in c.reschedulable_pods]
    results = provisioner.schedule_with(pods + reschedulable, state_nodes)
    stamp_uninitialized_errors(results, deleting_pod_uids)
    # pods that only became pending for the simulation must all land
    # (AllNonPendingPodsScheduled)
    sim_uids = {p.uid for p in reschedulable}
    non_pending_errors = {uid: e for uid, e in results.pod_errors.items()
                          if uid in sim_uids}
    return results, non_pending_errors
