"""Disruption solver core types.

Mirrors /root/reference/pkg/controllers/disruption/types.go: the Method
interface shape, Candidate (StateNode + pricing context + disruptionCost),
and Command (candidates to delete + replacements to launch).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import labels as api_labels
from ..api.nodepool import NodePool
from ..api.objects import Pod
from ..cloudprovider.types import InstanceType
from ..scheduling.requirements import label_requirements
from ..state.statenode import StateNode
from ..utils import disruption as disruption_utils
from ..utils import pod as pod_utils
from ..utils.pdb import Limits

GRACEFUL = "graceful"   # respects blocking PDBs + do-not-disrupt
EVENTUAL = "eventual"   # bounded by TerminationGracePeriod instead


class CandidateError(Exception):
    pass


class PodBlockEvictionError(CandidateError):
    pass


@dataclass
class Candidate:
    """types.go:105-114."""
    state_node: StateNode
    nodepool: NodePool
    instance_type: Optional[InstanceType]
    zone: str
    capacity_type: str
    reschedulable_pods: List[Pod]
    disruption_cost: float

    @property
    def provider_id(self) -> str:
        return self.state_node.provider_id

    @property
    def name(self) -> str:
        return self.state_node.name()

    @property
    def nodepool_name(self) -> str:
        return self.state_node.nodepool_name()

    def price(self) -> Optional[float]:
        """Current offering price (consolidation.go getCandidatePrices)."""
        if self.instance_type is None:
            return None
        reqs = label_requirements(self.state_node.labels())
        offs = self.instance_type.offerings.compatible(reqs)
        if not offs:
            return None
        return max(o.price for o in offs)


def new_candidate(now: float, node: StateNode, pods_on_node: List[Pod],
                  pdb_limits: Limits, nodepools: Dict[str, NodePool],
                  instance_types: Dict[str, Dict[str, InstanceType]],
                  disrupting_provider_ids=(),
                  disruption_class: str = GRACEFUL) -> Candidate:
    """types.go NewCandidate: every gate raises CandidateError with the
    blocking reason."""
    err = node.validate_node_disruptable(now)
    if err is not None:
        raise CandidateError(err)
    if node.provider_id in disrupting_provider_ids:
        raise CandidateError("candidate is already being disrupted")
    pool = nodepools.get(node.nodepool_name())
    it_map = instance_types.get(node.nodepool_name())
    if pool is None or it_map is None:
        raise CandidateError(
            f'nodepool "{node.nodepool_name()}" can\'t be resolved for state node')
    err = _validate_pods_disruptable(pods_on_node, pdb_limits)
    if err is not None:
        tgp = node.nodeclaim.spec.termination_grace_period \
            if node.nodeclaim is not None else None
        if not (disruption_class == EVENTUAL and tgp is not None
                and isinstance(err, PodBlockEvictionError)):
            raise err
    nc = node.nodeclaim
    return Candidate(
        state_node=node.deep_copy(),
        nodepool=pool,
        instance_type=it_map.get(
            node.labels().get(api_labels.LABEL_INSTANCE_TYPE, "")),
        zone=node.labels().get(api_labels.LABEL_TOPOLOGY_ZONE, ""),
        capacity_type=node.labels().get(api_labels.CAPACITY_TYPE_LABEL_KEY, ""),
        reschedulable_pods=[p for p in pods_on_node
                            if pod_utils.is_reschedulable(p)],
        disruption_cost=(disruption_utils.rescheduling_cost(pods_on_node)
                         * disruption_utils.lifetime_remaining(now, nc)))


def _validate_pods_disruptable(pods: List[Pod], pdb_limits: Limits):
    """statenode.go:215-232: blocking do-not-disrupt pods, then PDBs.

    The do-not-disrupt sweep covers EVERY active pod — the reference
    explicitly lets mirror pods and daemonsets block disruption through
    the annotation (statenode.go:221-223) while terminal/terminating pods
    never do. The PDB sweep then covers only evictable pods (mirror pods
    are exempt; daemonset pods are not)."""
    for p in pods:
        if pod_utils.is_active(p) and not pod_utils.is_disruptable(p):
            return PodBlockEvictionError(
                f"pod {p.namespace}/{p.name} has the "
                f'"{api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY}" annotation')
    for p in pods:
        if not pod_utils.is_evictable(p):
            continue
        ok, pdb = pdb_limits.can_evict(p)
        if not ok:
            return PodBlockEvictionError(
                f'pdb "{pdb.namespace}/{pdb.name}" prevents pod evictions')
    return None


@dataclass
class Command:
    """types.go:150+: what a method decided."""
    candidates: List[Candidate] = field(default_factory=list)
    replacements: list = field(default_factory=list)  # in-flight nodeclaims
    reason: str = ""
    consolidation_type: str = ""
    # pass trace_id of the disruption pass that computed this command
    # ("" when tracing is off): joins the execute-time log line with the
    # compute-time trace and flight-recorder record
    trace_id: str = ""

    @property
    def decision(self) -> str:
        if not self.candidates:
            return "no-op"
        return "replace" if self.replacements else "delete"

    def is_empty(self) -> bool:
        return not self.candidates
