"""Batched leave-one-out candidate evaluation for single-node consolidation.

The reference's SingleNodeConsolidation (singlenodeconsolidation.go:44-101)
walks the fair order calling a FULL scheduling simulation per candidate —
at 5,000 candidates that is 5,000 solver rebuilds racing the 3-minute
timeout. The TPU design evaluates every candidate's deletion from ONE
shared `DisruptionSnapshot` encode: the device feasibility precompute
already yields, for every (group, node) and (group, template, instance
type) pair at once, exactly the quantities each leave-one-out row needs —
each row just masks out one candidate's node and marks its reschedulable
pods pending. The per-row decision (delete feasible / replaceable by one
cheaper node / unconsolidatable) is then closed-form host array math over
those shared tensors.

Exactness contract, mirroring the PrefixSimulator fallback contract:

- rows the math can express are classified without any simulation;
- rows it can't (multi-group candidates, topology constraints, host ports,
  volumes, nodepool limits, minValues, pending base pods) report
  `needs_sim` and run through the exact shared-snapshot replay;
- a `win` classification is never trusted blindly: the caller re-derives
  the actual Command through the replay + `decide()`, so a classifier bug
  can only cost one extra probe, never a wrong command;
- the seeded parity fuzzer (tests/test_single_consolidation_fuzzer.py)
  pins decision equality against the per-candidate host oracle.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..api import labels as api_labels
from ..scheduling.requirement import IN, Requirement
from .prefix import DisruptionSnapshot, SnapshotFallback, exist_fill_order
from .types import Candidate

_INF = math.inf

WIN = "win"          # a simulation probe is expected to yield a command
REJECT = "reject"    # provably unconsolidatable: skip the probe entirely
NEEDS_SIM = "sim"    # row inexpressible in the batched math: probe to know


@dataclass
class LooVerdict:
    kind: str
    reason: str = ""  # decide()-shaped reason for REJECT rows


class _GroupView:
    """Per-group leave-one-out arrays over the shared exist tensors, in the
    packer's existing-node fill order (initialized first, name tiebreak)."""

    def __init__(self, enc, g: int, order: np.ndarray, pos_of: np.ndarray,
                 err: np.ndarray):
        t = enc.tensors
        N = order.size
        self.cap = np.where(t.exist_ok[g, :N],
                            t.exist_cap[g, :N].astype(np.int64), 0)
        cap_o = self.cap[order]
        self.cum = np.concatenate(([0], np.cumsum(cap_o)))
        self.total = int(self.cum[-1])
        # positions (in fill order) of uninitialized MANAGED nodes this
        # group could land on — any pod reaching one becomes a sim error
        # (helpers.go:93-111), so the row is rejected
        self.err_pos = np.nonzero(err[order] & (cap_o > 0))[0]
        self.pos_of = pos_of


class LeaveOneOutEngine:
    """Classifies every candidate of one single-node consolidation pass."""

    def __init__(self, snapshot: DisruptionSnapshot,
                 candidates: Sequence[Candidate],
                 spot_to_spot_enabled: bool = False):
        self.snapshot = snapshot
        self.enc = snapshot.encoding_for(candidates)  # may raise
        self.candidates = list(candidates)
        self.spot_to_spot_enabled = spot_to_spot_enabled
        self.stats = {"classified": 0, "needs_sim": 0, "probes": 0}
        # shape-class attribution of the NEEDS_SIM rows (obs/fallbacks
        # vocabulary): which inexpressible shapes force exact replay sims —
        # the disruption half of the fallback cost ledger
        self.sim_classes: Dict[str, int] = {}
        self._worst_memo: Dict[tuple, np.ndarray] = {}
        self._reqs_memo: Dict[tuple, object] = {}
        from ..obs.tracer import TRACER
        with TRACER.span("disruption.loo", candidates=len(self.candidates)):
            self._verdicts = self._classify()
        self.stats["classified"] = sum(
            1 for v in self._verdicts if v.kind != NEEDS_SIM)
        self.stats["needs_sim"] = sum(
            1 for v in self._verdicts if v.kind == NEEDS_SIM)
        from ..obs.fallbacks import LEDGER
        LEDGER.record_disruption(self.sim_classes)

    # -- public -------------------------------------------------------------

    def verdict(self, i: int) -> LooVerdict:
        return self._verdicts[i]

    def probe(self, i: int):
        """The exact shared-snapshot replay for candidate i."""
        self.stats["probes"] += 1
        return self.enc.simulate_subset([i])

    # -- classification ------------------------------------------------------

    def _count_sim(self, shape: str, n: int = 1) -> None:
        self.sim_classes[shape] = self.sim_classes.get(shape, 0) + n

    def _classify(self) -> List[LooVerdict]:
        enc = self.enc
        snap = self.snapshot
        n = len(self.candidates)
        sim = [LooVerdict(NEEDS_SIM)] * n
        # global gates: shapes whose leave-one-out packs interact in ways
        # the closed-form math doesn't model go through the replay
        if snap.base_pods:
            self._count_sim("base_pods", n)
            return sim  # every row re-packs the shared pending set
        if enc.problem.min_its is not None:
            self._count_sim("minvalues", n)
            return sim  # minValues floors change fills and claim counts
        if any(np_.spec.limits for np_ in snap.ts.nodepools):
            self._count_sim("limits", n)
            return sim  # subtractMax pessimism is order-dependent
        t = enc.tensors
        state_nodes = snap.ts.state_nodes
        N = len(state_nodes)
        if N == 0:
            self._count_sim("other", n)
            return sim
        simple = [not g.topo and not g.host_ports
                  and not (g.pods and g.pods[0].spec.volumes)
                  for g in enc.groups]
        order = np.array(exist_fill_order(state_nodes), dtype=np.int64)
        pos_of = np.empty(N, dtype=np.int64)
        pos_of[order] = np.arange(N)
        err = np.array([sn.managed() and not sn.initialized()
                        for sn in state_nodes], dtype=bool)

        views: Dict[int, _GroupView] = {}
        out: List[LooVerdict] = []
        for i, c in enumerate(self.candidates):
            counts: Dict[int, int] = {}
            unknown = False
            for uid in enc.pod_uids_by_candidate[i]:
                gi = enc.uid_group.get(uid)
                if gi is None:
                    unknown = True
                    break
                counts[gi] = counts.get(gi, 0) + 1
            n_idx = enc.node_index.get(c.state_node.name())
            if unknown or n_idx is None or len(counts) != 1:
                self._count_sim("multi_group" if not unknown
                                and n_idx is not None else "other")
                out.append(LooVerdict(NEEDS_SIM))
                continue
            (g, k), = counts.items()
            if not simple[g]:
                grp = enc.groups[g]
                self._count_sim(
                    "topo" if grp.topo else
                    "ports" if grp.host_ports else "volumes")
                out.append(LooVerdict(NEEDS_SIM))
                continue
            view = views.get(g)
            if view is None:
                view = _GroupView(enc, g, order, pos_of, err)
                views[g] = view
            out.append(self._classify_row(c, g, k, n_idx, view))
        return out

    def _classify_row(self, c: Candidate, g: int, k: int, n_idx: int,
                      view: _GroupView) -> LooVerdict:
        cap_c = int(view.cap[n_idx])
        p_pos = int(view.pos_of[n_idx])
        total_i = view.total - cap_c
        # the greedy existing-node fill reaches an uninitialized managed
        # node (=> sim error => rejection) iff the demand exceeds the
        # capacity accumulated before the first such node in fill order,
        # with the candidate's own column removed
        thr = _INF
        ep = view.err_pos
        if ep.size:
            j = int(np.searchsorted(ep, p_pos))
            if j > 0:
                thr = float(view.cum[ep[0]])
            jj = j + 1 if j < ep.size and ep[j] == p_pos else j
            if jj < ep.size:
                thr = min(thr, float(view.cum[ep[jj]] - cap_c))
        if k <= thr and k <= total_i:
            return LooVerdict(WIN)  # delete: zero new nodes, no errors
        if k > thr:
            return LooVerdict(REJECT, (
                "not all pods would schedule, would schedule against "
                "an uninitialized node"))
        # remainder opens fresh capacity: first viable template takes all
        r = k - total_i
        t = self.enc.tensors
        m0 = next((m for m in range(len(self.enc.templates))
                   if t.it_ok[g, m].any()), None)
        if m0 is None:
            return LooVerdict(REJECT, (
                "not all pods would schedule, no instance type satisfied "
                "the pod"))
        per = int(t.ppn[g, m0][t.it_ok[g, m0]].max())
        claims = -(-r // per)
        if claims != 1:
            return LooVerdict(REJECT, (
                f"Can't remove without creating {claims} candidates"))
        return self._classify_replacement(c, g, m0, r)

    # -- replacement pricing (consolidation.go:176-302 closed form) ---------

    def _combined_reqs(self, g: int, m: int, spot_pinned: bool):
        key = (g, m, spot_pinned)
        reqs = self._reqs_memo.get(key)
        if reqs is None:
            reqs = self.enc.templates[m].requirements.copy()
            reqs.add(*self.enc.groups[g].requirements.values())
            if spot_pinned:
                reqs.add(Requirement(api_labels.CAPACITY_TYPE_LABEL_KEY, IN,
                                     [api_labels.CAPACITY_TYPE_SPOT]))
            self._reqs_memo[key] = reqs
        return reqs

    def _worst_prices(self, g: int, m: int, spot_pinned: bool) -> np.ndarray:
        """[T] worst launch price per catalog instance type under the
        replacement's combined requirements — the exact
        Offerings.worst_launch_price the price filter uses
        (nodeclaim.go:136-145), vectorized once per (group, template)."""
        key = (g, m, spot_pinned)
        worst = self._worst_memo.get(key)
        if worst is None:
            reqs = self._combined_reqs(g, m, spot_pinned)
            worst = np.array(
                [it.offerings.available().worst_launch_price(reqs)
                 for it in self.enc.catalog], dtype=np.float64)
            self._worst_memo[key] = worst
        return worst

    def _classify_replacement(self, c: Candidate, g: int, m0: int,
                              r: int) -> LooVerdict:
        from .methods import MIN_SPOT_TO_SPOT_INSTANCE_TYPES
        t = self.enc.tensors
        it_set = t.it_ok[g, m0] & (t.ppn[g, m0] >= r)
        price = c.price()
        if price is None:
            return LooVerdict(REJECT)
        base_reqs = self._combined_reqs(g, m0, False)
        ct_req = base_reqs.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        if c.capacity_type == api_labels.CAPACITY_TYPE_SPOT \
                and ct_req.has(api_labels.CAPACITY_TYPE_SPOT):
            if not self.spot_to_spot_enabled:
                return LooVerdict(REJECT, (
                    "SpotToSpotConsolidation is disabled, can't replace a "
                    "spot node with a spot node"))
            worst = self._worst_prices(g, m0, True)
            cheaper = int((it_set & (worst < price)).sum())
            if cheaper < MIN_SPOT_TO_SPOT_INSTANCE_TYPES:
                return LooVerdict(REJECT, (
                    "SpotToSpotConsolidation requires "
                    f"{MIN_SPOT_TO_SPOT_INSTANCE_TYPES} cheaper instance "
                    "type options than the current candidate to "
                    f"consolidate, got {cheaper}"))
            return LooVerdict(WIN)
        worst = self._worst_prices(g, m0, False)
        if not bool((it_set & (worst < price)).any()):
            return LooVerdict(REJECT, "Can't replace with a cheaper node")
        return LooVerdict(WIN)


class MultiNodeLooEngine:
    """Ranked multi-node subset search: closed-form verdicts for the
    prefix subsets the multi-node binary search probes (ISSUE 14).

    The reference's multi-node consolidation binary-searches the largest
    cost-ordered candidate PREFIX replaceable by at most one cheaper node
    (multinodeconsolidation.go:110-162), paying a full host replay per
    midpoint. This engine scores every prefix length over the SAME shared
    snapshot tensors the single-node LeaveOneOutEngine reads:

    - prefixes whose pods all land in ONE simple group generalize the
      single-node closed form exactly (multiple excluded exist columns,
      summed demand, summed candidate price, the same uninitialized-node
      threshold / claims-count / price-filter math);
    - multi-group prefixes get SOUND rejection bounds only: a group whose
      solo demand provably reaches an uninitialized managed node (any
      contention only brings that node closer), and a resource-volume
      lower bound proving >= 2 fresh claims (any node's usable capacity
      is bounded by the catalog's per-resource max);
    - everything else is NEEDS_SIM: the midpoint replays exactly as the
      reference search would.

    Exactness contract (the single-node contract, verbatim): a REJECT is
    only ever returned when the replay's decide() would provably return an
    empty command, so the binary search can skip that midpoint's replay
    without changing ITS decision; a WIN is never trusted — the search
    replays it to derive the actual command. The multi-node parity fuzzer
    (tests/test_single_consolidation_fuzzer.py) pins decision equality
    against the engine-off binary search seed by seed.
    """

    def __init__(self, snapshot: DisruptionSnapshot,
                 candidates: Sequence[Candidate],
                 spot_to_spot_enabled: bool = False):
        self.snapshot = snapshot
        self.enc = snapshot.encoding_for(candidates)  # may raise
        self.candidates = list(candidates)
        self.spot_to_spot_enabled = spot_to_spot_enabled
        self.stats = {"classified": 0, "needs_sim": 0, "probes_saved": 0}
        self._worst_memo: Dict[tuple, np.ndarray] = {}
        self._reqs_memo: Dict[tuple, object] = {}
        self._verdicts: Dict[int, LooVerdict] = {}
        from ..obs.tracer import TRACER
        with TRACER.span("disruption.mnloo", candidates=len(self.candidates)):
            self._prepare()

    # the single-node engine's replacement-pricing memos, shared verbatim
    _combined_reqs = LeaveOneOutEngine._combined_reqs
    _worst_prices = LeaveOneOutEngine._worst_prices

    def _prepare(self) -> None:
        enc = self.enc
        snap = self.snapshot
        self._global_sim = None
        if snap.base_pods:
            self._global_sim = "base_pods"
        elif enc.problem.min_its is not None:
            self._global_sim = "minvalues"
        elif any(np_.spec.limits for np_ in snap.ts.nodepools):
            self._global_sim = "limits"
        state_nodes = snap.ts.state_nodes
        N = len(state_nodes)
        if N == 0:
            self._global_sim = self._global_sim or "other"
        if self._global_sim is not None:
            return
        self._order = np.array(exist_fill_order(state_nodes), dtype=np.int64)
        pos_of = np.empty(N, dtype=np.int64)
        pos_of[self._order] = np.arange(N)
        self._pos_of = pos_of
        self._err = np.array([sn.managed() and not sn.initialized()
                              for sn in state_nodes], dtype=bool)
        self._simple = [not g.topo and not g.host_ports
                        and not (g.pods and g.pods[0].spec.volumes)
                        for g in enc.groups]
        self._views: Dict[int, _GroupView] = {}
        # per-candidate (group->count, node index); the first candidate the
        # tensors can't express makes every prefix containing it NEEDS_SIM
        self._cand: List[Optional[tuple]] = []
        for i, c in enumerate(self.candidates):
            counts: Dict[int, int] = {}
            bad = False
            for uid in enc.pod_uids_by_candidate[i]:
                gi = enc.uid_group.get(uid)
                if gi is None:
                    bad = True
                    break
                counts[gi] = counts.get(gi, 0) + 1
            n_idx = enc.node_index.get(c.state_node.name())
            if bad or n_idx is None or bool(self._err[n_idx]) \
                    or any(not self._simple[g] for g in counts):
                self._cand.append(None)
            else:
                self._cand.append((counts, n_idx))

    def _view(self, g: int) -> _GroupView:
        v = self._views.get(g)
        if v is None:
            v = _GroupView(self.enc, g, self._order, self._pos_of, self._err)
            self._views[g] = v
        return v

    def verdict(self, n: int) -> LooVerdict:
        """Closed-form verdict for the prefix candidates[:n]."""
        v = self._verdicts.get(n)
        if v is None:
            v = self._verdict(n)
            self._verdicts[n] = v
            self.stats["classified" if v.kind != NEEDS_SIM
                       else "needs_sim"] += 1
            from ..metrics import registry as metrics
            metrics.DISRUPTION_SUBSET_VERDICTS.inc({"kind": v.kind})
            if v.kind == REJECT:
                self.stats["probes_saved"] += 1
        return v

    def _verdict(self, n: int) -> LooVerdict:
        if self._global_sim is not None:
            return LooVerdict(NEEDS_SIM)
        prefix = self._cand[:n]
        if any(c is None for c in prefix):
            return LooVerdict(NEEDS_SIM)
        # per-group aggregates over the prefix: demand, removed capacity,
        # capacity removed before each group's first uninitialized position
        k: Dict[int, int] = {}
        removed: Dict[int, int] = {}
        removed_pre_err: Dict[int, int] = {}
        groups = set()
        for counts, _ in prefix:
            groups.update(counts)
        for g in groups:
            view = self._view(g)
            kg = rg = rpe = 0
            e0 = int(view.err_pos[0]) if view.err_pos.size else -1
            for counts, n_idx in prefix:
                kg += counts.get(g, 0)
                cap = int(view.cap[n_idx])
                rg += cap
                if e0 >= 0 and int(view.pos_of[n_idx]) < e0:
                    rpe += cap
            k[g], removed[g], removed_pre_err[g] = kg, rg, rpe

        # sound uninit rejection per group: contention from other groups
        # only brings the first error node closer (see class docstring)
        for g in groups:
            view = self._view(g)
            if view.err_pos.size:
                thr = float(view.cum[view.err_pos[0]]) - removed_pre_err[g]
                if k[g] > thr:
                    return LooVerdict(REJECT, (
                        "not all pods would schedule, would schedule "
                        "against an uninitialized node"))

        overflow = {g: k[g] - (self._view(g).total - removed[g])
                    for g in groups}
        overflow = {g: r for g, r in overflow.items() if r > 0}
        if not overflow:
            if len(groups) == 1:
                return LooVerdict(WIN)  # exact: delete, zero new nodes
            # multi-group: solo totals are optimistic — contention could
            # still overflow, so a delete is plausible but not proven
            return LooVerdict(NEEDS_SIM)

        if len(groups) > 1:
            return self._multi_group_claims_bound(overflow)
        (g,) = groups
        return self._single_group_replacement(n, g, overflow[g])

    def _multi_group_claims_bound(self, overflow: Dict[int, int]
                                  ) -> LooVerdict:
        """Resource-volume lower bound on fresh claims: every node's
        usable capacity per resource is bounded by the catalog max, so
        ceil(total overflow volume / max node) >= 2 proves the replay
        would create >= 2 claims — decide() rejects those."""
        t = self.enc.tensors
        p = self.enc.problem
        need = np.zeros(p.group_req.shape[1], dtype=np.float64)
        for g, r in overflow.items():
            need += r * p.group_req[g].astype(np.float64)
        max_alloc = p.it_alloc.max(axis=0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per_res = np.where(max_alloc > 0, need / max_alloc,
                               np.where(need > 0, np.inf, 0.0))
        claims_lb = int(np.ceil(per_res.max())) if per_res.size else 0
        if claims_lb >= 2:
            return LooVerdict(REJECT, (
                f"Can't remove without creating {claims_lb} candidates"))
        return LooVerdict(NEEDS_SIM)

    def _single_group_replacement(self, n: int, g: int, r: int) -> LooVerdict:
        """The single-node replacement classification with summed demand
        and summed candidate price (consolidation.go:176-302 closed form,
        multi-candidate decide() semantics: no spot-to-spot >= 15 floor
        for len(candidates) > 1)."""
        t = self.enc.tensors
        m0 = next((m for m in range(len(self.enc.templates))
                   if t.it_ok[g, m].any()), None)
        if m0 is None:
            return LooVerdict(REJECT, (
                "not all pods would schedule, no instance type satisfied "
                "the pod"))
        per = int(t.ppn[g, m0][t.it_ok[g, m0]].max())
        claims = -(-r // per)
        if claims != 1:
            return LooVerdict(REJECT, (
                f"Can't remove without creating {claims} candidates"))
        prefix = self.candidates[:n]
        price = 0.0
        for c in prefix:
            p_ = c.price()
            if p_ is None:
                return LooVerdict(REJECT)
            price += p_
        it_set = t.it_ok[g, m0] & (t.ppn[g, m0] >= r)
        base_reqs = self._combined_reqs(g, m0, False)
        ct_req = base_reqs.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        all_spot = all(c.capacity_type == api_labels.CAPACITY_TYPE_SPOT
                       for c in prefix)
        if all_spot and ct_req.has(api_labels.CAPACITY_TYPE_SPOT):
            if not self.spot_to_spot_enabled:
                return LooVerdict(REJECT, (
                    "SpotToSpotConsolidation is disabled, can't replace a "
                    "spot node with a spot node"))
            worst = self._worst_prices(g, m0, True)
            if not bool((it_set & (worst < price)).any()):
                return LooVerdict(REJECT, "Can't replace with a cheaper node")
            return LooVerdict(WIN)  # len > 1: no MIN_SPOT_TO_SPOT floor
        worst = self._worst_prices(g, m0, False)
        if not bool((it_set & (worst < price)).any()):
            return LooVerdict(REJECT, "Can't replace with a cheaper node")
        return LooVerdict(WIN)
