"""Disruption controller + orchestration queue.

Mirrors /root/reference/pkg/controllers/disruption/controller.go and
orchestration/queue.go: a 10s singleton loop trying methods in order
Drift -> Emptiness -> MultiNodeConsolidation -> SingleNodeConsolidation,
first success wins (:84-94,137-149); execution taints candidates, launches
replacements, marks for deletion, and hands the command to the async queue,
which waits for replacements to initialize before deleting the candidates,
rolling back (untaint + unmark) on timeout (queue.go:163-281).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim
from ..api.objects import Node
from ..controllers.manager import Result, SingletonController
from ..events import catalog as events_catalog
from ..kube.store import Store
from ..logging import get_logger
from ..obs.tracer import TRACER
from ..provisioning.provisioner import Provisioner
from ..scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT
from ..state.cluster import Cluster
from ..utils.backoff import ItemBackoff
from ..utils.clock import Clock
from .methods import (Drift, Emptiness, Method, MultiNodeConsolidation,
                      SingleNodeConsolidation)
from .types import Command
from .validation import CONSOLIDATION_TTL_SECONDS, validate_command

POLL_INTERVAL_SECONDS = 10.0         # controller.go:68
COMMAND_TIMEOUT_SECONDS = 10 * 60.0  # queue.go commandTimeout

log = get_logger("disruption")


@dataclass
class QueuedCommand:
    command: Command
    replacement_names: List[str]
    enqueued_at: float
    provider_ids: List[str] = field(default_factory=list)
    next_at: float = 0.0  # rate-limited retry gate

    @property
    def key(self) -> tuple:
        return tuple(self.provider_ids)


QUEUE_BASE_DELAY = 1.0   # orchestration/queue.go:51
QUEUE_MAX_DELAY = 10.0   # orchestration/queue.go:52


class OrchestrationQueue(SingletonController):
    """orchestration/queue.go:108-281 (deterministic-runtime version).
    Commands still waiting on replacements retry with per-item exponential
    backoff (queue.go:128-132: 1s base / 10s cap) instead of a flat 1s."""

    name = "disruption.queue"

    def __init__(self, store: Store, cluster: Cluster,
                 clock: Optional[Clock] = None, recorder=None):
        from ..events.recorder import Recorder
        self.store = store
        self.cluster = cluster
        self.clock = clock or store.clock
        self.recorder = recorder or Recorder(self.clock)
        self.items: List[QueuedCommand] = []
        self._backoff = ItemBackoff(QUEUE_BASE_DELAY, QUEUE_MAX_DELAY)

    def has_any(self, provider_id: str) -> bool:
        return any(provider_id in qc.provider_ids for qc in self.items)

    def add(self, qc: QueuedCommand) -> None:
        qc.provider_ids = [c.provider_id for c in qc.command.candidates]
        self.items.append(qc)

    def reconcile(self) -> Optional[Result]:
        now = self.clock.now()
        remaining: List[QueuedCommand] = []
        delays: List[float] = []
        for qc in self.items:
            if qc.next_at > now:
                remaining.append(qc)
                delays.append(qc.next_at - now)
                continue
            state = self._process(qc)
            if state == "wait":
                delay = self._backoff.next_delay(qc.key)
                qc.next_at = now + delay
                remaining.append(qc)
                delays.append(delay)
            else:
                self._backoff.forget(qc.key)
        self.items = remaining
        return Result(requeue_after=min(delays)) if remaining else None

    def _process(self, qc: QueuedCommand) -> str:
        if self.clock.now() - qc.enqueued_at > COMMAND_TIMEOUT_SECONDS:
            self._rollback(qc)
            return "done"
        for name in qc.replacement_names:
            nc = self.store.get(NodeClaim, name)
            if nc is None:
                # replacement died (launch failure / liveness): roll back
                self._rollback(qc)
                return "done"
            # queue.go:243-249: narrate replacement progress (dedupe
            # collapses the per-pass repeats)
            self.recorder.publish(
                events_catalog.disruption_launching(nc, qc.command.reason))
            if not nc.initialized():
                self.recorder.publish(
                    events_catalog.disruption_waiting_on_readiness(nc))
                return "wait"
        # all replacements ready: delete the candidates (queue.go:258-274)
        for c in qc.command.candidates:
            nc = c.state_node.nodeclaim
            live = self.store.get(NodeClaim, nc.name) if nc is not None else None
            if live is not None and live.metadata.deletion_timestamp is None:
                self.recorder.publish(*events_catalog.disruption_terminating(
                    c.state_node.name(), live.name, qc.command.reason))
                self.store.delete(live)
        return "done"

    def _rollback(self, qc: QueuedCommand) -> None:
        """queue.go:181-223: untaint + unmark so the nodes return to service."""
        log.warning("disruption command failed, rolling back",
                    reason=qc.command.reason,
                    candidates=[c.state_node.name()
                                for c in qc.command.candidates])
        for c in qc.command.candidates:
            node = self.store.get(Node, c.state_node.name())
            if node is not None:
                before = len(node.spec.taints)
                node.spec.taints = [
                    t for t in node.spec.taints
                    if not t.matches(DISRUPTED_NO_SCHEDULE_TAINT)]
                if len(node.spec.taints) != before:
                    self.store.update(node)
        self.cluster.unmark_for_deletion(*qc.provider_ids)


class DisruptionController(SingletonController):
    name = "disruption"

    def __init__(self, store: Store, cluster: Cluster, provisioner: Provisioner,
                 queue: OrchestrationQueue, clock: Optional[Clock] = None,
                 spot_to_spot_enabled: bool = False, recorder=None,
                 flight_recorder=None):
        from ..events.recorder import Recorder
        self.store = store
        # optional flightrec.FlightRecorder: every non-empty disruption
        # command is captured with its winner-simulation inputs for replay
        self.flight_recorder = flight_recorder
        self.cluster = cluster
        self.provisioner = provisioner
        self.queue = queue
        self.clock = clock or store.clock
        self.recorder = recorder or Recorder(self.clock)
        self.methods: List[Method] = [
            Drift(cluster, provisioner, recorder=self.recorder),
            Emptiness(cluster, provisioner, recorder=self.recorder),
            MultiNodeConsolidation(cluster, provisioner, spot_to_spot_enabled,
                                   clock=self.clock, recorder=self.recorder),
            SingleNodeConsolidation(cluster, provisioner, spot_to_spot_enabled,
                                    clock=self.clock, recorder=self.recorder),
        ]
        self.last_command: Optional[Command] = None
        # command awaiting the consolidation-TTL re-validation
        # (validation.go:83-215); (command, computed_at)
        self.pending: Optional[tuple] = None
        # the per-pass shared DisruptionSnapshot (reconcile scope only)
        self._snapshot = None
        # the cross-pass streaming state: delta-applied snapshot layers,
        # cached candidate rows, columnar budget accounting (stream.py).
        # It subscribes to the provisioner's shared EncodePlane, so a
        # disruption pass reuses the node/group rows the provisioning pass
        # just encoded (and vice versa) instead of keeping a third copy.
        from .stream import StreamingDisruptionState
        self.stream = StreamingDisruptionState(
            plane=getattr(provisioner, "state_plane", None))

    def reconcile(self) -> Optional[Result]:
        if not self.cluster.synced():
            return Result(requeue_after=1.0)
        self._cleanup_stale_taints()
        if self.pending is not None:
            return self._reconcile_pending()
        # ONE DisruptionSnapshot per pass: every method's candidate
        # collection and simulation shares the same encode. Built on the
        # first _disrupt call — even an idle pass pays its store scans,
        # but that replaces the per-METHOD context rebuild (4x nodepool +
        # catalog + PDB + pod listings) the old get_candidates cost; the
        # expensive tensor encode itself stays lazy inside the snapshot.
        self._snapshot = None
        try:
            for method in self.methods:
                if getattr(method, "is_consolidated", None) and \
                        method.is_consolidated():
                    continue
                # consolidation methods self-memoize inside compute_command
                # (skipped when budget-constrained — consolidation.go:89-96)
                executed = self._disrupt(method)
                if executed:
                    return Result(requeue_after=POLL_INTERVAL_SECONDS)
            return Result(requeue_after=POLL_INTERVAL_SECONDS)
        finally:
            self._snapshot = None
            for method in self.methods:
                if hasattr(method, "attach_snapshot"):
                    method.attach_snapshot(None)

    def _pass_snapshot(self):
        if self._snapshot is None:
            # the stream keeps the snapshot object across passes and
            # rebuilds only the layers whose invalidation tokens moved
            self._snapshot = self.stream.refresh(self.cluster,
                                                 self.provisioner)
        return self._snapshot

    def _cleanup_stale_taints(self) -> None:
        """controller.go:124-135: a crash mid-disruption can leave nodes
        tainted disrupted:NoSchedule with no queue entry driving them —
        idempotently untaint every node not in the orchestration queue."""
        for sn in self.cluster.state_nodes(deep_copy=False):
            if self.queue.has_any(sn.provider_id) or sn.node is None:
                continue
            # a deleting/terminating node is the NodeTermination controller's
            # to manage — untainting it would let pods bind back onto a
            # draining node (statenode.go:461-479 skips these)
            if sn.deleting() or sn.nodeclaim is None:
                continue
            node = self.store.get(Node, sn.name())
            if node is None or node.metadata.deletion_timestamp is not None:
                continue
            kept = [t for t in node.spec.taints
                    if not t.matches(DISRUPTED_NO_SCHEDULE_TAINT)]
            if len(kept) != len(node.spec.taints):
                node.spec.taints = kept
                self.store.update(node)

    def _reconcile_pending(self) -> Optional[Result]:
        cmd, computed_at = self.pending
        elapsed = self.clock.now() - computed_at
        if elapsed < CONSOLIDATION_TTL_SECONDS:
            return Result(
                requeue_after=CONSOLIDATION_TTL_SECONDS - elapsed)
        self.pending = None
        disrupting = {pid for qc in self.queue.items for pid in qc.provider_ids}
        # the validation pass gets its OWN snapshot: the cluster had a TTL's
        # worth of time to move since the compute pass encoded it
        if validate_command(self.cluster, self.provisioner, cmd, cmd.reason,
                            disrupting_provider_ids=disrupting):
            self._execute(cmd)
        return Result(requeue_after=POLL_INTERVAL_SECONDS)

    def _disrupt(self, method: Method) -> bool:
        """controller.go:155-190."""
        with TRACER.span("disruption.pass", method=method.reason) as sp:
            return self._disrupt_traced(method, sp)

    def _disrupt_traced(self, method: Method, sp) -> bool:
        from ..metrics import registry as metrics
        disrupting = {pid for qc in self.queue.items for pid in qc.provider_ids}
        snapshot = self._pass_snapshot()
        if hasattr(method, "attach_snapshot"):
            method.attach_snapshot(snapshot)
        # columnar candidate construction over the stream's cached rows
        # (bit-identical to helpers.get_candidates against this snapshot)
        candidates = self.stream.candidates_for(
            method.should_disrupt, disrupting_provider_ids=disrupting,
            disruption_class=method.disruption_class,
            recorder=self.recorder)
        metrics.DISRUPTION_ELIGIBLE_NODES.set(
            len(candidates), {"reason": method.reason})
        if not candidates:
            # idle pass: up to 4 of these every 10s poll would flood the
            # trace ring and evict the interesting traces — don't ring it
            TRACER.drop_current()
            return False
        sp.set(candidates=len(candidates))
        budgets = self.stream.budget_mapping(method.reason,
                                             recorder=self.recorder)
        started = self.clock.now()
        cmd, results = method.compute_command(budgets, candidates)
        metrics.DISRUPTION_EVAL_DURATION.observe(
            self.clock.now() - started,
            {"method": getattr(method, "consolidation_type", "") or
             method.reason})
        if cmd.is_empty():
            return False
        # the pass trace_id rides the command so the execute-time log line
        # (possibly a TTL validation later) can still join the trace
        cmd.trace_id = TRACER.current_trace_id()
        if self.flight_recorder is not None:
            # capture at decision time (before the TTL validation pass): the
            # record must hold the inputs the decision was COMPUTED from
            self.flight_recorder.capture_disruption(
                snapshot, method, budgets, candidates, cmd, results,
                self.clock.now() - started)
        # graceful methods revalidate after the consolidation TTL; eventual
        # (drift) executes immediately (drift.go has no validation pass)
        if method.disruption_class == "graceful":
            self.pending = (cmd, self.clock.now())
            return True
        self._execute(cmd)
        return True

    def _execute(self, cmd: Command) -> None:
        """controller.go:196-246: taint -> launch replacements -> mark ->
        enqueue."""
        self.last_command = cmd
        log.info("disrupting nodes",
                 reason=cmd.reason, decision=cmd.decision,
                 consolidation_type=cmd.consolidation_type,
                 candidates=[c.state_node.name() for c in cmd.candidates],
                 replacements=len(cmd.replacements),
                 trace_id=cmd.trace_id)
        from ..metrics import registry as metrics
        metrics.DISRUPTION_DECISIONS.inc({
            "decision": cmd.decision, "reason": cmd.reason,
            "consolidation_type": cmd.consolidation_type})
        for c in cmd.candidates:
            metrics.NODECLAIMS_DISRUPTED.inc({
                "nodepool": c.nodepool_name, "reason": cmd.reason})
        for c in cmd.candidates:
            node = self.store.get(Node, c.state_node.name())
            if node is not None and not any(
                    t.matches(DISRUPTED_NO_SCHEDULE_TAINT)
                    for t in node.spec.taints):
                node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
                self.store.update(node)
        replacement_names: List[str] = []
        for nc in cmd.replacements:
            nc.finalize()
            api_nc = nc.to_nodeclaim()
            api_nc.metadata.namespace = ""
            self.store.create(api_nc)
            self.cluster.update_nodeclaim(api_nc)
            replacement_names.append(api_nc.name)
        self.cluster.mark_for_deletion(*(c.provider_id for c in cmd.candidates))
        self.queue.add(QueuedCommand(
            command=cmd, replacement_names=replacement_names,
            enqueued_at=self.clock.now()))
