"""Prometheus-style metrics registry.

Mirrors the metric families of /root/reference/pkg/metrics/metrics.go (the
karpenter_ namespace counters for nodeclaims/nodes/pods) plus the solver
timing metrics (provisioning/scheduling/metrics.go:39-94, disruption/
metrics.go:44-85), with text exposition for scraping.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

NAMESPACE = "karpenter"


def _label_key(labels: dict) -> Tuple:
    return tuple(sorted(labels.items()))


def _count_series_drop(metric_name: str) -> None:
    # SERIES_DROPPED is defined at module bottom (it needs REGISTRY); it is
    # itself uncapped, so this can never recurse
    sd = globals().get("SERIES_DROPPED")
    if sd is not None:
        sd.inc({"metric": metric_name})


class Metric:
    def __init__(self, name: str, help: str, label_names: Iterable[str] = (),
                 max_series: int = 0):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        # cardinality cap (0 = unbounded): a pathological label mix (one
        # series per pod uid, per dynamic phase name, ...) must not grow
        # the registry without bound — new series past the cap are dropped
        # and counted on karpenter_metrics_series_dropped_total{metric}
        self.max_series = max_series
        self._values: Dict[Tuple, float] = {}

    def _admit(self, container: dict, k: Tuple) -> bool:
        if not self.max_series or k in container \
                or len(container) < self.max_series:
            return True
        _count_series_drop(self.name)
        return False

    def labels_dict(self, key: Tuple) -> dict:
        return dict(key)


class Counter(Metric):
    kind = "counter"

    def inc(self, labels: Optional[dict] = None, value: float = 1.0) -> None:
        k = _label_key(labels or {})
        if not self._admit(self._values, k):
            return
        self._values[k] = self._values.get(k, 0.0) + value

    def value(self, labels: Optional[dict] = None) -> float:
        return self._values.get(_label_key(labels or {}), 0.0)


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, labels: Optional[dict] = None) -> None:
        k = _label_key(labels or {})
        if not self._admit(self._values, k):
            return
        self._values[k] = value

    def delete(self, labels: Optional[dict] = None) -> None:
        self._values.pop(_label_key(labels or {}), None)

    def prune(self, live: "list[dict]") -> None:
        """Drop every series not in `live` — exporters that mirror object
        state call this so deleted objects' series disappear instead of
        freezing at their last value (and cardinality stays bounded)."""
        keep = {_label_key(d) for d in live}
        for k in [k for k in self._values if k not in keep]:
            del self._values[k]

    def value(self, labels: Optional[dict] = None) -> float:
        return self._values.get(_label_key(labels or {}), 0.0)


class Histogram(Metric):
    kind = "histogram"
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                       1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

    def __init__(self, name, help, label_names=(), buckets=None,
                 max_series: int = 0):
        super().__init__(name, help, label_names, max_series=max_series)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float, labels: Optional[dict] = None) -> None:
        k = _label_key(labels or {})
        if not self._admit(self._counts, k):
            return
        counts = self._counts.setdefault(k, [0] * (len(self.buckets) + 1))
        for i, b in enumerate(self.buckets):
            if value <= b:
                counts[i] += 1
        counts[-1] += 1  # +Inf
        self._sums[k] = self._sums.get(k, 0.0) + value

    def count(self, labels: Optional[dict] = None) -> int:
        k = _label_key(labels or {})
        return self._counts.get(k, [0])[-1]

    def sum(self, labels: Optional[dict] = None) -> float:
        return self._sums.get(_label_key(labels or {}), 0.0)


class Registry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()
        # measure() duration clock, injectable (the set_condition_clock
        # pattern): fake-clock tests assert exact bucket placement instead
        # of sleeping
        self._measure_clock = time.perf_counter

    def set_measure_clock(self, now) -> "Callable[[], float]":
        """Swap the measure() timing clock; returns the previous one so
        tests can restore it."""
        prev = self._measure_clock
        self._measure_clock = now
        return prev

    def counter(self, name: str, help: str = "", label_names=(),
                max_series: int = 0) -> Counter:
        return self._register(Counter, name, help, label_names, max_series)

    def gauge(self, name: str, help: str = "", label_names=(),
              max_series: int = 0) -> Gauge:
        return self._register(Gauge, name, help, label_names, max_series)

    def histogram(self, name: str, help: str = "", label_names=(),
                  buckets=None, max_series: int = 0) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help, label_names, buckets,
                              max_series=max_series)
                self._metrics[name] = m
            return m

    def _register(self, cls, name, help, label_names, max_series=0):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, label_names, max_series=max_series)
                self._metrics[name] = m
            return m

    def measure(self, histogram_name: str, labels: Optional[dict] = None):
        """metrics.Measure() duration helper (metrics.go:88-96), timed on
        the injectable measure clock."""
        h = self.histogram(histogram_name)
        start = self._measure_clock()

        def done():
            h.observe(self._measure_clock() - start, labels)

        return done

    # -- exposition ---------------------------------------------------------

    def expose(self) -> str:
        lines: List[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                for k, counts in m._counts.items():
                    lbl = dict(k)
                    cum = 0
                    for b, c in zip(m.buckets, counts[:-1]):
                        cum = c
                        lines.append(_line(f"{name}_bucket",
                                           {**lbl, "le": _fmt(b)}, cum))
                    lines.append(_line(f"{name}_bucket",
                                       {**lbl, "le": "+Inf"}, counts[-1]))
                    lines.append(_line(f"{name}_sum", lbl, m._sums.get(k, 0.0)))
                    lines.append(_line(f"{name}_count", lbl, counts[-1]))
            else:
                for k, v in m._values.items():
                    lines.append(_line(name, dict(k), v))
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    return repr(v) if not math.isinf(v) else "+Inf"


def _escape(v) -> str:
    """Prometheus text-format label-value escaping (exposition format spec:
    backslash, double-quote, and line feed must be escaped)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _line(name: str, labels: dict, value) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape(v)}"'
                        for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {value}"
    return f"{name} {value}"


REGISTRY = Registry()

# -- metric families mirrored from the reference ---------------------------

NODECLAIMS_CREATED = REGISTRY.counter(
    "karpenter_nodeclaims_created_total",
    "Number of nodeclaims created", ("nodepool",))
NODECLAIMS_TERMINATED = REGISTRY.counter(
    "karpenter_nodeclaims_terminated_total",
    "Number of nodeclaims terminated", ("nodepool",))
NODECLAIMS_DISRUPTED = REGISTRY.counter(
    "karpenter_nodeclaims_disrupted_total",
    "Number of nodeclaims disrupted", ("nodepool", "reason"))
NODES_CREATED = REGISTRY.counter(
    "karpenter_nodes_created_total", "Number of nodes created", ("nodepool",))
NODES_TERMINATED = REGISTRY.counter(
    "karpenter_nodes_terminated_total", "Number of nodes terminated",
    ("nodepool",))
NODE_TERMINATION_DURATION = REGISTRY.histogram(
    "karpenter_nodes_termination_duration_seconds",
    "Deletion-timestamp to finalizer removal (drain + detach + instance)",
    ("nodepool",),
    buckets=(1, 5, 10, 30, 60, 120, 300, 600, 1800, 3600))
NODE_LIFETIME_DURATION = REGISTRY.histogram(
    "karpenter_nodes_lifetime_duration_seconds",
    "Node creation to termination",
    ("nodepool",),
    buckets=(60, 300, 1800, 3600, 6 * 3600, 24 * 3600, 7 * 24 * 3600))
PODS_STARTUP_DURATION = REGISTRY.histogram(
    "karpenter_pods_startup_duration_seconds",
    "Time from pod creation to running")
SCHEDULING_DURATION = REGISTRY.histogram(
    "karpenter_provisioner_scheduling_duration_seconds",
    "Duration of one scheduling solve")
SCHEDULING_QUEUE_DEPTH = REGISTRY.gauge(
    "karpenter_provisioner_scheduling_queue_depth",
    "Pending pods in the scheduling queue")
UNSCHEDULABLE_PODS = REGISTRY.gauge(
    "karpenter_ignored_pod_count", "Pods the solver could not place")
DISRUPTION_EVAL_DURATION = REGISTRY.histogram(
    "karpenter_voluntary_disruption_decision_evaluation_duration_seconds",
    "Duration of disruption decision evaluation", ("method",))
DISRUPTION_DECISIONS = REGISTRY.counter(
    "karpenter_voluntary_disruption_decisions_total",
    "Disruption decisions made", ("decision", "reason", "consolidation_type"))
DISRUPTION_ELIGIBLE_NODES = REGISTRY.gauge(
    "karpenter_voluntary_disruption_eligible_nodes",
    "Nodes eligible for disruption", ("reason",))
CONSOLIDATION_TIMEOUTS = REGISTRY.counter(
    "karpenter_voluntary_disruption_consolidation_timeouts_total",
    "Consolidation searches abandoned at their timeout",
    ("consolidation_type",))
# -- streaming disruption engine (ISSUE 14): cross-pass delta residency ----

DISRUPTION_STREAM_LAYERS = REGISTRY.counter(
    "karpenter_disruption_stream_reuse_total",
    "Streaming-snapshot layer outcomes per disruption pass",
    ("layer", "outcome"))
DISRUPTION_STREAM_ROWS = REGISTRY.counter(
    "karpenter_disruption_candidate_rows_total",
    "Cached candidate-row outcomes per disruption pass", ("outcome",))
DISRUPTION_CANDIDATE_BUILD = REGISTRY.histogram(
    "karpenter_disruption_candidate_build_seconds",
    "Wall clock of the streaming candidate/snapshot refresh per pass")
DISRUPTION_SUBSET_VERDICTS = REGISTRY.counter(
    "karpenter_disruption_subset_verdicts_total",
    "Closed-form multi-node subset verdicts (ranked prefix search)",
    ("kind",))

NODEPOOL_USAGE = REGISTRY.gauge(
    "karpenter_nodepools_usage", "In-use resources per nodepool",
    ("nodepool", "resource_type"))
NODEPOOL_LIMIT = REGISTRY.gauge(
    "karpenter_nodepools_limit", "Resource limits per nodepool",
    ("nodepool", "resource_type"))

# -- fault-tolerant runtime (controller-runtime's
# controller_runtime_reconcile_errors_total analog plus the quarantine /
# circuit-breaker state this runtime adds on top) -------------------------

RECONCILE_ERRORS = REGISTRY.counter(
    "karpenter_reconcile_errors_total",
    "Reconcile invocations that raised, per controller", ("controller",))
RECONCILE_QUARANTINED = REGISTRY.gauge(
    "karpenter_reconcile_quarantined",
    "Work items quarantined in the dead-letter set after exhausting "
    "retries", ("controller",))
EVENTS_DROPPED = REGISTRY.counter(
    "karpenter_events_dropped_total",
    "Events dropped by best-effort delivery", ("reason",))
SOLVER_CIRCUIT_STATE = REGISTRY.gauge(
    "karpenter_solver_circuit_state",
    "Tensor-solver circuit breaker state (0=closed, 1=open, 2=half-open)")
SOLVER_COMPILE_CACHE_HITS = REGISTRY.counter(
    "karpenter_solver_compile_cache_hits_total",
    "Feasibility-precompute solves served by an already-compiled "
    "executable for their padded shape bucket")
SOLVER_COMPILE_CACHE_MISSES = REGISTRY.counter(
    "karpenter_solver_compile_cache_misses_total",
    "Feasibility-precompute solves that had to compile a fresh executable "
    "for a new padded shape bucket")
OFFERINGS_UNAVAILABLE = REGISTRY.gauge(
    "karpenter_offerings_unavailable",
    "Offering keys currently cached as unavailable (TTL live) in the "
    "capacity-failure feedback registry")
OFFERINGS_MARKED = REGISTRY.counter(
    "karpenter_offerings_marked_total",
    "Offering keys marked unavailable by capacity failures", ("reason",))
NODECLAIMS_LIVENESS_TERMINATED = REGISTRY.counter(
    "karpenter_nodeclaims_liveness_terminated_total",
    "NodeClaims deleted because they failed to register within the "
    "liveness TTL", ("nodepool",))
FLIGHTREC_RECORDS = REGISTRY.counter(
    "karpenter_flightrecorder_records_total",
    "Decision records captured by the flight recorder", ("kind",))
FLIGHTREC_DROPPED = REGISTRY.counter(
    "karpenter_flightrecorder_dropped_total",
    "Decision records dropped (ring eviction or capture failure)",
    ("reason",))
PROBLEM_STATE_SHARD_ROWS = REGISTRY.counter(
    "karpenter_problem_state_shard_rows_total",
    "Existing-node rows handled per mesh shard of the sharded "
    "ProblemState, by outcome: reencoded/clean at encode time, "
    "uploaded/upload_skipped at device-placement time",
    ("shard", "outcome"), max_series=256)
STATE_PLANE_SUBSCRIBERS = REGISTRY.gauge(
    "karpenter_state_plane_subscribers",
    "Live subscriber handles per shared EncodePlane (state/plane.py); "
    "pruned to the live-plane set on every refresh",
    ("plane",), max_series=256)
STATE_PLANE_ROWS = REGISTRY.counter(
    "karpenter_state_plane_rows_total",
    "Node/group rows served by the shared EncodePlane per subscriber, "
    "by outcome: shared (cache hit, possibly encoded by another "
    "subscriber) vs reencoded",
    ("subscriber", "outcome"), max_series=256)
STATE_AUDIT = REGISTRY.counter(
    "karpenter_state_audit_total",
    "Warm-state integrity audits (state/audit.py StateAuditor) by cache "
    "layer and outcome: audited (shadow re-encode / digest verify "
    "matched) vs corrupt (mismatch -> the layer quarantined to a cold "
    "rebuild for the pass). layer=device carries the mesh degradation "
    "ladder: killed (device lost mid-dispatch), carve/single (the pass "
    "completed on a degraded rung), readmitted (half-open probe "
    "succeeded and the breaker re-closed)",
    ("layer", "outcome"), max_series=64)
EXIST_SPLICE_BYTES = REGISTRY.counter(
    "karpenter_exist_splice_bytes_total",
    "Exist-side per-shard delta placement bytes, by outcome: uploaded "
    "(dirty spans spliced host->device) vs skipped (clean spans left "
    "resident in the donated device buffer)",
    ("outcome",), max_series=4)

def phase_seconds_by_name() -> Dict[str, float]:
    """Total observed seconds per phase (span name) across every label
    combination of karpenter_solver_phase_duration_seconds — the sim
    report's per-subsystem attribution source (snapshot at run start,
    delta at the end)."""
    out: Dict[str, float] = {}
    # list() snapshot: solver threads may observe new series mid-iteration
    for k, s in list(SOLVER_PHASE_DURATION._sums.items()):
        phase = dict(k).get("phase", "")
        out[phase] = out.get(phase, 0.0) + s
    return out


# -- bounded tenant label ---------------------------------------------------
# The sidecar serves many tenant clusters from one process; tenant-labeled
# series (queue depth/wait, phase histograms) must stay bounded no matter
# what tenant names clients send. First-come tenants keep their name; past
# the cap every new tenant maps to the shared overflow value, so a
# tenant-per-request caller can't explode series cardinality (the PR-7
# max_series cap then never has to silently drop real phase series).

TENANT_LABEL_CAP = 32
TENANT_OVERFLOW = "_other"
_TENANT_LABELS: set = set()


def tenant_label(tenant) -> str:
    """Bounded tenant label value (see TENANT_LABEL_CAP above)."""
    t = str(tenant)
    if t in _TENANT_LABELS:
        return t
    if len(_TENANT_LABELS) < TENANT_LABEL_CAP:
        _TENANT_LABELS.add(t)
        return t
    return TENANT_OVERFLOW


# -- pass-level tracing + end-to-end SLO layer (obs/) ----------------------

SOLVER_PHASE_DURATION = REGISTRY.histogram(
    "karpenter_solver_phase_duration_seconds",
    "Per-phase solver wall clock, derived from the pass tracer's span data "
    "(phase = span name: encode.catalog, encode.groups, encode.nodes, "
    "device.upload, compile, device.execute, pack, materialize, ...); "
    "sidecar-served solves add a bounded tenant label",
    ("phase", "encode_kind", "tenant"),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0, 10.0),
    # phases are a fixed vocabulary (~40 span names) x {cold, delta, ""} x
    # bounded tenants (TENANT_LABEL_CAP + overflow + the in-process "") —
    # worst case ~4k legitimate series, so the cap is sized as a backstop
    # against a DYNAMIC span name leaking in, not a lid real tenants hit
    max_series=8192)
PODS_TIME_TO_SCHEDULE = REGISTRY.histogram(
    "karpenter_pods_time_to_schedule_seconds",
    "First seen pending to capacity decision (NodeClaim created or "
    "existing-node placement) per pod — the operator-side end-to-end "
    "scheduling SLO",
    buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
             1800.0))
SLO_BREACHES = REGISTRY.counter(
    "karpenter_slo_breaches_total",
    "Pass traces that exceeded a configured SLO budget (slo = the watched "
    "span name); each breach also publishes an SLOBreached warning event "
    "and dumps the pass's flight-recorder records",
    ("slo",), max_series=64)
SERIES_DROPPED = REGISTRY.counter(
    "karpenter_metrics_series_dropped_total",
    "Label sets dropped by a metric's cardinality cap (max_series)",
    ("metric",))

# -- multi-tenant solver sidecar (sidecar/server.py admission layer) -------

SIDECAR_QUEUE_DEPTH = REGISTRY.gauge(
    "karpenter_sidecar_queue_depth",
    "Solve requests waiting in the sidecar's admission queue, per tenant "
    "(bounded tenant label)",
    ("tenant",), max_series=64)
SIDECAR_QUEUE_WAIT = REGISTRY.histogram(
    "karpenter_sidecar_queue_wait_seconds",
    "Admission-queue wait before a sidecar solve reaches the device, per "
    "tenant (bounded tenant label)",
    ("tenant",),
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0),
    max_series=64)
SIDECAR_RESYNCS = REGISTRY.counter(
    "karpenter_sidecar_session_resyncs_total",
    "Delta-session resync triggers: content-digest mismatches, LRU/idle "
    "session evictions, unknown-session hits from stale clients",
    ("reason",), max_series=16)

# -- fault-tolerant service path (ISSUE 11): crash-safe server + resilient
# client. Server side: tenant-fair load shedding, drain state, and the
# request-digest dedupe cache that makes retries/hedges idempotent. Client
# side: deadline/backoff retries and hedged solves. ---------------------------

SIDECAR_SHED = REGISTRY.counter(
    "karpenter_sidecar_shed_total",
    "Solve requests shed from the sidecar admission queue: 'fairness' = a "
    "burst tenant's newest waiter evicted so an under-share tenant could "
    "enqueue, 'overload' = rejected at the saturated bound, 'draining' = "
    "NACKed during graceful drain (all retryable client-side)",
    ("tenant", "reason"), max_series=128)
SIDECAR_DEDUP_HITS = REGISTRY.counter(
    "karpenter_sidecar_dedup_hits_total",
    "Session solve requests served from the request-digest response cache "
    "(a retry or hedge of a request the server already applied — the "
    "at-most-once-apply guarantee), per tenant (bounded label)",
    ("tenant",), max_series=64)
SIDECAR_DRAINING = REGISTRY.gauge(
    "karpenter_sidecar_draining",
    "1 while the sidecar is draining (new RPCs NACKed UNAVAILABLE, "
    "in-flight solves finishing), 0 otherwise")
SIDECAR_CLIENT_RETRIES = REGISTRY.counter(
    "karpenter_sidecar_client_retries_total",
    "Client-side RPC retries by status code that triggered them "
    "(unavailable, deadline_exceeded, resource_exhausted; jittered "
    "exponential backoff under a token retry budget)",
    ("code",), max_series=16)
SIDECAR_CLIENT_HEDGES = REGISTRY.counter(
    "karpenter_sidecar_client_hedges_total",
    "Hedged solve RPCs: 'fired' = a second identical request launched "
    "after hedge_delay with no response, 'won' = the hedge answered first "
    "(safe: solves are pure functions of session state and the server "
    "dedupes by request digest)",
    ("outcome",), max_series=8)

# -- replicated sidecar fleet (ISSUE 17): session checkpoint/migration,
# consistent-hash tenant routing, zero-downtime rolling restarts. ------------

SIDECAR_MIGRATIONS = REGISTRY.counter(
    "karpenter_sidecar_migrations_total",
    "Session checkpoint movements in a sidecar fleet: 'drain' = exported "
    "to the handoff store by a draining replica, 'restore' = rebuilt warm "
    "on a peer from its checkpoint, 'rollback' = a digest-mismatched "
    "session reloaded from its last acked checkpoint for delta catch-up, "
    "'restore_rejected' = a checkpoint the codec loudly refused "
    "(corrupt/truncated/version skew), 'export_error' = a post-solve "
    "checkpoint write that failed",
    ("reason",), max_series=16)
SIDECAR_HANDOFF_EVICTED = REGISTRY.counter(
    "karpenter_sidecar_handoff_evicted_total",
    "Fleet handoff-store session checkpoints evicted, by reason: 'cap' "
    "= LRU-dropped past the entry bound, 'ttl' = orphaned past the "
    "expiry (the owning replica died without a successor restoring it)",
    ("reason",), max_series=4)
SIDECAR_REPLICA_SESSIONS = REGISTRY.gauge(
    "karpenter_sidecar_replica_sessions",
    "Live delta sessions held by each sidecar fleet replica (bounded "
    "replica label)",
    ("replica",), max_series=32)
SIDECAR_REPLICA_FAILOVERS = REGISTRY.counter(
    "karpenter_sidecar_replica_failovers_total",
    "Client-side replica switches by the consistent-hash fleet router: "
    "'migrated' = followed a draining replica's migrated_to rider, "
    "'unavailable' = re-routed to the ring successor after consecutive "
    "UNAVAILABLE answers marked the replica down",
    ("reason",), max_series=8)

# -- whole-fleet causal observability (ISSUE 12) ---------------------------
# Fallback cost ledger: every host-oracle escape classified by the shape
# class that forced it (obs/fallbacks.py), so ROADMAP item 1 gets its
# priority ordering from measurements instead of guesses. Device truth:
# per-executable dispatch-vs-device time split and XLA memory watermarks
# (obs/device.py). Profile lifecycle: obs/profile.py.

FALLBACK_PODS = REGISTRY.counter(
    "karpenter_fallback_pods_total",
    "Pods solved on the host-oracle path instead of the tensor kernel "
    "(subsystem=provisioning) or LOO consolidation candidate rows punted "
    "to exact replay sims (subsystem=disruption), by the shape class that "
    "forced the escape (volumes, topo, ports, minvalues, multi_group, "
    "limits, base_pods, circuit_open, ...)",
    ("shape", "subsystem"), max_series=64)
FALLBACK_SOLVES = REGISTRY.counter(
    "karpenter_fallback_solves_total",
    "Solves (or disruption passes) in which at least one pod/candidate "
    "escaped the batched math, by shape class (a mixed solve increments "
    "every class it contains)",
    ("shape", "subsystem"), max_series=64)
FALLBACK_HOST_SECONDS = REGISTRY.counter(
    "karpenter_fallback_host_seconds_total",
    "Wall seconds spent in the host-oracle path (full fallbacks and "
    "remainder passes), attributed pro-rata by pod count across the "
    "solve's escape shape classes",
    ("shape", "subsystem"), max_series=64)
FALLBACK_TENSOR_SECONDS = REGISTRY.counter(
    "karpenter_fallback_tensor_seconds_total",
    "Wall seconds spent in the tensor path across all solves — the "
    "denominator for host-vs-tensor cost comparisons on mixed batches")
DEVICE_DISPATCHES = REGISTRY.counter(
    "karpenter_device_dispatches_total",
    "Dispatches of a cached compiled executable, per executable label "
    "(the binpack padded-shape-bucket cache key's digest)",
    ("executable",), max_series=64)
DEVICE_DISPATCH_SECONDS = REGISTRY.counter(
    "karpenter_device_dispatch_seconds_total",
    "Host-side dispatch overhead (exe(*args) enqueue time) per executable "
    "— the host half of the device-time attribution split",
    ("executable",), max_series=64)
DEVICE_EXECUTE_SECONDS = REGISTRY.counter(
    "karpenter_device_execute_seconds_total",
    "Measured device completion time (block_until_ready delta after "
    "dispatch) per executable — the accelerator half of the split; only "
    "collected while tracing is enabled",
    ("executable",), max_series=64)
DEVICE_MEMORY_PEAK = REGISTRY.gauge(
    "karpenter_device_memory_peak_bytes",
    "Per-device XLA memory watermark: the max memory_analysis() peak "
    "(args + temps + output) across every executable compiled so far",
    ("device",), max_series=64)
PROFILE_ACTIVE = REGISTRY.gauge(
    "karpenter_profile_active",
    "1 while a jax.profiler device-trace session is running "
    "(/debug/profile?device=start or python -m karpenter_tpu.obs profile)")

# -- trace-driven fleet simulator (sim/) -----------------------------------
# The simulator's own aggregate truth lives in its report/ledger (those are
# digested for determinism); these families exist so a sim run serves the
# SAME /metrics surface an operator does — dashboards built against a live
# cluster read identically against a replay.

SIM_EVENTS_APPLIED = REGISTRY.counter(
    "karpenter_sim_events_applied_total",
    "Scenario timeline events the fleet simulator has actuated, by event "
    "kind (deploy, scale, rolling_update, pdb, spot_reclaim, zonal_outage, "
    "drought, drain, flaky, slo)",
    ("kind",), max_series=32)
SIM_TICKS = REGISTRY.counter(
    "karpenter_sim_ticks_total",
    "Simulator loop iterations (one full operator quiesce per tick; the "
    "adaptive stepper jumps straight to the next scenario event, manager "
    "timer, or batcher deadline)")
SIM_CLOCK_SECONDS = REGISTRY.gauge(
    "karpenter_sim_clock_seconds",
    "Simulated seconds elapsed since scenario start (the accelerated "
    "FakeClock's progress through the timeline)")
SIM_POD_HOURS = REGISTRY.counter(
    "karpenter_sim_pod_hours_total",
    "Bound-pod hours integrated over simulated time (the denominator of "
    "the cost-per-pod-hour SLO)")
SIM_FLEET_COST = REGISTRY.counter(
    "karpenter_sim_fleet_cost_dollars_total",
    "Fleet cost integrated from per-node offering prices over simulated "
    "time (the numerator of the cost-per-pod-hour SLO)")
