"""Intentionally empty: the reference (sigs.k8s.io/karpenter) is a
control-plane node autoscaler, not an ML framework - it contains no model
families (SURVEY.md §2.9). The scaffold keeps this package so the standard
layout (models/ ops/ parallel/ utils/) holds; the framework's "models" are
the solver programs in ops/ and provisioning/."""
