"""Structured, level-configurable JSON logging.

Mirrors /root/reference/pkg/operator/logging/logging.go:55-124: one zap-style
JSON line per record ({"level","time","logger","message", ...key-values}),
level set from Options.log_level, a NOP logger for simulation paths that must
stay silent (logging.go:34-36 NopLogger), and named component loggers
(NewLogger(ctx, component)). Built on the stdlib logging machinery so
handlers/levels compose with anything the embedding process already does.
"""

from __future__ import annotations

import json
import logging as stdlog
import sys
import time
from typing import Optional

_LEVELS = {
    "debug": stdlog.DEBUG,
    "info": stdlog.INFO,
    "warn": stdlog.WARNING,
    "warning": stdlog.WARNING,
    "error": stdlog.ERROR,
}

_ROOT_NAME = "karpenter"


class JSONFormatter(stdlog.Formatter):
    """zap production-config encoding (logging.go:60-79): message/level/time/
    logger keys, ISO8601 time, extra key-values inlined."""

    def format(self, record: stdlog.LogRecord) -> str:
        out = {
            "level": record.levelname,
            "time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                  time.localtime(record.created))
            + f".{int(record.msecs):03d}",
            "logger": record.name,
            "message": record.getMessage(),
        }
        kv = getattr(record, "kv", None)
        if kv:
            out.update(kv)
        if record.exc_info and record.exc_info[0] is not None:
            out["error"] = str(record.exc_info[1])
        return json.dumps(out, default=str)


class Logger:
    """zap.SugaredLogger-shaped wrapper: leveled methods take structured
    key-values; with_values() binds context the way zap's With does."""

    def __init__(self, py: stdlog.Logger, bound: Optional[dict] = None):
        self._py = py
        self._bound = dict(bound or {})

    def named(self, name: str) -> "Logger":
        return Logger(self._py.getChild(name), self._bound)

    def with_values(self, **kv) -> "Logger":
        merged = dict(self._bound)
        merged.update(kv)
        return Logger(self._py, merged)

    def _log(self, level: int, msg: str, kv: dict) -> None:
        if not self._py.isEnabledFor(level):
            return
        merged = dict(self._bound)
        merged.update(kv)
        self._py.log(level, msg, extra={"kv": merged})

    def debug(self, msg: str, **kv) -> None:
        self._log(stdlog.DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._log(stdlog.INFO, msg, kv)

    def warning(self, msg: str, **kv) -> None:
        self._log(stdlog.WARNING, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._log(stdlog.ERROR, msg, kv)


def configure(level: str = "info", stream=None) -> None:
    """Install the JSON handler on the karpenter root logger (idempotent;
    reconfiguring replaces the handler). Mirrors DefaultZapConfig: level from
    options, single output stream, no propagation into the host process's
    root logger."""
    root = stdlog.getLogger(_ROOT_NAME)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = stdlog.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JSONFormatter())
    root.addHandler(handler)
    root.setLevel(_LEVELS.get(level.lower(), stdlog.INFO))
    root.propagate = False


def get_logger(component: str = "") -> Logger:
    """NewLogger(ctx, component) analog. Loggers are children of the
    karpenter root, so one configure() call governs them all."""
    name = f"{_ROOT_NAME}.{component}" if component else _ROOT_NAME
    return Logger(stdlog.getLogger(name))


# NopLogger (logging.go:34-36): consolidation simulations re-enter the
# scheduler many times per decision; they log nothing.
_nop = stdlog.getLogger(_ROOT_NAME + ".nop")
_nop.addHandler(stdlog.NullHandler())
_nop.propagate = False
_nop.setLevel(stdlog.CRITICAL + 1)
NOP = Logger(_nop)
