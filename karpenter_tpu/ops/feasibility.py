"""JAX feasibility kernels over encoded requirement tensors.

These reproduce, as dense vector ops, exactly the checks the host scheduler
runs per pod x instance-type (reference: scheduling/nodeclaim.go:248-301 —
compatible() = Requirements.Intersects, fits() = resources.Fits, offering
compatibility = Offerings.Available().HasCompatible):

- ``intersects_matrix``  [A,B]: pairwise Requirements.Intersects emptiness rule
  incl. the both-sides-{NotIn,DoesNotExist} exemption and Gt/Lt joint-bound
  collapse (requirements.go:283-304, requirement.go:155-188).
- ``compatible_matrix``  [A,B]: Intersects plus the undefined-key rule with an
  allow-undefined key set (requirements.go:175-187).
- ``fits_matrix``        [A,B]: int32 resource fit.
- ``offering_compat``    [B,T]: any available offering whose (zone, capacity
  type) values are admitted by the B-side masks.
- ``combine``: requirement-set intersection of two encoded batches — the tensor
  analogue of Requirements.Add over all keys at once.

All kernels are shape-polymorphic pure functions; jit/vmap/shard_map friendly.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Enc(NamedTuple):
    """Device-side batch of encoded requirement sets ([..., K, W] / [..., K])."""
    mask: jax.Array        # uint32 [..., K, W]
    defined: jax.Array     # bool [..., K]
    complement: jax.Array  # bool [..., K]
    exempt: jax.Array      # bool [..., K]
    gt: jax.Array          # int32 [..., K]
    lt: jax.Array          # int32 [..., K]


def to_device(e) -> Enc:
    return Enc(mask=jnp.asarray(e.mask.astype(np.uint32)),
               defined=jnp.asarray(e.defined),
               complement=jnp.asarray(e.complement),
               exempt=jnp.asarray(e.exempt),
               gt=jnp.asarray(np.clip(e.gt, -2**31, 2**31 - 1).astype(np.int32)),
               lt=jnp.asarray(np.clip(e.lt, -2**31, 2**31 - 1).astype(np.int32)))


def host_enc(e) -> Enc:
    """to_device's dtype normalization WITHOUT committing to a device: host
    numpy leaves, for callers whose placement is decided later (a sharded
    AOT executable auto-places uncommitted inputs per its compiled
    shardings; a jnp.asarray here would commit to the default device and be
    rejected)."""
    return Enc(mask=np.ascontiguousarray(e.mask.astype(np.uint32)),
               defined=np.asarray(e.defined, dtype=bool),
               complement=np.asarray(e.complement, dtype=bool),
               exempt=np.asarray(e.exempt, dtype=bool),
               gt=np.clip(e.gt, -2**31, 2**31 - 1).astype(np.int32),
               lt=np.clip(e.lt, -2**31, 2**31 - 1).astype(np.int32))


def _pairwise_nonempty(a: Enc, b: Enc):
    """[A,B,K] mask-AND emptiness + joint bound collapse."""
    # accumulate over words to keep peak memory at [A,B,K]
    W = a.mask.shape[-1]
    nonempty = None
    for w in range(W):
        inter = a.mask[:, None, :, w] & b.mask[None, :, :, w]
        nz = inter != 0
        nonempty = nz if nonempty is None else (nonempty | nz)
    gt = jnp.maximum(a.gt[:, None, :], b.gt[None, :, :])
    lt = jnp.minimum(a.lt[:, None, :], b.lt[None, :, :])
    both_bounded = (gt > jnp.int32(-2**31)) & (lt < jnp.int32(2**31 - 1))
    crossed = both_bounded & (gt >= lt)
    return nonempty & ~crossed


def intersects_matrix(a: Enc, b: Enc) -> jax.Array:
    """[A,B] True where a.Intersects(b) passes (requirements.go:283-304)."""
    nonempty = _pairwise_nonempty(a, b)
    checked = a.defined[:, None, :] & b.defined[None, :, :]
    exempt = a.exempt[:, None, :] & b.exempt[None, :, :]
    bad = checked & ~nonempty & ~exempt
    return ~jnp.any(bad, axis=-1)


def compatible_matrix(a: Enc, b: Enc, allow_undefined: jax.Array) -> jax.Array:
    """[A,B] True where a.Compatible(b, allow_undefined) passes
    (requirements.go:175-187). allow_undefined: bool [K]."""
    nonempty = _pairwise_nonempty(a, b)
    checked = a.defined[:, None, :] & b.defined[None, :, :]
    exempt = a.exempt[:, None, :] & b.exempt[None, :, :]
    bad = checked & ~nonempty & ~exempt
    undef_bad = (b.defined[None, :, :] & ~a.defined[:, None, :]
                 & ~allow_undefined[None, None, :] & ~b.exempt[None, :, :])
    return ~jnp.any(bad | undef_bad, axis=-1)


def combine(a: Enc, b: Enc) -> Enc:
    """Per-key intersection of two aligned batches (shapes must broadcast) —
    the tensor analogue of Requirements.Add(...) over every key at once
    (requirement.go:155-188 semantics)."""
    gt = jnp.maximum(a.gt, b.gt)
    lt = jnp.minimum(a.lt, b.lt)
    both_bounded = (gt > jnp.int32(-2**31)) & (lt < jnp.int32(2**31 - 1))
    crossed = both_bounded & (gt >= lt)
    mask = jnp.where(crossed[..., None], jnp.uint32(0), a.mask & b.mask)
    complement = a.complement & b.complement & ~crossed
    empty = ~jnp.any(mask != 0, axis=-1)
    exempt = jnp.where(complement, a.exempt | b.exempt, empty)
    # concrete results drop bounds (requirement.go:183-186)
    gt = jnp.where(complement, gt, jnp.int32(-2**31))
    lt = jnp.where(complement, lt, jnp.int32(2**31 - 1))
    return Enc(mask=mask, defined=a.defined | b.defined, complement=complement,
               exempt=exempt, gt=gt, lt=lt)


def fits_matrix(requests: jax.Array, available: jax.Array) -> jax.Array:
    """requests [B,R] x available [A,R] -> [A,B] bool (resources.Fits:
    zero-valued requests always fit; missing resources encode as 0)."""
    req = requests[None, :, :]
    avail = available[:, None, :]
    ok = (req <= 0) | (req <= avail)
    return jnp.all(ok, axis=-1)


def offering_compat(mask_b: jax.Array, zone_key: int, captype_key: int,
                    off_zone: jax.Array, off_captype: jax.Array,
                    off_available: jax.Array) -> jax.Array:
    """[B,T]: does any available offering of instance type t satisfy entity b's
    zone/capacity-type masks? (Offerings.Available().HasCompatible — an
    offering passes when the entity's mask at the key admits its single value.)

    mask_b: uint32 [B,K,W]; off_zone/off_captype: int32 [T,O] value indices
    (-1 == offering doesn't constrain that key); off_available: bool [T,O].
    """
    def bit_ok(masks, key, idx):
        # masks [B,W'] for the key; idx [T,O]
        word = jnp.where(idx >= 0, idx // 32, 0)
        bit = jnp.where(idx >= 0, idx % 32, 0)
        m = masks[:, None, None, :]            # [B,1,1,W]
        w = jnp.take_along_axis(
            jnp.broadcast_to(m, m.shape[:1] + idx.shape + m.shape[-1:]),
            jnp.broadcast_to(word[None, :, :, None], (masks.shape[0],) + idx.shape + (1,)),
            axis=-1)[..., 0]                   # [B,T,O]
        has = (w >> bit[None, :, :].astype(jnp.uint32)) & jnp.uint32(1)
        return jnp.where(idx[None, :, :] >= 0, has == 1, True)

    zone_ok = bit_ok(mask_b[:, zone_key, :], zone_key, off_zone)
    cap_ok = bit_ok(mask_b[:, captype_key, :], captype_key, off_captype)
    ok = off_available[None, :, :] & zone_ok & cap_ok
    return jnp.any(ok, axis=-1)


def pods_per_node(alloc: jax.Array, overhead: jax.Array, req: jax.Array) -> jax.Array:
    """alloc [T,R], overhead [M,R] (daemon), req [G,R] -> [G,M,T] int32: how many
    identical pods fit a fresh node of type t under template m. Zero-request
    resources don't constrain the pod count — but the daemon overhead itself
    must fit the node in EVERY resource (the host oracle folds daemon
    requests into the claim's request vector, scheduler.go:356-382 +
    nodeclaim.go:108-117, so a type whose overhead outgrows it in any
    column is infeasible there too): such types get 0."""
    free = alloc[None, :, :] - overhead[:, None, :]      # [M,T,R]
    daemon_fits = jnp.all(free >= 0, axis=-1)            # [M,T]
    free = jnp.maximum(free, 0)
    r = req[:, None, None, :]                            # [G,1,1,R]
    per = jnp.where(r > 0, free[None] // jnp.maximum(r, 1), jnp.int32(2**30))
    per = jnp.min(per, axis=-1).astype(jnp.int32)        # [G,M,T]
    return jnp.where(daemon_fits[None], per, jnp.int32(0))
