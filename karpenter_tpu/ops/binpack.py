"""Grouped first-fit-decreasing bin-pack solver with TPU-resident feasibility.

Replaces the reference's per-pod greedy loop (scheduler.go:207-315, O(pods x
instance-types) with full refiltering per pod) by:

1. ``precompute`` — ONE jit-compiled device program computing every pairwise
   feasibility quantity the greedy needs, over all (group, template, instance
   type, zone, existing node) combinations at once: requirement compatibility
   (bitpacked mask algebra), offering availability per zone, int32 pods-per-node
   via broadcast division. This is the O(G*M*T*Z + G*N) hot math.
2. ``pack`` — a host-side greedy over *groups* (dozens, not tens of thousands)
   in first-fit-decreasing order, making the same decisions the reference
   makes per pod but in closed form per group: zone water-fill for topology
   spreads, per-node caps for hostname spread/anti-affinity, cohort tracking
   for cross-group node mixing, subtractMax limit pessimism per opened node.
   Cohort state lives in a columnar ``CohortSet`` so the in-flight-node scan
   (eligibility, prospective zone commits, capacity) is batched array math
   per group instead of per-cohort Python — the round-6 recovery of the
   sub-second 50k x 2k flagship solve.

Node-count parity with the reference greedy is validated against the host
oracle scheduler in tests/test_binpack_parity.py.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import labels as api_labels
from . import encode as enc
from . import feasibility as feas
from .encode import EncodedRequirements

INT32_MAX = 2**31 - 1


# --------------------------------------------------------------------------
# numpy mini-algebra over EncodedRequirements rows (host-side cohort updates;
# same rules as feasibility.py kernels, scalar-shaped)
# --------------------------------------------------------------------------

def np_compatible(a: EncodedRequirements, b: EncodedRequirements,
                  allow_undefined: np.ndarray) -> bool:
    gt = np.maximum(a.gt, b.gt)
    lt = np.minimum(a.lt, b.lt)
    crossed = (gt > -2**31) & (lt < 2**31 - 1) & (gt >= lt)
    nonempty = np.any(a.mask & b.mask, axis=-1) & ~crossed
    checked = a.defined & b.defined
    exempt = a.exempt & b.exempt
    bad = checked & ~nonempty & ~exempt
    undef_bad = b.defined & ~a.defined & ~allow_undefined & ~b.exempt
    return not np.any(bad | undef_bad)


def np_combine(a: EncodedRequirements, b: EncodedRequirements) -> EncodedRequirements:
    gt = np.maximum(a.gt, b.gt)
    lt = np.minimum(a.lt, b.lt)
    crossed = (gt > -2**31) & (lt < 2**31 - 1) & (gt >= lt)
    mask = np.where(crossed[..., None], np.uint32(0), a.mask & b.mask)
    complement = a.complement & b.complement & ~crossed
    empty = ~np.any(mask != 0, axis=-1)
    exempt = np.where(complement, a.exempt | b.exempt, empty)
    gt = np.where(complement, gt, -2**31)
    lt = np.where(complement, lt, 2**31 - 1)
    return EncodedRequirements(mask=mask, defined=a.defined | b.defined,
                               complement=complement, exempt=exempt, gt=gt, lt=lt)


# --------------------------------------------------------------------------
# problem + device precompute
# --------------------------------------------------------------------------

@dataclass
class PackProblem:
    """Fully encoded solve input. Build via provisioning.tensor_scheduler."""
    vocab: enc.Vocab
    # groups
    group_enc: EncodedRequirements        # stacked [G, ...]
    group_req: np.ndarray                 # int64 [G, R] scaled requests
    group_count: np.ndarray               # int64 [G]
    # templates
    template_enc: EncodedRequirements     # [M, ...]
    daemon_overhead: np.ndarray           # int64 [M, R]
    tol_template: np.ndarray              # bool [G, M] pod tolerates template taints
    # instance types (union catalog)
    it_enc: EncodedRequirements           # [T, ...]
    it_alloc: np.ndarray                  # int64 [T, R]
    it_capacity: np.ndarray               # int64 [T, R]
    it_price: np.ndarray                  # float32 [T] cheapest available offering
    template_its: np.ndarray              # bool [M, T]
    off_zone: np.ndarray                  # int32 [T, O] zone value idx or -1
    off_captype: np.ndarray               # int32 [T, O]
    off_available: np.ndarray             # bool [T, O]
    # zones
    zone_key: int                         # key index of topology zone
    captype_key: int
    zone_values: np.ndarray               # int32 [Z] value indices
    # existing nodes (may be empty)
    exist_enc: Optional[EncodedRequirements] = None  # [N, ...]
    exist_avail: Optional[np.ndarray] = None         # int64 [N, R]
    exist_zone: Optional[np.ndarray] = None          # int32 [N] zone idx or -1
    tol_exist: Optional[np.ndarray] = None           # bool [G, N]
    allow_undefined: Optional[np.ndarray] = None     # bool [K] well-known keys
    off_price: Optional[np.ndarray] = None           # float32 [T, O] (inf absent)
    # int32 [M, G]: minValues floor on DISTINCT INSTANCE TYPES for the
    # combined (template, group) requirement set, 0 = none. The packer caps
    # every fill so at least this many types survive each claim's it_set —
    # the tensor twin of the per-add SatisfiesMinValues gate
    # (scheduler.py:159-162, types.go:178-212). minValues on other keys
    # stays on the host path (build_problem falls back).
    min_its: Optional[np.ndarray] = None
    # shared mutable slot (from the catalog-encoding cache): device-resident
    # copies of the catalog-side arrays, so repeat solves against the same
    # instance-type catalog skip the host->device upload entirely
    device_cache: Optional[dict] = None
    # content token of the existing-node tensors (set by the persistent
    # ProblemState: node names + revisions + daemonset digest + vocab
    # identity). When set, device_args caches the exist-side device upload
    # in device_cache under this token, so steady-state passes against an
    # unchanged node set skip the [N, ...] host->device upload exactly like
    # the catalog side. None (the default) preserves per-call uploads.
    exist_token: Optional[tuple] = None
    # per-shard content tokens of the existing-node rows (sharded
    # ProblemState over the mesh pods_groups axis): tuple of S tokens, one
    # per contiguous Np/S row span (encode.shard_spans). When set, the mesh
    # placer's put_exist_side re-uploads ONLY the spans whose token changed
    # (a node revision bump re-uploads its shard's rows, not all N). None
    # keeps the whole-side exist_token cache behaviour.
    exist_shard_tokens: Optional[tuple] = None


@dataclass
class PackTensors:
    """Fetched results of the device precompute."""
    compat_tm: np.ndarray      # bool [M, G] template x group requirement compat
    it_ok: np.ndarray          # bool [G, M, T]
    ppn: np.ndarray            # int32 [G, M, T] pods-per-fresh-node
    it_ok_z: np.ndarray        # bool [G, M, T, Z]
    zone_adm: np.ndarray       # bool [G, M, Z] combined reqs admit zone
    exist_ok: np.ndarray       # bool [G, N]
    exist_cap: np.ndarray      # int32 [G, N]


def zone_pack_layout(Z: int):
    """(storage dtype, word count) for the packed zone bitfield — the ONE
    place this is decided: the kernel packs with it and _output_layout
    decodes with it, so they can never drift apart."""
    dtype = np.uint8 if Z <= 8 else (np.uint16 if Z <= 16 else np.uint32)
    return dtype, -(-Z // np.iinfo(dtype).bits)


def precompute_kernel(group, template, it, group_req, daemon, alloc,
                      template_its, off_zone, off_captype, off_available,
                      zone_values, allow_undefined, tol_template,
                      exist, exist_avail, tol_exist,
                      *, zone_key: int, captype_key: int, has_exist: bool):
    G = group.mask.shape[0]
    M = template.mask.shape[0]
    T = it.mask.shape[0]
    Z = zone_values.shape[0]

    # template x group compatibility + combined requirement sets [M*G]
    compat_tm = feas.compatible_matrix(template, group, allow_undefined)  # [M, G]
    cmb = feas.combine(
        jax.tree.map(lambda x: x[:, None], template),
        jax.tree.map(lambda x: x[None, :], group))          # [M, G, K, ...]
    cmb_flat = jax.tree.map(lambda x: x.reshape((M * G,) + x.shape[2:]), cmb)

    # instance-type requirement compat: existing side = IT (nodeclaim.go:295-297)
    it_compat = feas.intersects_matrix(it, cmb_flat)         # [T, M*G]
    it_compat = it_compat.T.reshape(M, G, T).transpose(1, 0, 2)  # [G, M, T]

    # offerings: per zone and any-zone
    zone_bit_words = zone_values // 32
    zone_bits = zone_values % 32
    zmask = cmb_flat.mask[:, zone_key, :]                    # [MG, W]
    zone_adm = ((jnp.take(zmask, zone_bit_words, axis=1)
                 >> zone_bits[None, :].astype(jnp.uint32)) & 1) == 1  # [MG, Z]
    # offering o passes for (mg, t, z) iff available, zone==z, captype admitted
    cap_bit_ok = _offering_value_ok(cmb_flat.mask, captype_key, off_captype)  # [MG,T,O]
    zmatch = off_zone[None, :, :, None] == zone_values[None, None, None, :]   # [1,T,O,Z]
    off_ok_z = jnp.any(off_available[None, :, :, None] & zmatch
                       & cap_bit_ok[:, :, :, None], axis=2)  # [MG, T, Z]
    off_ok_z = off_ok_z & zone_adm[:, None, :]
    off_ok_any = jnp.any(off_ok_z, axis=-1)                  # [MG, T]

    # pods per node
    ppn = feas.pods_per_node(alloc, daemon, group_req)       # [G, M, T]

    ok_base = (it_compat
               & template_its[None, :, :]
               & tol_template[:, :, None]
               & compat_tm.T[:, :, None]
               & (ppn >= 1))
    it_ok_z = (ok_base[:, :, :, None]
               & off_ok_z.reshape(M, G, T, Z).transpose(1, 0, 2, 3))
    # pack the zone axis into a bitfield: Wz fetched words instead of Z+1
    # bool planes (it_ok_any == any bit set, derived host-side). Multi-word
    # so Z > 32 packs losslessly.
    np_dtype, Wz = zone_pack_layout(Z)
    pack_dtype = jnp.dtype(np_dtype)
    word_bits = jnp.iinfo(pack_dtype).bits
    z_pad = Wz * word_bits - Z
    padded_ok = jnp.pad(it_ok_z, ((0, 0), (0, 0), (0, 0), (0, z_pad)))
    weights = (jnp.ones((), pack_dtype)
               << jnp.arange(word_bits, dtype=pack_dtype))
    it_okz_packed = jnp.sum(
        padded_ok.reshape(G, M, T, Wz, word_bits).astype(pack_dtype)
        * weights[None, None, None, None, :], axis=-1,
        dtype=pack_dtype)                                    # [G,M,T,Wz]
    zone_adm_gmz = zone_adm.reshape(M, G, Z).transpose(1, 0, 2)

    if has_exist:
        exist_compat = feas.compatible_matrix(exist, group,
                                              jnp.zeros_like(allow_undefined))  # [N, G]
        exist_ok = exist_compat.T & tol_exist                # [G, N]
        per = jnp.where(group_req[:, None, :] > 0,
                        exist_avail[None, :, :] // jnp.maximum(group_req[:, None, :], 1),
                        jnp.int32(INT32_MAX))
        exist_cap = jnp.clip(jnp.min(per, axis=-1), 0, INT32_MAX).astype(jnp.int32)
        exist_ok = exist_ok & (exist_cap >= 1)
    else:
        exist_ok = jnp.zeros((G, 1), dtype=bool)
        exist_cap = jnp.zeros((G, 1), dtype=jnp.int32)

    ppn16 = jnp.clip(ppn, 0, 32767).astype(jnp.int16)
    return (compat_tm, it_okz_packed, ppn16, zone_adm_gmz, exist_ok, exist_cap)


def _pack_outputs(outs):
    """Flatten the kernel's six outputs into ONE uint8 buffer on device:
    jax.device_get pays a host<->device round trip per array, and through a
    network tunnel (axon) that latency — not bandwidth — dominates the
    fetch. Multi-byte dtypes are bitcast to uint8 lanes; booleans widen."""
    import jax.lax as lax
    parts = []
    for o in outs:
        if o.dtype == jnp.uint8:
            parts.append(o.reshape(-1))
        elif o.dtype == jnp.bool_:
            parts.append(o.astype(jnp.uint8).reshape(-1))
        else:
            parts.append(
                lax.bitcast_convert_type(o.reshape(-1), jnp.uint8).reshape(-1))
    return jnp.concatenate(parts)


def _precompute_packed_kernel(*args, **statics):
    return _pack_outputs(precompute_kernel(*args, **statics))


_precompute_packed = partial(jax.jit, static_argnames=(
    "zone_key", "captype_key", "has_exist"))(_precompute_packed_kernel)


def _split_packed(flat: np.ndarray, shapes_dtypes):
    """Host-side inverse of _pack_outputs."""
    out = []
    off = 0
    for shape, dtype, logical in shapes_dtypes:
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        chunk = flat[off:off + n].view(dtype).reshape(shape)
        off += n
        out.append(chunk.astype(bool) if logical == "bool" else chunk)
    assert off == flat.size, \
        f"packed output layout desync: consumed {off} of {flat.size} bytes"
    return out


def _offering_value_ok(mask_b, key: int, off_val):
    """[B,T,O]: does mask_b admit each offering's single value at `key`
    (-1 == unconstrained)."""
    masks = mask_b[:, key, :]                                # [B, W]
    word = jnp.where(off_val >= 0, off_val // 32, 0)
    bit = jnp.where(off_val >= 0, off_val % 32, 0)
    w = masks[:, word]                                       # [B, T, O]
    has = (w >> bit[None, :, :].astype(jnp.uint32)) & jnp.uint32(1)
    return jnp.where(off_val[None, :, :] >= 0, has == 1, True)


class ArgPlacer:
    """Placement policy for device_args uploads. The default (None) commits
    every array to the default device and caches the catalog/exist sides
    under the plain slot names. A sharded placer (parallel/mesh._MeshPlacer)
    overrides the hooks: group-side arrays stay host numpy (a sharded AOT
    executable auto-places uncommitted inputs per its compiled shardings),
    the catalog side is padded + device_put with its NamedSharding once and
    cached under a device-identity-namespaced slot, and the exist side is
    replicated. One device_args serves both paths — the single kernel
    lineage the mesh regression postmortem demanded."""

    #: appended to device_cache slot names so differently-placed uploads of
    #: the same catalog never collide (a single-device-committed array is
    #: REJECTED by a sharded executable, and vice versa)
    cache_ns: tuple = ()

    def enc(self, e) -> feas.Enc:
        return feas.to_device(e)

    def i32(self, a):
        return jnp.asarray(np.clip(a, -INT32_MAX - 1,
                                   INT32_MAX).astype(np.int32))

    def array(self, a):
        return jnp.asarray(a)

    def put_it_side(self, it_side):
        """Final placement for the 7 catalog-side leaves (already through
        enc/i32/array). Sharded placers device_put each with its spec."""
        return it_side

    def put_exist_side(self, exist, exist_avail, p=None):
        """``p`` is the (padded) problem: sharded placers read its
        exist_shard_tokens to re-upload only dirty per-shard row blocks."""
        return exist, exist_avail

    def device_token(self) -> tuple:
        """Placement identity folded into the cached exist-upload's token:
        the content token (PackProblem.exist_token) says WHAT the rows are,
        this says WHERE they live. A mesh<->single-device flip in one
        process, or a default-device change, must never serve the other
        placement's arrays even if the node set is unchanged."""
        d = jax.devices()[0]
        return ("dev", jax.default_backend(), int(getattr(d, "id", 0)))

    def it_side_valid(self, p: "PackProblem", it_side) -> bool:
        """Guards the cached catalog upload against a differently-padded
        problem reusing the slot (a mesh-padded catalog must never serve a
        single-device solve, whose output layout is sized from the
        problem). Sharded placers key their slot by the padded size
        instead, so they skip this."""
        return tuple(it_side[1].shape) == p.it_alloc.shape


_DEFAULT_PLACER = ArgPlacer()


def device_args(p: PackProblem, placer: Optional[ArgPlacer] = None):
    """Build the positional-array / static-kwarg split for precompute_kernel."""
    from ..obs.tracer import TRACER
    with TRACER.span("device.upload"):
        return _device_args(p, placer or _DEFAULT_PLACER)


def _device_args(p: PackProblem, placer: ArgPlacer):
    has_exist = p.exist_enc is not None and p.exist_enc.mask.shape[0] > 0
    dev = placer.enc
    i32 = placer.i32
    arr = placer.array
    if has_exist:
        # tol_exist is group-dependent and uploads fresh every call; the
        # node-only (exist_enc, exist_avail) pair is cacheable per
        # exist_token (see PackProblem.exist_token)
        ex_key = ("exist_side",) + placer.cache_ns
        # the stored token pairs the CONTENT token with the placer's
        # placement identity: a mesh<->single-device flip in one process
        # reuses the same ProblemState (same exist_token) but must never be
        # served the other placement's arrays
        ex_tok = (p.exist_token, placer.device_token()) \
            if p.exist_token is not None else None
        ex_slot = (p.device_cache.get(ex_key)
                   if p.device_cache is not None and ex_tok is not None
                   else None)
        if ex_slot is not None and ex_slot[0] == ex_tok:
            exist, exist_avail = ex_slot[1]
        else:
            exist, exist_avail = placer.put_exist_side(
                dev(p.exist_enc), i32(p.exist_avail), p=p)
            if p.device_cache is not None and ex_tok is not None:
                p.device_cache[ex_key] = (ex_tok, (exist, exist_avail))
        tol_exist = arr(p.tol_exist)
    else:
        K, W = p.group_enc.mask.shape[1:]
        exist = feas.Enc(mask=np.zeros((1, K, W), np.uint32),
                         defined=np.zeros((1, K), bool),
                         complement=np.zeros((1, K), bool),
                         exempt=np.zeros((1, K), bool),
                         gt=np.zeros((1, K), np.int32),
                         lt=np.zeros((1, K), np.int32))
        exist = feas.Enc(*(arr(x) for x in exist))
        exist_avail = arr(np.zeros((1, p.group_req.shape[1]), np.int32))
        tol_exist = arr(np.zeros((p.group_req.shape[0], 1), bool))
    cache = p.device_cache
    it_key = ("it_side",) + placer.cache_ns
    it_side = cache.get(it_key) if cache is not None else None
    if it_side is not None and not placer.it_side_valid(p, it_side):
        it_side = None
    if it_side is None:
        it_side = placer.put_it_side(
            (dev(p.it_enc), i32(p.it_alloc), arr(p.off_zone),
             arr(p.off_captype), arr(p.off_available),
             arr(p.zone_values), arr(p.allow_undefined)))
        if cache is not None:
            cache[it_key] = it_side
    (it_enc_d, it_alloc_d, off_zone_d, off_captype_d, off_avail_d,
     zone_values_d, allow_undef_d) = it_side
    args = (dev(p.group_enc), dev(p.template_enc), it_enc_d,
            i32(p.group_req), i32(p.daemon_overhead),
            it_alloc_d, arr(p.template_its),
            off_zone_d, off_captype_d,
            off_avail_d, zone_values_d,
            allow_undef_d, arr(p.tol_template),
            exist, exist_avail, tol_exist)
    statics = dict(zone_key=p.zone_key, captype_key=p.captype_key,
                   has_exist=has_exist)
    return args, statics


def _output_layout(p: PackProblem, has_exist: bool):
    """(shape, storage-dtype, logical) per kernel output, matching
    precompute_kernel's return order."""
    G = p.group_req.shape[0]
    M = p.daemon_overhead.shape[0]
    T = p.it_alloc.shape[0]
    Z = p.zone_values.shape[0]
    N = p.exist_avail.shape[0] if has_exist else 1
    pack_dtype, Wz = zone_pack_layout(Z)
    return [
        ((M, G), np.uint8, "bool"),            # compat_tm
        ((G, M, T, Wz), pack_dtype, "raw"),    # it_okz_packed
        ((G, M, T), np.int16, "raw"),          # ppn
        ((G, M, Z), np.uint8, "bool"),         # zone_adm
        ((G, N), np.uint8, "bool"),            # exist_ok
        ((G, N), np.int32, "raw"),             # exist_cap
    ]


# Persistent compiled-executable cache for the precompute program, keyed on
# the padded shape bucket (every leaf's shape+dtype plus the static kwargs).
# jax.jit keeps its own per-function cache, but going through explicit AOT
# lower/compile makes the hit/miss behavior observable: successive disruption
# passes in the reconcile loop land on the same padded buckets (pow2 node
# axis, pow2 group axis in the snapshot path, bucketed mask domain) and must
# stop paying recompilation — solver_compile_cache_{hits,misses} proves it.
import threading as _threading
from collections import OrderedDict as _OrderedDict

_EXEC_CACHE: "_OrderedDict[tuple, object]" = _OrderedDict()
_EXEC_CACHE_MAX = 32
_EXEC_CACHE_LOCK = _threading.Lock()


def _exec_cache_key(args, statics) -> tuple:
    leaves = jax.tree_util.tree_leaves(args)
    return (tuple((tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves),
            tuple(sorted(statics.items())))


def _get_executable(args, statics, shard=None):
    """(compiled executable, cache_hit, cache_key) for the precompute
    program, through the ONE persistent executable cache. ``shard=None``
    compiles the single-device packed-output kernel; a sharded dispatch
    (parallel/mesh) passes ``shard=(key_prefix, in_shardings,
    out_shardings)`` and gets the raw 6-output kernel compiled under GSPMD
    — same kernel, same cache; the key_prefix carries the device identity +
    mesh grid + gather mode, NOT the Mesh object, so a recreated mesh over
    the same devices reuses the executable. The returned key is the
    device-time attribution identity (obs/device.py)."""
    from ..obs.tracer import TRACER
    key = _exec_cache_key(args, statics)
    if shard is not None:
        key = (shard[0], key)
    with _EXEC_CACHE_LOCK:
        exe = _EXEC_CACHE.get(key)
        if exe is not None:
            _EXEC_CACHE.move_to_end(key)
    if exe is not None:
        return exe, True, key
    with TRACER.span("compile"):
        if shard is None:
            exe = _precompute_packed.lower(*args, **statics).compile()
        else:
            _, in_sh, out_sh = shard
            exe = jax.jit(
                lambda *a: precompute_kernel(*a, **statics),
                in_shardings=in_sh,
                out_shardings=out_sh).lower(*args).compile()
    with _EXEC_CACHE_LOCK:
        if key not in _EXEC_CACHE and len(_EXEC_CACHE) >= _EXEC_CACHE_MAX:
            _EXEC_CACHE.popitem(last=False)
        _EXEC_CACHE[key] = exe
        _EXEC_CACHE.move_to_end(key)
    return exe, False, key


def _arg_devices(args):
    """Placement labels off the committed arg arrays (sharded uploads carry
    their NamedSharding device set); None when the args are host buffers
    the executable will auto-place."""
    for leaf in jax.tree_util.tree_leaves(args):
        devs = getattr(leaf, "devices", None)
        if callable(devs):
            try:
                ds = devs()
            except Exception:  # noqa: BLE001
                continue
            if ds:
                return sorted((str(d.id) for d in ds), key=_dev_sort)
    return None


def _dev_sort(label: str):
    return (0, int(label)) if label.isdigit() else (1, label)


def _shape_summary(args) -> str:
    leaves = jax.tree_util.tree_leaves(args)
    big = sorted(leaves, key=lambda a: -int(np.prod(a.shape) or 0))[:3]
    return ",".join("x".join(map(str, leaf.shape)) for leaf in big)


def _run_precompute(args, statics, shard=None):
    from ..metrics.registry import (SOLVER_COMPILE_CACHE_HITS,
                                    SOLVER_COMPILE_CACHE_MISSES)
    from ..obs.tracer import TRACER
    exe, hit, key = _get_executable(args, statics, shard)
    if hit:
        SOLVER_COMPILE_CACHE_HITS.inc()
    else:
        SOLVER_COMPILE_CACHE_MISSES.inc()
    if not TRACER.enabled:
        # tracing off: fully asynchronous dispatch, byte-identical to the
        # pre-attribution hot path (the fetch site absorbs device time)
        return exe(*args)
    # device-time attribution (ISSUE 12): split host dispatch overhead
    # from the accelerator's own completion truth. Blocking here is free —
    # every caller fetches the results immediately after this returns, so
    # the wait MOVES into the device.execute span rather than being added.
    from ..obs.device import DEVICE_TIME
    st = DEVICE_TIME.get(key)
    if st is None:
        # first dispatch of this executable: the arg-tree walks feeding
        # shapes/devices run ONCE here, never on the steady-state path
        st = DEVICE_TIME.register(key, exe, "mesh" if shard else "single",
                                  shapes=_shape_summary(args),
                                  devices=_arg_devices(args))
    t0 = time.perf_counter()
    with TRACER.span("device.dispatch", executable=st.label,
                     compile_cache="hit" if hit else "miss"):
        out = exe(*args)
    t1 = time.perf_counter()
    with TRACER.span("device.execute", executable=st.label):
        jax.block_until_ready(out)
    DEVICE_TIME.record(st, t1 - t0, time.perf_counter() - t1)
    return out


# -- injected device-loss verdicts (utils/chaos.DeviceKiller) ----------------
# A real device loss surfaces as an XLA runtime error mid-dispatch; chaos
# injects the same failure deterministically so the degradation ladder
# (parallel/mesh.resilient_precompute) can be driven in tests and sim runs.

_DEVICE_CHAOS = None


class DeviceLossError(Exception):
    """A device participating in this dispatch is gone (ICI link drop,
    preempted donor chip, injected kill verdict). Carries the lost
    device's id so the mesh ladder can feed its per-device breaker."""

    def __init__(self, device_id, detail: str = ""):
        super().__init__(f"device {device_id} lost"
                         + (f": {detail}" if detail else ""))
        self.device_id = device_id


def install_device_chaos(killer):
    """Install (or clear, with None) the seeded device-kill verdict source
    consulted before every device dispatch; returns the previous hook so
    callers can restore it."""
    global _DEVICE_CHAOS
    prev = _DEVICE_CHAOS
    _DEVICE_CHAOS = killer
    return prev


def check_devices(device_ids) -> None:
    """Raise DeviceLossError if the installed chaos verdict kills any of
    the devices about to participate in a dispatch. No-op (one global
    read) when no chaos is installed."""
    killer = _DEVICE_CHAOS
    if killer is not None:
        hit = killer.verdict(device_ids)
        if hit is not None:
            raise DeviceLossError(hit, "injected kill verdict")


def precompute(p: PackProblem) -> PackTensors:
    # deliberately NOT chaos-checked: the meshless precompute is the
    # host-path rung below the ladder (disruption snapshots, validation
    # probes run it too), and the host is the one device the ladder
    # assumes alive. Only resilient_precompute consults the kill verdict,
    # against the devices actually participating in a mesh dispatch.
    from ..obs.tracer import TRACER
    args, statics = device_args(p)
    # single packed fetch: per-array device_get pays a host<->device round
    # trip per tensor, and through a network tunnel (axon) the LATENCY of
    # those trips — not the bytes — dominates the fetch. Device execution is
    # async-dispatched, so the fetch span carries the kernel's compute time.
    with TRACER.span("device.fetch"):
        flat = np.asarray(_run_precompute(args, statics))
    compat_tm, it_okz_packed, ppn, zone_adm, exist_ok, exist_cap = \
        _split_packed(flat, _output_layout(p, statics["has_exist"]))
    return unpack_tensors(compat_tm, it_okz_packed, ppn, zone_adm,
                          exist_ok, exist_cap, p.zone_values.shape[0])


def _exist_delta_kernel(group, group_req, exist, exist_avail, tol_exist,
                        allow_undefined):
    # EXACTLY the has_exist branch of precompute_kernel, lifted out so a
    # node-churn pass can refresh the [G, N] exist tensors without paying
    # the catalog axis. Same ops in the same order on the same dtypes:
    # the outputs are bit-identical to the fused kernel's.
    exist_compat = feas.compatible_matrix(exist, group,
                                          jnp.zeros_like(allow_undefined))
    exist_ok = exist_compat.T & tol_exist                    # [G, N]
    per = jnp.where(group_req[:, None, :] > 0,
                    exist_avail[None, :, :]
                    // jnp.maximum(group_req[:, None, :], 1),
                    jnp.int32(INT32_MAX))
    exist_cap = jnp.clip(jnp.min(per, axis=-1), 0,
                         INT32_MAX).astype(jnp.int32)
    exist_ok = exist_ok & (exist_cap >= 1)
    return exist_ok, exist_cap


_exist_delta_jit = jax.jit(_exist_delta_kernel)


def exist_delta(p: PackProblem) -> "Tuple[np.ndarray, np.ndarray]":
    """(exist_ok, exist_cap) for this problem, computed by the exist-only
    slice of the precompute. The sharded ProblemState's tensors memo calls
    this when ONLY the existing-node side changed since the memoized
    precompute: the group/catalog outputs are content-equal by token, and
    this refresh costs O(G*N) instead of the full O(G*M*T*Z) kernel."""
    from ..obs.tracer import TRACER
    clip = lambda a: np.clip(a, -INT32_MAX - 1,
                             INT32_MAX).astype(np.int32)
    with TRACER.span("device.exist_delta",
                     nodes=int(p.exist_avail.shape[0])):
        out = _exist_delta_jit(
            feas.to_device(p.group_enc), clip(p.group_req),
            feas.to_device(p.exist_enc), clip(p.exist_avail),
            np.asarray(p.tol_exist), np.asarray(p.allow_undefined))
        exist_ok, exist_cap = jax.device_get(out)
    return exist_ok, exist_cap


def unpack_tensors(compat_tm, it_okz_packed, ppn, zone_adm, exist_ok,
                   exist_cap, Z: int) -> PackTensors:
    """Expand the packed zone bitfield [G,M,T,Wz] back into the packer's bool
    views."""
    word_bits = np.iinfo(it_okz_packed.dtype).bits
    bits = (it_okz_packed[..., None] >> np.arange(word_bits).astype(
        it_okz_packed.dtype)) & 1                      # [G,M,T,Wz,word_bits]
    shape = it_okz_packed.shape[:3] + (-1,)
    it_ok_z = bits.astype(bool).reshape(shape)[..., :Z]
    return PackTensors(compat_tm=compat_tm,
                       it_ok=np.any(it_okz_packed != 0, axis=-1),
                       ppn=ppn.astype(np.int32), it_ok_z=it_ok_z,
                       zone_adm=zone_adm, exist_ok=exist_ok,
                       exist_cap=exist_cap)


# --------------------------------------------------------------------------
# host greedy over groups
# --------------------------------------------------------------------------

class CohortSet:
    """Columnar store of in-flight cohorts (a cohort = n identical planned
    nodes: same template, zone restriction, cumulative requests, surviving
    instance-type set). Round 5's per-object ``Cohort`` list forced the
    group packer into a Python ``for cohort in cohorts`` scan per group —
    re-running the requirement-compat, zone-commit and capacity math one
    cohort at a time — which cost the sub-second flagship Solve()
    (BENCH_r05 1.197 s vs r4 0.499 s). Stacking every per-cohort quantity
    row-wise lets ``Packer._fill_cohorts`` evaluate ALL candidate cohorts
    for a group in a handful of vectorized passes with identical placement
    semantics (the parity fuzzer pins them).

    Incremental aggregates maintained per row, AND-folded as groups board
    (order-independent, so equal to the scan the old code re-ran per probe):

    - ``zadm[c, z]``  — every aboard group admits zone z
      (``zone_adm[gp, m, z]`` reduced over the aboard set);
    - ``okz[c, t, w]`` — bitpacked (encode.pack_bits layout) zone-
      feasibility intersection ``AND_gp it_ok_z[gp, m, t, :]``, the
      prospective zone-commit mask of the round-5 fix;
    - ``aboard[c, g]`` — the aboard-group bitset (host-port conflict gate);
    - ``enc_*``       — the accumulated requirement row, stacked so
      requirement compatibility is one batched mask reduction.
    """

    _ROW_FIELDS = ("m", "zone", "n", "fill", "it_set", "requests", "aboard",
                   "zadm", "okz", "enc_mask", "enc_defined", "enc_complement",
                   "enc_exempt", "enc_gt", "enc_lt")

    def __init__(self, p: PackProblem, t: PackTensors, G: int, cap: int = 64):
        self.T = p.it_alloc.shape[0]
        self.R = p.group_req.shape[1]
        self.Z = p.zone_values.shape[0]
        K, W = p.group_enc.mask.shape[1:]
        self.C = 0
        self._cap = cap
        self._t = t
        self.m = np.zeros(cap, np.int32)
        self.zone = np.full(cap, -1, np.int32)          # -1 == zone-free
        self.n = np.zeros(cap, np.int64)
        self.fill = np.zeros(cap, np.int64)             # pods per node
        self.it_set = np.zeros((cap, self.T), bool)
        self.requests = np.zeros((cap, self.R), np.int64)
        self.aboard = np.zeros((cap, G), bool)
        self.zadm = np.zeros((cap, self.Z), bool)
        self.okz = np.zeros((cap, self.T, (self.Z + 7) // 8), np.uint8)
        self.enc_mask = np.zeros((cap, K, W), np.uint32)
        self.enc_defined = np.zeros((cap, K), bool)
        self.enc_complement = np.zeros((cap, K), bool)
        self.enc_exempt = np.zeros((cap, K), bool)
        self.enc_gt = np.zeros((cap, K), np.int64)
        self.enc_lt = np.zeros((cap, K), np.int64)
        self.pods_by_group: List[Dict[int, int]] = []   # per-node fill
        self._okz_rows: Dict[tuple, np.ndarray] = {}

    def _grow(self) -> None:
        self._cap *= 2
        for name in self._ROW_FIELDS:
            a = getattr(self, name)
            out = np.zeros((self._cap,) + a.shape[1:], a.dtype)
            out[:self.C] = a[:self.C]
            setattr(self, name, out)

    def _okz_row(self, g: int, m: int) -> np.ndarray:
        """[T, ceil(Z/8)] bitpacked ``it_ok_z[g, m]`` (memoized: boarding
        the same group repeatedly must not re-pack)."""
        key = (g, m)
        row = self._okz_rows.get(key)
        if row is None:
            row = enc.pack_bits(self._t.it_ok_z[g, m])
            self._okz_rows[key] = row
        return row

    def append(self, g: int, m: int, zone: Optional[int], it_set: np.ndarray,
               requests: np.ndarray, n: int, enc_row: EncodedRequirements,
               fill: int) -> int:
        ci = self.C
        if ci == self._cap:
            self._grow()
        self.m[ci] = m
        self.zone[ci] = -1 if zone is None else zone
        self.n[ci] = n
        self.fill[ci] = fill
        self.it_set[ci] = it_set
        self.requests[ci] = requests
        self.aboard[ci] = False
        self.aboard[ci, g] = True
        self.zadm[ci] = self._t.zone_adm[g, m]
        self.okz[ci] = self._okz_row(g, m)
        self.set_enc(ci, enc_row)
        self.pods_by_group.append({g: fill})
        self.C += 1
        return ci

    def split(self, ci: int, n_new: int) -> int:
        """Copy row ci into a fresh row with node count ``n_new`` (the
        caller shrinks ci's own count): remainder/last-node cohorts inherit
        every aggregate, exactly like the old object copy did."""
        cj = self.C
        if cj == self._cap:
            self._grow()
        for name in self._ROW_FIELDS:
            a = getattr(self, name)
            a[cj] = a[ci]
        self.n[cj] = n_new
        self.pods_by_group.append(dict(self.pods_by_group[ci]))
        self.C += 1
        return cj

    def append_row_from(self, other: "CohortSet", ci: int) -> int:
        """Copy row ``ci`` of ``other`` (built over the same problem,
        tensors and group count) into this set: the sharded pack's merge
        step. Row aggregates copy verbatim — they are order-independent
        AND-folds, so a merged set scans exactly like one that boarded the
        same groups sequentially."""
        cj = self.C
        if cj == self._cap:
            self._grow()
        for name in self._ROW_FIELDS:
            getattr(self, name)[cj] = getattr(other, name)[ci]
        self.pods_by_group.append(dict(other.pods_by_group[ci]))
        self.C += 1
        return cj

    def enc_row(self, ci: int) -> EncodedRequirements:
        """Row VIEWS — callers combine them into fresh arrays (np_combine
        never mutates) and write back via set_enc."""
        return EncodedRequirements(
            mask=self.enc_mask[ci], defined=self.enc_defined[ci],
            complement=self.enc_complement[ci], exempt=self.enc_exempt[ci],
            gt=self.enc_gt[ci], lt=self.enc_lt[ci])

    def set_enc(self, ci: int, e: EncodedRequirements) -> None:
        self.enc_mask[ci] = e.mask
        self.enc_defined[ci] = e.defined
        self.enc_complement[ci] = e.complement
        self.enc_exempt[ci] = e.exempt
        self.enc_gt[ci] = e.gt
        self.enc_lt[ci] = e.lt

    def compatible_rows(self, b: EncodedRequirements,
                        allow_undefined: np.ndarray) -> np.ndarray:
        """[C] bool: np_compatible(row, b) for every cohort row at once —
        the batched twin of the old per-cohort scan check."""
        C = self.C
        gt = np.maximum(self.enc_gt[:C], b.gt)
        lt = np.minimum(self.enc_lt[:C], b.lt)
        crossed = (gt > -2**31) & (lt < 2**31 - 1) & (gt >= lt)
        nonempty = np.any(self.enc_mask[:C] & b.mask, axis=-1) & ~crossed
        checked = self.enc_defined[:C] & b.defined
        exempt = self.enc_exempt[:C] & b.exempt
        bad = checked & ~nonempty & ~exempt
        undef_bad = (b.defined & ~self.enc_defined[:C]
                     & ~allow_undefined & ~b.exempt)
        return ~np.any(bad | undef_bad, axis=-1)


# cap on checkpoints retained in a PackSeed: each holds full copies of the
# cohort arrays + exist_avail, and restored seeds carry their usable prefix
# forward every pass — without a bound a long-lived provisioner would
# accumulate them without limit
MAX_SEED_CHECKPOINTS = 12


@dataclass
class PackCheckpoint:
    """Complete mutable packer state after the first ``pos`` groups of the
    FFD order were packed: the warm-start restore point. Group references
    inside (aboard columns, pods_by_group keys, existing fills, error-log
    rows, g_of_pos) are group INDICES of the pack that recorded it;
    _remap_checkpoint translates them into the next pass's index space."""
    pos: int
    C: int
    rows: dict                      # CohortSet field name -> array copy [:C]
    pods_by_group: list
    existing: dict                  # node idx -> [(g, fill), ...]
    error_log: list                 # [(g, tail_count, msg), ...] in order
    exist_avail: np.ndarray
    limits: list                    # template_limits deep copy
    limit_constrained: bool
    g_of_pos: list                  # group index packed at FFD position p


@dataclass
class PackSeed:
    """One pack's replayable skeleton, stored by the ProblemState across
    passes. Valid for a later pack exactly when that pack's global token
    matches AND a prefix of its FFD-ordered per-group tokens matches —
    the packer is sequentially deterministic over the FFD order, so equal
    inputs up to position P imply byte-equal state at P."""
    global_token: tuple
    ffd_tokens: list                # per-FFD-position (sig, token)
    checkpoints: list               # PackCheckpoints, ascending pos


@dataclass
class WarmStart:
    """Per-solve warm-start context built by the ProblemState: the global
    input token (everything the packer reads that is not per-group), the
    per-group tokens indexed by current group index, and the previous
    pass's seed. After pack() the packer leaves the new seed in
    ``result_seed`` and its restore stats in restored_pos/matched."""
    global_token: tuple
    tokens: list
    seed: Optional[PackSeed] = None
    result_seed: Optional[PackSeed] = None
    restored_pos: int = 0
    matched: int = 0
    # sharded hierarchical pack composition (parallel/mesh.sharded_pack):
    # one PackSeed per round-robin FFD block. Each shard's Packer runs the
    # SAME warm machinery over its block order (the seed's ffd_tokens are
    # that block's per-group tokens), so a shard whose groups kept their
    # tokens AND their block replays its whole pack; a group that moved
    # shards breaks both affected blocks' prefixes from its position on.
    shard_seeds: Optional[list] = None
    result_shard_seeds: Optional[list] = None
    # cross-shard reconcile fold memo (mesh._reconcile), carried across
    # passes by the ProblemState; replaced in place when the fold re-runs
    reconcile_memo: Optional[dict] = None


@dataclass
class PackResult:
    # (template m, zone idx or None, it_set bool [T], [pod,...]) per new node
    nodes: List[tuple] = field(default_factory=list)
    existing: Dict[int, list] = field(default_factory=dict)  # node idx -> pods
    errors: Dict[str, str] = field(default_factory=dict)     # pod uid -> error
    cohorts: Optional[CohortSet] = None
    # a nodepool limit excluded capacity during this pack: WHO gets the
    # scarce budget is order-dependent, so pack errors under limit pressure
    # are not oracle-final (the production scheduler re-solves on the host
    # path instead of trusting them; see TensorScheduler._solve)
    limit_constrained: bool = False


# -- donor-row headroom policy (sharded hierarchical pack) --------------------

# the old fixed bar, kept as the ceiling for dense many-node groups
DONOR_HEADROOM_DENSE = 0.25
DONOR_HEADROOM_MEDIUM = 0.15
DONOR_HEADROOM_SMALL = 0.05


def donor_headroom(group_count: int, shards: int) -> float:
    """Group-size-aware donor bar for the sharded pack's cross-shard
    reconcile (retires the fixed 0.25, ROADMAP item 3): a single-node row
    donates its pods to the merge mini-pack when its best surviving
    instance type still has this much relative headroom over the
    accumulated requests.

    A group of ``group_count`` pods round-robined over ``shards`` blocks
    leaves ~count/shards pods per shard — SMALL groups fragment into
    per-shard tails that are each a large fraction of the whole group, so
    coalescing them wins whole nodes and they donate at a low bar; HUGE
    groups produce dense rows whose tail is one node in hundreds, so only
    a clearly underfilled row is worth the re-pack. Deterministic pure
    function of (group size, shard count): the sharded pack stays
    seed-free and the policy is pinned by a directed vector
    (tests/test_parallel_mesh.py)."""
    if shards <= 1 or group_count <= 0:
        return DONOR_HEADROOM_DENSE
    frag = group_count / shards
    if frag <= 16:
        return DONOR_HEADROOM_SMALL
    if frag <= 128:
        return DONOR_HEADROOM_MEDIUM
    return DONOR_HEADROOM_DENSE


def waterfill(counts: np.ndarray, viable: np.ndarray, admitted: np.ndarray,
              c: int, max_skew: int,
              min_domains: Optional[int] = None,
              zone_names: Optional[np.ndarray] = None,
              min_mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Distribute c pods over zones the way the reference's min-count domain
    selection does (topologygroup.go:181-227): each pod goes to the lowest-count
    admitted+viable zone subject to count+1-min <= maxSkew. The global min is
    taken over `min_mask` — the POD's view of the domain universe
    (topologygroup.go:229-250), which can include zones no template reaches
    (e.g. a cluster pod in a zone the pool excludes pins the min there) —
    defaulting to `admitted`. With minDomains set and fewer min_mask domains
    than it, the global min floors to zero (topologygroup.go:240-247), so the
    skew check binds against absolute counts. Returns per-zone allocation
    (pods that can't place anywhere are simply not allocated; caller errors
    them)."""
    counts = counts.astype(np.int64).copy()
    alloc = np.zeros_like(counts)
    remaining = c
    if min_mask is None:
        min_mask = admitted
    floor_zero = (min_domains is not None
                  and int(min_mask.sum()) < min_domains)
    # fast path: every admitted zone viable AND the pod's min universe is
    # exactly the placement set -> sequential min-fill equals a closed-form
    # water-fill (skew never binds when always filling the min; invalid
    # under the minDomains zero floor or when an unreachable domain pins
    # the global min below the fill level)
    if not floor_zero and admitted.any() and (viable | ~admitted).all() \
            and bool((min_mask == admitted).all()):
        idx = np.where(admitted)[0]
        cz = counts[idx]
        # largest level L with sum(max(0, L - cz)) <= remaining
        lo, hi = int(cz.min()), int(cz.max()) + remaining
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if int(np.maximum(0, mid - cz).sum()) <= remaining:
                lo = mid
            else:
                hi = mid - 1
        add = np.maximum(0, lo - cz)
        rem = remaining - int(add.sum())
        at_level = np.where(cz + add == lo)[0]  # lex order == index order
        for pos in at_level[:rem]:
            add[pos] += 1
        alloc[idx] = add
        return alloc
    while remaining > 0:
        if floor_zero:
            m0 = 0
        else:
            m0 = counts[min_mask].min() if min_mask.any() else 0
        eligible = viable & admitted & (counts + 1 - m0 <= max_skew)
        if not eligible.any():
            break
        cand = np.where(eligible)[0]
        # min count, ties by domain NAME — the host oracle's deterministic
        # tie-break (_next_domain_spread iterates sorted(candidates))
        tie = zone_names[cand] if zone_names is not None else cand
        zi = cand[np.lexsort((tie, counts[cand]))[0]]
        alloc[zi] += 1
        counts[zi] += 1
        remaining -= 1
    return alloc


class Packer:
    """Greedy group packer consuming PackTensors."""

    def __init__(self, p: PackProblem, t: PackTensors, groups,
                 template_limits: List[Optional[dict]],
                 limit_resources: List[str],
                 initial_zone_counts: Optional[np.ndarray] = None,
                 exist_order: Optional[List[int]] = None,
                 exist_counts: Optional[np.ndarray] = None,
                 host_match_total: Optional[np.ndarray] = None,
                 vol_group_counts: Optional[list] = None,
                 vol_node_remaining: Optional[list] = None,
                 group_ports: Optional[list] = None,
                 exist_port_block: Optional[np.ndarray] = None,
                 warm: Optional[WarmStart] = None):
        self.p = p
        self.t = t
        self.groups = groups
        self.G = len(groups)
        self.Z = len(p.zone_values)
        self.T = p.it_alloc.shape[0]
        self.M = p.daemon_overhead.shape[0]
        self.template_limits = template_limits  # remaining ResourceList (scaled) or None
        self.limit_resources = limit_resources
        self.zone_counts = (initial_zone_counts.copy() if initial_zone_counts is not None
                            else np.zeros((self.G, self.Z), dtype=np.int64))
        self.exist_order = exist_order if exist_order is not None else (
            list(range(p.exist_avail.shape[0])) if p.exist_avail is not None else [])
        self.exist_avail = (p.exist_avail.copy() if p.exist_avail is not None
                            else np.zeros((0, p.group_req.shape[1]), dtype=np.int64))
        # scheduled cluster pods matching each group's hostname-level
        # selector, per packable existing node [G, N] and in total [G] (the
        # countDomains analog for hostname topologies, topology.go:268-321)
        self.exist_counts = exist_counts
        self.host_match_total = host_match_total
        # CSI attach limits for per-pod (ephemeral) claims, linearized
        # (volumeusage.go:201-208): vol_group_counts[g] = {driver: claims
        # per pod} or None; vol_node_remaining[n] = {driver: remaining
        # slots} for limited drivers only, or None for unlimited nodes.
        # Shared MUTABLE per-node dicts: every group placing on a node
        # draws down the same driver budget.
        self.vol_group_counts = vol_group_counts
        self.vol_node_remaining = vol_node_remaining
        # host-port semantics, tensorized (hostportusage.go:34-90):
        # group_ports[g] = (ip, port, protocol) triples or (); identical
        # specs mean any two pods of a port group conflict -> one pod per
        # node; a precomputed GxG matrix gates cross-group co-location and
        # exist_port_block[G, N] excludes nodes already using the ports
        self.group_ports = group_ports
        self.exist_port_block = exist_port_block
        if group_ports is not None and any(group_ports):
            from ..scheduling.hostports import triples_conflict
            pg = [g for g in range(self.G) if group_ports[g]]
            self._port_conflict = np.zeros((self.G, self.G), dtype=bool)
            for i, gi in enumerate(pg):
                for gj in pg[i:]:
                    if triples_conflict(group_ports[gi], group_ports[gj]):
                        self._port_conflict[gi, gj] = True
                        self._port_conflict[gj, gi] = True
        else:
            self._port_conflict = None
        # domain-name tie-break order for zone selection (host parity)
        self._zone_names = np.array(p.vocab.values[p.zone_key], dtype=object)
        self.result = PackResult()
        self.cohorts = CohortSet(p, t, self.G)
        # per-group nonzero request columns + request-restricted catalog
        # slices, so the per-probe capacity math touches only the resources
        # the group actually requests (hot path: _cohort_caps)
        self._req_nz = [np.nonzero(p.group_req[g])[0] for g in range(self.G)]
        self._req_vals = [p.group_req[g][self._req_nz[g]] for g in range(self.G)]
        # a group whose requirement row defines NO key is compatible with
        # every accumulated cohort requirement set (np_compatible's bad /
        # undef_bad terms both need b.defined) — the common case in large
        # batches, so the whole batched compat pass is skipped for it
        self._g_trivial = ~p.group_enc.defined.any(axis=1)
        # minValues floor on distinct instance types per (template, group):
        # every fill is capped so at least this many types survive the claim
        # (the host oracle refuses per-pod adds that would drop below it,
        # scheduler.py:159-162) — zero-cost when no floor is set
        self._min_its = p.min_its
        self._has_min_its = (p.min_its is not None
                             and bool((p.min_its > 0).any()))
        # warm-start context (ProblemState): restore the previous pass's
        # packer state at the longest clean FFD prefix and re-pack only the
        # suffix. The machinery is disabled (full pack) for any shape whose
        # shared mutable state is not checkpointed: host-port groups,
        # volume attach budgets, and minValues floors — the invalidation
        # matrix rows that conservatively fall back to a full pack.
        self._warm = warm
        self._error_log: List[tuple] = []
        self._alloc_nz_cache: Dict[int, np.ndarray] = {}
        self._adj_nz_cache: Dict[tuple, np.ndarray] = {}
        self._madj_cache: Dict[int, np.ndarray] = {}
        self._dfits_cache: Dict[int, np.ndarray] = {}
        self._gz_grid_cache: Dict[int, np.ndarray] = {}
        self._node_enc_cache: Dict[tuple, EncodedRequirements] = {}
        self._zone_enc_cache: Dict[int, EncodedRequirements] = {}

    def _it_alloc_nz(self, g: int) -> np.ndarray:
        """[T, nnz(g)] raw allocatable restricted to group g's requested
        resources (daemon overhead enters per candidate template in
        _cohort_caps)."""
        out = self._alloc_nz_cache.get(g)
        if out is None:
            out = self.p.it_alloc[:, self._req_nz[g]]
            self._alloc_nz_cache[g] = out
        return out

    def _gz_grid(self, g: int) -> np.ndarray:
        """[M, T, Z+1] group-side feasibility with the any-zone plane
        appended at index Z, so mixed zone-committed / zone-free candidate
        cohorts gather their per-IT admission in ONE fancy index."""
        grid = self._gz_grid_cache.get(g)
        if grid is None:
            grid = np.concatenate(
                [self.t.it_ok_z[g], self.t.it_ok[g][:, :, None]], axis=2)
            self._gz_grid_cache[g] = grid
        return grid

    # -- helpers ------------------------------------------------------------

    def _viable_templates(self, g: int) -> List[int]:
        return [m for m in range(self.M) if self.t.it_ok[g, m].any()]

    def _open_nodes(self, g: int, m: int, zone: Optional[int], n_pods: int,
                    per_node: int) -> int:
        """Open as many nodes as limits allow for n_pods; returns pods placed."""
        if per_node <= 0:
            return 0
        it_ok = (self.t.it_ok_z[g, m, :, zone] if zone is not None
                 else self.t.it_ok[g, m])
        it_set = it_ok & (self.t.ppn[g, m] >= 1)
        if not it_set.any():
            return 0
        limits = self.template_limits[m]
        cohort_enc = self._node_enc(g, m, zone)
        if limits is None:
            full_nodes, rem = divmod(n_pods, per_node)
            placed = 0
            if full_nodes and self._append_cohort(g, m, zone, it_set, per_node,
                                                  cohort_enc, n=full_nodes):
                placed += full_nodes * per_node
            if rem and self._append_cohort(g, m, zone, it_set, rem,
                                           cohort_enc, n=1):
                placed += rem
            return placed
        placed = 0
        while placed < n_pods:
            it_fit = it_set & self._under_limits(m, it_set)
            if not it_fit.any():
                self.result.limit_constrained = True
                break
            # size the fill from the LIMIT-FILTERED set: per_node came from
            # the unfiltered max-capacity type, which limits may have
            # excluded — overfilling would prune the cohort's options empty
            per_fit = min(per_node,
                          self._fill_ceiling(g, m, self.t.ppn[g, m], it_fit))
            if per_fit <= 0:
                break
            fill = min(per_fit, n_pods - placed)
            # append BEFORE consuming limits: a fill-sizing failure must not
            # leak a phantom node's worth of limit capacity (subtractMax
            # models only nodes that actually open, scheduler.go:388-405)
            if not self._append_cohort(g, m, zone, it_fit, fill, cohort_enc,
                                       n=1):
                break
            self._subtract_max(m, it_fit)
            placed += fill
        return placed

    def _under_limits(self, m: int, it_set: np.ndarray) -> np.ndarray:
        limits = self.template_limits[m]
        ok = np.ones(self.T, dtype=bool)
        for rname in self.limit_resources:
            if rname not in limits:
                continue  # this pool doesn't limit rname (limits.ExceededBy)
            ridx = self.p.vocab.resource_idx.get(rname)
            if ridx is None:
                continue
            ok &= self.p.it_capacity[:, ridx] <= limits[rname]
        return ok

    def _subtract_max(self, m: int, it_set: np.ndarray) -> None:
        """subtractMax pessimism (scheduler.go:388-405)."""
        limits = self.template_limits[m]
        for rname in list(limits):
            ridx = self.p.vocab.resource_idx.get(rname)
            if ridx is None:
                continue
            limits[rname] = limits[rname] - int(self.p.it_capacity[it_set, ridx].max())

    def _node_enc(self, g: int, m: int, zone: Optional[int]) -> EncodedRequirements:
        """Fresh-cohort requirement row; memoized (pure in (g, m, zone), and
        append copies it into the cohort store so sharing is safe)."""
        key = (g, m, zone)
        e = self._node_enc_cache.get(key)
        if e is None:
            e = np_combine(_row(self.p.template_enc, m), _row(self.p.group_enc, g))
            if zone is not None:
                e = np_combine(e, self._zone_enc(zone))
            self._node_enc_cache[key] = e
        return e

    def _zone_enc(self, zone: int) -> EncodedRequirements:
        e = self._zone_enc_cache.get(zone)
        if e is None:
            e = self._build_zone_enc(zone)
            self._zone_enc_cache[zone] = e
        return e

    def _build_zone_enc(self, zone: int) -> EncodedRequirements:
        K, W = self.p.group_enc.mask.shape[1:]
        mask = np.full((K, W), 0xFFFFFFFF, dtype=np.uint32)
        defined = np.zeros(K, dtype=bool)
        complement = np.ones(K, dtype=bool)
        exempt = np.zeros(K, dtype=bool)
        zk = self.p.zone_key
        row = np.zeros(W, dtype=np.uint32)
        vi = int(self.p.zone_values[zone])
        row[vi // 32] |= np.uint32(1 << (vi % 32))
        mask[zk] = row
        defined[zk] = True
        complement[zk] = False
        return EncodedRequirements(mask=mask, defined=defined, complement=complement,
                                   exempt=exempt,
                                   gt=np.full(K, -2**31, dtype=np.int64),
                                   lt=np.full(K, 2**31 - 1, dtype=np.int64))

    def _adjusted_alloc(self, m: int) -> np.ndarray:
        """[T, R] allocatable minus template m's daemon overhead, memoized
        (pure function of m; _commit_to_cohort sits on the remainder hot
        path)."""
        out = self._madj_cache.get(m)
        if out is None:
            out = self.p.it_alloc - self.p.daemon_overhead[m]
            self._madj_cache[m] = out
        return out

    def _fill_ceiling(self, g: int, m: int, vals: np.ndarray,
                      mask: np.ndarray) -> int:
        """Max per-node fill of group g on a fresh template-m node honoring
        the minValues floor: the k-th largest masked per-IT capacity (plain
        max when no floor — k ITs hold >= fill pods iff fill <= k-th
        largest). Callers guarantee mask.any()."""
        sel = vals[mask]
        k = int(self._min_its[m, g]) if self._has_min_its else 0
        if k <= 1:
            return int(sel.max())
        if sel.size < k:
            return 0
        return int(np.partition(sel, sel.size - k)[sel.size - k])

    def _daemon_fits(self, m: int) -> np.ndarray:
        """[T] bool: daemon-adjusted allocatable is nonnegative in EVERY
        resource — the request-independent part of _fits_requests, memoized
        so the hot fit check only touches the requested columns."""
        out = self._dfits_cache.get(m)
        if out is None:
            out = (self._adjusted_alloc(m) >= 0).all(axis=1)
            self._dfits_cache[m] = out
        return out

    def _adj_nz(self, m: int, nz: np.ndarray) -> np.ndarray:
        """[T, len(nz)] daemon-adjusted allocatable restricted to columns
        nz, memoized per (template, column-set)."""
        key = (m, nz.tobytes())
        out = self._adj_nz_cache.get(key)
        if out is None:
            out = self._adjusted_alloc(m)[:, nz]
            self._adj_nz_cache[key] = out
        return out

    def _fits_requests(self, m: int, requests: np.ndarray) -> np.ndarray:
        """[T] bool: instance types whose daemon-adjusted allocatable holds
        the cumulative request vector — the tensor twin of the per-pod
        instance-type refiltering (nodeclaim.go:108-117): an IT that fit the
        first pod must leave the set once the accumulated load outgrows it,
        or downstream consumers (price ordering, the consolidation price
        filter, the provider's cheapest-offering pick) see phantom options.
        Split as (all columns >= 0) AND (requested columns hold the load):
        equal to the full [T, R] compare because requests are nonnegative,
        at a fraction of the width."""
        nz = np.nonzero(requests)[0]
        fit = self._daemon_fits(m)
        if nz.size:
            fit = fit & (self._adj_nz(m, nz) >= requests[nz]).all(axis=1)
        return fit

    def _append_cohort(self, g: int, m: int, zone: Optional[int],
                       it_set: np.ndarray, fill: int,
                       cohort_enc: EncodedRequirements, n: int = 1) -> bool:
        """Returns False (placing nothing) when the fill-sizing invariant is
        violated — the fill outgrew every surviving instance type. Callers
        treat that as 0 pods placed, so the group's remainder flows to the
        normal unplaced-pods error path instead of an assert crashing the
        whole batch (and `python -O` silently materializing an empty
        it_set)."""
        req = self.p.group_req[g] * fill
        it_set = it_set & self._fits_requests(m, req)
        if not it_set.any():
            return False
        if self._has_min_its:
            k = int(self._min_its[m, g])
            if k > 1 and int(it_set.sum()) < k:
                return False  # fresh claim can't keep the minValues floor
        self.cohorts.append(g=g, m=m, zone=zone, it_set=it_set, requests=req,
                            n=n, enc_row=cohort_enc, fill=fill)
        return True

    def _cohort_caps(self, g: int, cand: np.ndarray, zone: Optional[int],
                     prospect: Optional[np.ndarray]
                     ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        """Batched cohort capacity: (caps [nc], surviving it-set ts [nc, T],
        per-IT capacities per [nc, T] or None when g requests nothing) for
        EVERY candidate row in ``cand`` at once (the round-5 code re-derived
        this per cohort in Python). Negative free capacity floors the per-IT
        min below zero, which the caller's cap<=0 gate treats identically to
        the old clamp-to-zero; rows whose surviving set is empty report cap
        0. ``prospect`` rows evaluate a PROSPECTIVE zone commitment of a
        zone-free cohort (see _fill_cohorts) without mutating it: their
        admission additionally intersects the cohort's accumulated
        aboard-group zone-feasibility bitfield (CohortSet.okz). ``per`` rows
        let commits derive the post-commit instance-type set as
        ``ts & (per >= fill)`` — exactly the _fits_requests refiltering,
        because ts only holds types that fit the PRE-commit load."""
        cs = self.cohorts
        m_c = cs.m[cand]
        grid = self._gz_grid(g)                             # [M, T, Z+1]
        if zone is not None:
            ez = np.full(cand.size, zone, np.int64)
        else:
            cz = cs.zone[cand]
            ez = np.where(cz < 0, self.Z, cz)               # Z == any-zone
        ts = cs.it_set[cand] & grid[m_c, :, ez]             # [nc, T]
        if prospect is not None:
            pm = prospect[cand]
            if pm.any():
                ts[pm] = ts[pm] & enc.bit_column(cs.okz[cand[pm]], zone)
        any_ts = ts.any(axis=1)
        k_c = self._min_its[m_c, g] if self._has_min_its else None
        nz = self._req_nz[g]
        if nz.size == 0:
            ok = (any_ts if k_c is None
                  else ts.sum(axis=1) >= np.maximum(k_c, 1))
            return np.where(ok, np.int64(INT32_MAX), np.int64(0)), ts, None
        need = (self.p.daemon_overhead[m_c][:, nz]
                + cs.requests[cand][:, nz])                 # [nc, nnz]
        alloc = self._it_alloc_nz(g)
        rv = self._req_vals[g]
        # per-resource [nc, T] floordivs + running min: same arithmetic as
        # the 3-D broadcast, without materializing the [nc, T, nnz] temp
        per = (alloc[None, :, 0] - need[:, 0:1]) // rv[0]
        for r in range(1, nz.size):
            per = np.minimum(per, (alloc[None, :, r] - need[:, r:r + 1])
                             // rv[r])
        masked = np.where(ts, per, np.iinfo(np.int64).min)
        caps = masked.max(axis=1)
        if k_c is not None and (k_c > 1).any():
            # minValues floor: cap at the k-th largest surviving capacity so
            # >= k instance types outlive the commit's it_set refiltering
            count = ts.sum(axis=1)
            T = masked.shape[1]
            for j in np.nonzero(k_c > 1)[0]:
                k = int(k_c[j])
                caps[j] = (np.partition(masked[j], T - k)[T - k]
                           if count[j] >= k else 0)
        return np.where(any_ts, caps, 0), ts, per

    def _fill_cohorts(self, g: int, remaining: int, zone: Optional[int],
                      per_node_cap: int) -> int:
        """Mix pods of g into compatible existing cohorts (the reference's
        fewest-pods-first in-flight node pass, scheduler.go:276-283).

        One vectorized eligibility pass over the whole cohort matrix —
        zone admission (incl. the prospective zone-commit gate via the
        incrementally AND-folded zadm/okz aggregates), template compat +
        toleration, accumulated-requirement compatibility, host-port
        exclusion — then capacities in geometrically growing fill-order
        chunks so the common few-cohorts fill never pays for the full
        matrix while an exhausting scan stays one batched pass. Placement
        semantics are unchanged: eligibility and capacity of a cohort are
        independent of commits to OTHER cohorts within one call, and
        split-off rows land past the scan snapshot exactly like the old
        list appends, so precomputing matches the sequential scan
        decision-for-decision."""
        if remaining <= 0:
            return 0
        cs = self.cohorts
        C = cs.C
        if C == 0:
            return 0
        m_all = cs.m[:C]
        elig = self.t.compat_tm[m_all, g] & self.p.tol_template[g, m_all]
        prospect = None
        if zone is not None:
            czone = cs.zone[:C]
            # a zone-free cohort may take zonal pods only by COMMITTING to
            # the zone (nodeclaim.go Add intersects requirements): allowed
            # iff every group already aboard admits the zone (zadm)
            prospect = (czone < 0) & cs.zadm[:C, zone]
            elig &= (czone == zone) | prospect
        # a cohort committed to SOME zone takes zone-free pods whenever the
        # group's requirements admit that zone — the enc-compat pass below
        # (or triviality) covers it, as before
        if not self._g_trivial[g] and elig.any():
            elig &= cs.compatible_rows(_row(self.p.group_enc, g),
                                       self.p.allow_undefined)
        if self._port_conflict is not None:
            conf = self._port_conflict[g]
            if conf.any():
                # a conflicting host port is already bound aboard
                elig &= ~(cs.aboard[:C] & conf).any(axis=1)
        if not elig.any():
            return 0
        order = np.argsort(cs.fill[:C], kind="stable")
        cand = order[elig[order]]
        placed_total = 0
        pos = 0
        chunk = 8
        while remaining > 0 and pos < cand.size:
            ch = cand[pos:pos + chunk]
            pos += ch.size
            chunk = min(chunk * 4, 512)
            caps, ts, per = self._cohort_caps(g, ch, zone, prospect)
            if per_node_cap:
                base = np.fromiter(
                    (cs.pods_by_group[ci].get(g, 0) for ci in ch),
                    dtype=np.int64, count=ch.size)
                caps = np.minimum(caps, np.maximum(0, per_node_cap - base))
            for j in np.nonzero(caps > 0)[0]:
                if remaining <= 0:
                    break
                ci = int(ch[j])
                cap = int(caps[j])
                commit_zone = prospect is not None and bool(prospect[ci])
                ts_row = ts[j]
                per_row = per[j] if per is not None else None
                # fill each node of the cohort up to cap; split if not all
                # consumed
                n_ci = int(cs.n[ci])
                fill_nodes = min(n_ci, -(-remaining // cap))
                if fill_nodes < n_ci:
                    # the UNFILLED nodes keep the cohort's original zone
                    # state: only nodes actually receiving zonal pods
                    # narrow their zone
                    cs.split(ci, n_ci - fill_nodes)
                    cs.n[ci] = fill_nodes
                # take at most cap per node: when demand exceeds the
                # cohort's total capacity (remaining > cap * n), every node
                # takes exactly cap and the leftover moves on — per_last
                # derived from the raw remaining overfilled the last node
                # past the per-node cap (e.g. 14 hostname-spread pods on
                # one node at maxSkew=1)
                take = min(remaining, cap * fill_nodes)
                per_last = take - cap * (fill_nodes - 1)
                if per_last != cap and fill_nodes > 1:
                    # last node takes the remainder; split it off
                    last = cs.split(ci, 1)
                    cs.n[ci] = fill_nodes - 1
                    if commit_zone:
                        self._commit_cohort_zone(ci, zone)
                        self._commit_cohort_zone(last, zone)
                    self._commit_to_cohort(last, g, per_last, ts_row, per_row)
                    self._commit_to_cohort(ci, g, cap, ts_row, per_row)
                    placed = take
                else:
                    fill = per_last if fill_nodes == 1 else cap
                    if commit_zone:
                        self._commit_cohort_zone(ci, zone)
                    self._commit_to_cohort(ci, g, fill, ts_row, per_row)
                    placed = fill * fill_nodes
                placed_total += placed
                remaining -= placed
        return placed_total

    def _commit_cohort_zone(self, ci: int, zone: int) -> None:
        """Pin a zone-free cohort to a zone: both the zone field AND the
        encoded requirements narrow (the enc drives offering admission in
        price ordering and keys the materialize order-cache — a stale
        all-zones enc would rank unreachable offerings and collide cache
        entries across differently-pinned cohorts)."""
        cs = self.cohorts
        cs.zone[ci] = zone
        cs.set_enc(ci, np_combine(cs.enc_row(ci), self._zone_enc(zone)))

    def _commit_to_cohort(self, ci: int, g: int, fill: int, ts: np.ndarray,
                          per: Optional[np.ndarray] = None):
        cs = self.cohorts
        cs.requests[ci] += self.p.group_req[g] * fill
        m = int(cs.m[ci])
        if per is not None:
            # ts only holds types fitting the pre-commit load, so the
            # _fits_requests refiltering against the grown request vector
            # reduces to the per-IT capacity bound (see _cohort_caps)
            cs.it_set[ci] = ts & (per >= fill)
        else:
            cs.it_set[ci] = ts & self._fits_requests(m, cs.requests[ci])
        pbg = cs.pods_by_group[ci]
        pbg[g] = pbg.get(g, 0) + fill
        cs.fill[ci] += fill
        if not cs.aboard[ci, g]:
            # first boarding of g: fold its planes into the aggregates.
            # Re-boarding is a no-op for all three — requirement combine
            # and the AND-folds are idempotent — which the old code paid
            # for anyway on every repeat commit.
            cs.aboard[ci, g] = True
            cs.zadm[ci] &= self.t.zone_adm[g, m]
            cs.okz[ci] &= cs._okz_row(g, m)
            if not self._g_trivial[g]:
                # combining with a no-requirements row is the identity
                cs.set_enc(ci, np_combine(cs.enc_row(ci),
                                          _row(self.p.group_enc, g)))

    def _fill_existing(self, g: int, remaining: int, zone: Optional[int],
                       per_node_cap: int,
                       node_caps: Optional[np.ndarray] = None,
                       max_nodes: int = 0) -> int:
        """Pack into live nodes. node_caps[n] (when given) hard-caps each
        node individually — the hostname-topology cap derived from already-
        scheduled matching pods (0 = excluded); max_nodes > 0 limits how many
        distinct nodes may be used (hostname pod affinity: all on one)."""
        placed_total = 0
        used_nodes = 0
        for n in self.exist_order:
            if remaining <= 0:
                break
            if max_nodes and used_nodes >= max_nodes:
                break
            if not self.t.exist_ok[g, n]:
                continue
            if zone is not None and (self.p.exist_zone is None
                                     or self.p.exist_zone[n] != zone):
                continue
            req = self.p.group_req[g]
            with np.errstate(divide="ignore"):
                per = np.where(req > 0, self.exist_avail[n] // np.maximum(req, 1),
                               INT32_MAX)
            cap = int(per.min()) if per.size else 0
            if per_node_cap:
                cap = min(cap, per_node_cap)
            if node_caps is not None:
                cap = min(cap, int(node_caps[n]))
            vol_counts = (self.vol_group_counts[g]
                          if self.vol_group_counts is not None else None)
            vol_rem = None
            if vol_counts:
                vol_rem = (self.vol_node_remaining[n]
                           if self.vol_node_remaining is not None
                           and n < len(self.vol_node_remaining) else None)
                if vol_rem:
                    cap = min(cap, min(
                        (vol_rem[d] // c for d, c in vol_counts.items()
                         if d in vol_rem), default=INT32_MAX))
            fill = min(cap, remaining)
            if fill <= 0:
                continue
            if vol_counts and vol_rem:
                for d, c in vol_counts.items():
                    if d in vol_rem:
                        vol_rem[d] -= c * fill
            self.exist_avail[n] = self.exist_avail[n] - req * fill
            self.result.existing.setdefault(n, []).append((g, fill))
            placed_total += fill
            remaining -= fill
            used_nodes += 1
        return placed_total

    # -- main ---------------------------------------------------------------

    def ffd_order(self) -> List[int]:
        """The first-fit-decreasing group order the sequential pack walks —
        exposed so the sharded pack (parallel/mesh.sharded_pack) can carve
        the SAME order into per-shard blocks."""
        cpu_idx = self.p.vocab.resource_idx.get("cpu", 0)
        mem_idx = self.p.vocab.resource_idx.get("memory", 0)
        return sorted(range(self.G), key=lambda g: (
            -self.p.group_req[g][cpu_idx], -self.p.group_req[g][mem_idx]))

    def pack(self, order: Optional[List[int]] = None) -> PackResult:
        """Pack every group of ``order`` (default: the full FFD order) into
        this packer's cohort set. An explicit order is the sharded-pack
        entry: it packs only that block of groups. The warm-start machinery
        is order-generic — checkpoints record state after a prefix of
        WHATEVER order this pack walks — so a per-shard WarmStart (its
        global token carries the shard identity, its seed that block's
        ffd_tokens) composes with an explicit block; callers that want a
        cold block pack simply construct the Packer without ``warm``."""
        if order is None:
            order = self.ffd_order()
        warm = self._warm if self._warm_usable() else None
        start = 0
        cks: List[PackCheckpoint] = []
        if warm is not None:
            start, cks = self._warm_restore(order, warm)
        step = max(1, (len(order) + 7) // 8)
        for pos in range(start, len(order)):
            self._pack_group(order[pos])
            if warm is not None and ((pos + 1) % step == 0
                                     or pos + 1 == len(order)):
                cks.append(self._checkpoint(pos + 1, order))
        if warm is not None:
            # bound the seed: carried + fresh checkpoints would otherwise
            # accumulate across passes (each holds full cohort-array
            # copies). Thin evenly, always keeping the LAST checkpoint so
            # an unchanged next pass still full-replays.
            if len(cks) > MAX_SEED_CHECKPOINTS:
                stride = -(-len(cks) // MAX_SEED_CHECKPOINTS)
                cks = cks[::-1][::stride][::-1]
            warm.result_seed = PackSeed(
                global_token=warm.global_token,
                ffd_tokens=[warm.tokens[g] for g in order],
                checkpoints=cks)
        self.result.cohorts = self.cohorts
        return self.result

    # -- warm start ---------------------------------------------------------

    def _warm_usable(self) -> bool:
        """Shapes whose shared mutable state is NOT checkpointed fall back
        to a full pack (delta encode still applies upstream): host ports
        (cross-group conflict state in result.existing), volume attach
        budgets (shared per-node dicts), minValues floors."""
        return (self._warm is not None
                and self.vol_group_counts is None
                and (self.group_ports is None
                     or not any(self.group_ports))
                and not self._has_min_its)

    def _warm_restore(self, order, warm: WarmStart
                      ) -> Tuple[int, List[PackCheckpoint]]:
        """Match the longest clean FFD prefix against the seed, restore the
        latest checkpoint inside it, and return (resume position, carried
        checkpoints remapped into the current group-index space)."""
        seed = warm.seed
        if seed is None or seed.global_token != warm.global_token:
            return 0, []
        n = 0
        for pos, g in enumerate(order):
            if pos >= len(seed.ffd_tokens) \
                    or seed.ffd_tokens[pos] != warm.tokens[g]:
                break
            n = pos + 1
        warm.matched = n
        usable = [c for c in seed.checkpoints if c.pos <= n]
        if not usable:
            return 0, []
        ck = max(usable, key=lambda c: c.pos)
        # position p of the seed's order packed old group ck.g_of_pos[p];
        # the current pack has order[p] there — token equality at every
        # prefix position makes the pairing exact
        remap = {ck.g_of_pos[p]: order[p] for p in range(ck.pos)}
        carried = [self._remap_checkpoint(c, remap) for c in usable]
        self._restore(carried[-1])
        warm.restored_pos = ck.pos
        return ck.pos, carried

    def _remap_checkpoint(self, ck: PackCheckpoint, remap: dict
                          ) -> PackCheckpoint:
        aboard = ck.rows["aboard"]
        new_aboard = np.zeros((ck.C, self.G), dtype=bool)
        for og, ng in remap.items():
            new_aboard[:, ng] = aboard[:ck.C, og]
        rows = dict(ck.rows)
        rows["aboard"] = new_aboard
        return PackCheckpoint(
            pos=ck.pos, C=ck.C, rows=rows,
            pods_by_group=[{remap[g]: f for g, f in d.items()}
                           for d in ck.pods_by_group],
            existing={n: [(remap[g], f) for g, f in fills]
                      for n, fills in ck.existing.items()},
            error_log=[(remap[g], c, m) for g, c, m in ck.error_log],
            exist_avail=ck.exist_avail, limits=ck.limits,
            limit_constrained=ck.limit_constrained,
            g_of_pos=[remap[g] for g in ck.g_of_pos])

    def _checkpoint(self, pos: int, order) -> PackCheckpoint:
        cs = self.cohorts
        C = cs.C
        return PackCheckpoint(
            pos=pos, C=C,
            rows={name: getattr(cs, name)[:C].copy()
                  for name in CohortSet._ROW_FIELDS},
            pods_by_group=[dict(d) for d in cs.pods_by_group],
            existing={n: list(f) for n, f in self.result.existing.items()},
            error_log=list(self._error_log),
            exist_avail=self.exist_avail.copy(),
            limits=[None if lm is None else dict(lm)
                    for lm in self.template_limits],
            limit_constrained=self.result.limit_constrained,
            g_of_pos=[order[p] for p in range(pos)])

    def _restore(self, ck: PackCheckpoint) -> None:
        cs = self.cohorts
        cap = cs._cap
        while cap < ck.C:
            cap *= 2
        cs._cap = cap
        for name in CohortSet._ROW_FIELDS:
            src = ck.rows[name]
            out = np.zeros((cap,) + src.shape[1:], src.dtype)
            out[:ck.C] = src[:ck.C]
            setattr(cs, name, out)
        cs.C = ck.C
        cs.pods_by_group = [dict(d) for d in ck.pods_by_group]
        cs._okz_rows = {}
        self.result.existing = {n: list(f) for n, f in ck.existing.items()}
        self.result.limit_constrained = ck.limit_constrained
        # error replay re-binds the recorded tail spans to CURRENT pod
        # objects (uids change across passes; group identity + count don't)
        self._error_log = list(ck.error_log)
        for g, count, msg in ck.error_log:
            pods = self.groups[g].pods
            for pod in pods[len(pods) - count:]:
                self.result.errors[pod.uid] = msg
        self.exist_avail[:] = ck.exist_avail
        self.template_limits = [None if lm is None else dict(lm)
                                for lm in ck.limits]

    def _error_group(self, g: int, count: int, msg: str) -> None:
        self._error_log.append((g, count, msg))
        pods = self.groups[g].pods
        start = len(pods) - count
        for pod in pods[start:]:
            self.result.errors[pod.uid] = msg

    def _host_caps(self, g: int, host_spec) -> Tuple[int, Optional[np.ndarray]]:
        """Per-fresh-node cap (0 = unlimited) and per-existing-node caps from
        the group's hostname-level constraint. Self-selecting constraints
        budget against already-scheduled matching pods per node
        (exist_counts); non-self constraints never budget batch pods (they
        don't match the selector) — they only admit or exclude nodes by their
        static matching counts (topologygroup.go:181-227, 316-342 with the
        hostname global-min floored at 0, :232-234)."""
        if host_spec is None:
            return 0, None
        N = self.exist_avail.shape[0]
        cnt = (self.exist_counts[g] if self.exist_counts is not None
               else np.zeros(N, dtype=np.int64))
        if host_spec.kind == "spread-host":
            skew = host_spec.max_skew
            if host_spec.self_select:
                return skew, np.maximum(0, skew - cnt)
            return 0, np.where(cnt > skew, 0, INT32_MAX)
        # anti-host
        if host_spec.self_select:
            return 1, np.where(cnt > 0, 0, 1)
        return 0, np.where(cnt > 0, 0, INT32_MAX)

    def _apply_port_caps(self, g: int, per_node_cap: int,
                         node_caps: Optional[np.ndarray]
                         ) -> Tuple[int, Optional[np.ndarray]]:
        """Identical host-port specs all conflict pairwise, so a port group
        holds at most ONE pod per node (fresh or existing), and nodes whose
        current pods already bind a conflicting port are out entirely."""
        if not self.group_ports or not self.group_ports[g]:
            return per_node_cap, node_caps
        per_node_cap = 1 if per_node_cap == 0 else min(per_node_cap, 1)
        caps = np.ones(self.exist_avail.shape[0], dtype=np.int64)
        if self.exist_port_block is not None:
            # the block covers the REAL nodes; exist_avail may be padded
            blocked = np.nonzero(self.exist_port_block[g])[0]
            caps[blocked] = 0
        # ports bound onto existing nodes EARLIER IN THIS PACK (the
        # pre-solve block can't know them): any conflicting group already
        # placed on a node takes that node out (scheduler.py:329 semantics
        # — the oracle updates usage per placement)
        if self._port_conflict is not None:
            for n, fills in self.result.existing.items():
                for g2, _fill in fills:
                    if self._port_conflict[g, g2]:
                        caps[n] = 0
                        break
        if node_caps is not None:
            caps = np.minimum(caps, node_caps)
        return per_node_cap, caps

    def _pack_group(self, g: int) -> None:
        group = self.groups[g]
        c = group.count
        if c == 0:
            return
        specs = group.topo or []
        zone_spec = next((s for s in specs
                          if s.kind in ("spread-zone", "affinity-zone",
                                        "anti-zone")), None)
        host_spec = next((s for s in specs
                          if s.kind in ("spread-host", "anti-host",
                                        "affinity-host")), None)

        if host_spec is not None and host_spec.kind == "affinity-host":
            self._pack_affinity_host(g, c)  # always alone (grouping)
            return
        per_node_cap, node_caps = self._host_caps(g, host_spec)
        per_node_cap, node_caps = self._apply_port_caps(g, per_node_cap,
                                                        node_caps)

        if zone_spec is None:
            placed = self._fill_existing(g, c, None, per_node_cap, node_caps)
            placed += self._fill_cohorts(g, c - placed, None, per_node_cap)
            placed += self._place_new(g, c - placed, None, per_node_cap)
            if placed < c:
                msg = "no instance type satisfied the pod"
                if host_spec is not None:
                    msg = ("unsatisfiable hostname topology spread"
                           if host_spec.kind == "spread-host"
                           else "unsatisfiable hostname anti-affinity")
                self._error_group(g, c - placed, msg)
        elif zone_spec.kind == "spread-zone":
            if zone_spec.self_select:
                self._pack_spread_zone(g, c, zone_spec, per_node_cap, node_caps)
            else:
                self._pack_spread_zone_static(g, c, zone_spec, per_node_cap,
                                              node_caps)
        elif zone_spec.kind == "affinity-zone":
            self._pack_affinity_zone(g, c, zone_spec, per_node_cap, node_caps)
        else:  # anti-zone (always alone among zone kinds)
            self._pack_anti_zone(g, c, zone_spec, per_node_cap, node_caps)

    def _place_new(self, g: int, remaining: int, zone: Optional[int],
                   per_node_cap: int) -> int:
        if remaining <= 0:
            return 0
        placed = 0
        for m in range(self.M):
            if remaining - placed <= 0:
                break
            ppn_all = self.t.ppn[g, m]
            it_ok = (self.t.it_ok_z[g, m, :, zone] if zone is not None
                     else self.t.it_ok[g, m])
            if not it_ok.any():
                continue
            per = self._fill_ceiling(g, m, ppn_all, it_ok)
            if per_node_cap:
                per = min(per, per_node_cap)
            placed += self._open_nodes(g, m, zone, remaining - placed, per)
        return placed

    def _place_one_node(self, g: int, c: int) -> int:
        for m in range(self.M):
            it_ok = self.t.it_ok[g, m]
            if not it_ok.any():
                continue
            limits = self.template_limits[m]
            limit_pruned = False
            if limits is not None:
                it_fit = it_ok & self._under_limits(m, it_ok)
                if not it_fit.any():
                    self.result.limit_constrained = True
                    continue
                limit_pruned = bool((it_fit != it_ok).any())
                it_ok = it_fit
            # fill sized from the (limit-filtered) surviving set
            per = self._fill_ceiling(g, m, self.t.ppn[g, m], it_ok)
            fill = min(per, c)
            if fill <= 0:
                if limit_pruned:
                    # the surviving (smaller) types hold zero pods: this
                    # failure exists only because limits pruned the big
                    # ones — not an oracle-final verdict
                    self.result.limit_constrained = True
                continue
            if not self._append_cohort(g, m, None, it_ok, fill,
                                       self._node_enc(g, m, None)):
                continue
            if limits is not None:
                self._subtract_max(m, it_ok)
            return fill
        return 0

    def _zone_admitted_viable(self, g: int) -> Tuple[np.ndarray, np.ndarray]:
        # admitted zones: group+any template admits; viable: some IT offering
        admitted = np.zeros(self.Z, dtype=bool)
        viable = np.zeros(self.Z, dtype=bool)
        for m in self._viable_templates(g):
            admitted |= self.t.zone_adm[g, m]
            viable |= self.t.it_ok_z[g, m].any(axis=0)
        return admitted, viable

    def _zone_min_mask(self, g: int) -> np.ndarray:
        """The pod's view of the domain universe for global-min/minDomains
        arithmetic (topologygroup.go:229-250): every registered domain the
        POD's own requirements admit. The universe spans ALL templates'
        admitted zones — including templates the group can't actually use
        (tainted pools, incompatible requirements): a zero-count zone behind
        an intolerable taint still pins the reference's global min at 0 —
        plus zones holding recorded cluster pods (izc) that no template
        reaches at all."""
        greq = self.groups[g].requirements.get(api_labels.LABEL_TOPOLOGY_ZONE)
        pod_admits = np.fromiter((greq.has(z) for z in self._zone_names),
                                 dtype=bool, count=self.Z)
        # zone_adm[g, m] is already pod-side-intersected (combined reqs)
        return self.t.zone_adm[g].any(axis=0) | \
            (pod_admits & (self.zone_counts[g] > 0))

    def _fill_zone(self, g: int, a: int, z: int, per_node_cap: int,
                   node_caps: Optional[np.ndarray]) -> int:
        placed = self._fill_existing(g, a, z, per_node_cap, node_caps)
        placed += self._fill_cohorts(g, a - placed, z, per_node_cap)
        placed += self._place_new(g, a - placed, z, per_node_cap)
        return placed

    def _pack_spread_zone(self, g: int, c: int, spec, per_node_cap: int = 0,
                          node_caps: Optional[np.ndarray] = None) -> None:
        admitted, viable = self._zone_admitted_viable(g)
        if not admitted.any():
            self._error_group(g, c, "no zone admitted for topology spread")
            return
        alloc = waterfill(self.zone_counts[g], viable, admitted, c,
                          spec.max_skew, spec.min_domains,
                          zone_names=self._zone_names,
                          min_mask=self._zone_min_mask(g))
        placed_total = 0
        for z in np.argsort(-alloc):
            a = int(alloc[z])
            if a <= 0:
                continue
            placed = self._fill_zone(g, a, int(z), per_node_cap, node_caps)
            self.zone_counts[g, z] += placed
            placed_total += placed
        if placed_total < c:
            self._error_group(g, c - placed_total, "unsatisfiable zonal topology spread")

    def _pack_spread_zone_static(self, g: int, c: int, spec,
                                 per_node_cap: int,
                                 node_caps: Optional[np.ndarray]) -> None:
        """Non-self-selecting zonal spread: placing batch pods never changes
        the domain counts, so the skew arithmetic is static. Existing nodes
        in any skew-eligible zone may take pods; fresh nodes all commit to
        the min-count eligible zone, exactly the domain nextDomain would
        return for an unconstrained node (topologygroup.go:181-227)."""
        admitted, viable = self._zone_admitted_viable(g)
        if not admitted.any():
            self._error_group(g, c, "no zone admitted for topology spread")
            return
        counts = self.zone_counts[g]
        min_mask = self._zone_min_mask(g)
        floor_zero = (spec.min_domains is not None
                      and int(min_mask.sum()) < spec.min_domains)
        gmin = 0 if floor_zero else (int(counts[min_mask].min())
                                     if min_mask.any() else 0)
        eligible = admitted & (counts - gmin <= spec.max_skew)
        if not eligible.any():
            self._error_group(g, c, "unsatisfiable zonal topology spread")
            return
        placed = 0
        for z in np.where(eligible)[0]:
            if placed >= c:
                break
            placed += self._fill_existing(g, c - placed, int(z),
                                          per_node_cap, node_caps)
        fresh = eligible & viable
        if placed < c and fresh.any():
            cand = np.where(fresh)[0]
            z = int(cand[np.lexsort((self._zone_names[cand],
                                     counts[cand]))[0]])
            placed += self._fill_cohorts(g, c - placed, z, per_node_cap)
            placed += self._place_new(g, c - placed, z, per_node_cap)
        if placed < c:
            self._error_group(g, c - placed, "unsatisfiable zonal topology spread")

    def _pack_affinity_zone(self, g: int, c: int, spec, per_node_cap: int = 0,
                            node_caps: Optional[np.ndarray] = None) -> None:
        admitted, viable = self._zone_admitted_viable(g)
        counts = self.zone_counts[g]
        # occupancy is judged through the POD's domain view: a matching pod
        # in a zone no template reaches still blocks the bootstrap
        # (nextDomainAffinity returns empty options, not a fresh domain)
        occupied = (counts > 0) & self._zone_min_mask(g)
        if occupied.any():
            occupied &= admitted
            # pods must join an occupied domain (topologygroup.go:253-300);
            # if none of those domains has a viable instance type the pods
            # fail — there is NO bootstrap while matching pods exist
            candidates = np.where(occupied & viable)[0]
            if len(candidates) == 0:
                self._error_group(
                    g, c, "zonal pod affinity: no viable occupied zone")
                return
        elif not spec.self_select:
            # non-self affinity can never self-satisfy (the bootstrap at
            # topologygroup.go:283-287 requires the pod to match its own
            # selector): nothing matches anywhere -> unschedulable
            self._error_group(
                g, c, "zonal pod affinity: no pods match the affinity selector")
            return
        else:
            candidates = np.where(viable)[0]
            if len(candidates) == 0:
                self._error_group(g, c, "no viable zone for zonal pod affinity")
                return
        # host-parity tie-break: first domain by NAME (the oracle's affinity
        # bootstrap iterates sorted(self.domains)), not by vocab index
        z = int(min(candidates, key=self._zone_names.__getitem__))
        placed = self._fill_zone(g, c, z, per_node_cap, node_caps)
        self.zone_counts[g, z] += placed
        if placed < c:
            self._error_group(g, c - placed, "zonal pod affinity: zone capacity exhausted")

    def _pack_anti_zone(self, g: int, c: int, spec,
                        per_node_cap: int = 0,
                        node_caps: Optional[np.ndarray] = None) -> None:
        """Zonal anti-affinity: pods may only land in EMPTY domains
        (topologygroup.go:316-342). Self-selecting: each placement occupies a
        zone, and peers in the same batch are mutually excluded but not yet
        recorded — late committal places one pod per batch
        (topology_test.go:2150-2176). Non-self: batch pods never occupy
        domains, so every pod can go to any statically-empty zone."""
        admitted, viable = self._zone_admitted_viable(g)
        counts = self.zone_counts[g]
        empty = admitted & (counts == 0)
        if spec.self_select:
            placed = 0
            for z in np.where(empty)[0]:
                placed = self._fill_zone(g, 1, int(z), per_node_cap, node_caps)
                if placed:
                    self.zone_counts[g, z] += 1
                    break
            if placed < 1:
                self._error_group(g, c, "unsatisfiable zonal anti-affinity")
            elif c > 1:
                self._error_group(
                    g, c - 1, "zonal anti-affinity: domain undetermined until next batch")
            return
        placed = 0
        for z in np.where(empty)[0]:
            if placed >= c:
                break
            placed += self._fill_zone(g, c - placed, int(z), per_node_cap,
                                      node_caps)
        if placed < c:
            self._error_group(g, c - placed, "unsatisfiable zonal anti-affinity")

    def _pack_affinity_host(self, g: int, c: int) -> None:
        """Hostname pod affinity (self-selecting; grouping keeps non-self on
        the host path). With matching pods already scheduled, the batch must
        join their nodes (no bootstrap, topologygroup.go:253-287); otherwise
        the hostname domain is fixed by the first placement, so everything
        lands on ONE node and overflow is unschedulable."""
        total = (int(self.host_match_total[g])
                 if self.host_match_total is not None else 0)
        if total > 0:
            cnt = (self.exist_counts[g] if self.exist_counts is not None
                   else np.zeros(self.exist_avail.shape[0], dtype=np.int64))
            node_caps = np.where(cnt > 0, INT32_MAX, 0)
            placed = self._fill_existing(g, c, None, 0, node_caps)
            if placed < c:
                self._error_group(
                    g, c - placed,
                    "hostname pod affinity: no co-located capacity")
            return
        placed = self._fill_existing(g, c, None, 0, None, max_nodes=1)
        if placed == 0:
            placed = self._place_one_node(g, c)
        if placed < c:
            self._error_group(g, c - placed,
                              "hostname pod affinity: node capacity exhausted")


def _row(e: EncodedRequirements, i: int) -> EncodedRequirements:
    return EncodedRequirements(mask=e.mask[i], defined=e.defined[i],
                               complement=e.complement[i], exempt=e.exempt[i],
                               gt=e.gt[i], lt=e.lt[i])
