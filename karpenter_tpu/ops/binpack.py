"""Grouped first-fit-decreasing bin-pack solver with TPU-resident feasibility.

Replaces the reference's per-pod greedy loop (scheduler.go:207-315, O(pods x
instance-types) with full refiltering per pod) by:

1. ``precompute`` — ONE jit-compiled device program computing every pairwise
   feasibility quantity the greedy needs, over all (group, template, instance
   type, zone, existing node) combinations at once: requirement compatibility
   (bitpacked mask algebra), offering availability per zone, int32 pods-per-node
   via broadcast division. This is the O(G*M*T*Z + G*N) hot math.
2. ``pack`` — a host-side greedy over *groups* (dozens, not tens of thousands)
   in first-fit-decreasing order, making the same decisions the reference
   makes per pod but in closed form per group: zone water-fill for topology
   spreads, per-node caps for hostname spread/anti-affinity, cohort tracking
   for cross-group node mixing, subtractMax limit pessimism per opened node.

Node-count parity with the reference greedy is validated against the host
oracle scheduler in tests/test_binpack_parity.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import labels as api_labels
from . import encode as enc
from . import feasibility as feas
from .encode import EncodedRequirements

INT32_MAX = 2**31 - 1


# --------------------------------------------------------------------------
# numpy mini-algebra over EncodedRequirements rows (host-side cohort updates;
# same rules as feasibility.py kernels, scalar-shaped)
# --------------------------------------------------------------------------

def np_compatible(a: EncodedRequirements, b: EncodedRequirements,
                  allow_undefined: np.ndarray) -> bool:
    gt = np.maximum(a.gt, b.gt)
    lt = np.minimum(a.lt, b.lt)
    crossed = (gt > -2**31) & (lt < 2**31 - 1) & (gt >= lt)
    nonempty = np.any(a.mask & b.mask, axis=-1) & ~crossed
    checked = a.defined & b.defined
    exempt = a.exempt & b.exempt
    bad = checked & ~nonempty & ~exempt
    undef_bad = b.defined & ~a.defined & ~allow_undefined & ~b.exempt
    return not np.any(bad | undef_bad)


def np_combine(a: EncodedRequirements, b: EncodedRequirements) -> EncodedRequirements:
    gt = np.maximum(a.gt, b.gt)
    lt = np.minimum(a.lt, b.lt)
    crossed = (gt > -2**31) & (lt < 2**31 - 1) & (gt >= lt)
    mask = np.where(crossed[..., None], np.uint32(0), a.mask & b.mask)
    complement = a.complement & b.complement & ~crossed
    empty = ~np.any(mask != 0, axis=-1)
    exempt = np.where(complement, a.exempt | b.exempt, empty)
    gt = np.where(complement, gt, -2**31)
    lt = np.where(complement, lt, 2**31 - 1)
    return EncodedRequirements(mask=mask, defined=a.defined | b.defined,
                               complement=complement, exempt=exempt, gt=gt, lt=lt)


# --------------------------------------------------------------------------
# problem + device precompute
# --------------------------------------------------------------------------

@dataclass
class PackProblem:
    """Fully encoded solve input. Build via provisioning.tensor_scheduler."""
    vocab: enc.Vocab
    # groups
    group_enc: EncodedRequirements        # stacked [G, ...]
    group_req: np.ndarray                 # int64 [G, R] scaled requests
    group_count: np.ndarray               # int64 [G]
    # templates
    template_enc: EncodedRequirements     # [M, ...]
    daemon_overhead: np.ndarray           # int64 [M, R]
    tol_template: np.ndarray              # bool [G, M] pod tolerates template taints
    # instance types (union catalog)
    it_enc: EncodedRequirements           # [T, ...]
    it_alloc: np.ndarray                  # int64 [T, R]
    it_capacity: np.ndarray               # int64 [T, R]
    it_price: np.ndarray                  # float32 [T] cheapest available offering
    template_its: np.ndarray              # bool [M, T]
    off_zone: np.ndarray                  # int32 [T, O] zone value idx or -1
    off_captype: np.ndarray               # int32 [T, O]
    off_available: np.ndarray             # bool [T, O]
    # zones
    zone_key: int                         # key index of topology zone
    captype_key: int
    zone_values: np.ndarray               # int32 [Z] value indices
    # existing nodes (may be empty)
    exist_enc: Optional[EncodedRequirements] = None  # [N, ...]
    exist_avail: Optional[np.ndarray] = None         # int64 [N, R]
    exist_zone: Optional[np.ndarray] = None          # int32 [N] zone idx or -1
    tol_exist: Optional[np.ndarray] = None           # bool [G, N]
    allow_undefined: Optional[np.ndarray] = None     # bool [K] well-known keys
    off_price: Optional[np.ndarray] = None           # float32 [T, O] (inf absent)
    # shared mutable slot (from the catalog-encoding cache): device-resident
    # copies of the catalog-side arrays, so repeat solves against the same
    # instance-type catalog skip the host->device upload entirely
    device_cache: Optional[dict] = None


@dataclass
class PackTensors:
    """Fetched results of the device precompute."""
    compat_tm: np.ndarray      # bool [M, G] template x group requirement compat
    it_ok: np.ndarray          # bool [G, M, T]
    ppn: np.ndarray            # int32 [G, M, T] pods-per-fresh-node
    it_ok_z: np.ndarray        # bool [G, M, T, Z]
    zone_adm: np.ndarray       # bool [G, M, Z] combined reqs admit zone
    exist_ok: np.ndarray       # bool [G, N]
    exist_cap: np.ndarray      # int32 [G, N]


def zone_pack_layout(Z: int):
    """(storage dtype, word count) for the packed zone bitfield — the ONE
    place this is decided: the kernel packs with it and _output_layout
    decodes with it, so they can never drift apart."""
    dtype = np.uint8 if Z <= 8 else (np.uint16 if Z <= 16 else np.uint32)
    return dtype, -(-Z // np.iinfo(dtype).bits)


def precompute_kernel(group, template, it, group_req, daemon, alloc,
                      template_its, off_zone, off_captype, off_available,
                      zone_values, allow_undefined, tol_template,
                      exist, exist_avail, tol_exist,
                      *, zone_key: int, captype_key: int, has_exist: bool):
    G = group.mask.shape[0]
    M = template.mask.shape[0]
    T = it.mask.shape[0]
    Z = zone_values.shape[0]

    # template x group compatibility + combined requirement sets [M*G]
    compat_tm = feas.compatible_matrix(template, group, allow_undefined)  # [M, G]
    cmb = feas.combine(
        jax.tree.map(lambda x: x[:, None], template),
        jax.tree.map(lambda x: x[None, :], group))          # [M, G, K, ...]
    cmb_flat = jax.tree.map(lambda x: x.reshape((M * G,) + x.shape[2:]), cmb)

    # instance-type requirement compat: existing side = IT (nodeclaim.go:295-297)
    it_compat = feas.intersects_matrix(it, cmb_flat)         # [T, M*G]
    it_compat = it_compat.T.reshape(M, G, T).transpose(1, 0, 2)  # [G, M, T]

    # offerings: per zone and any-zone
    zone_bit_words = zone_values // 32
    zone_bits = zone_values % 32
    zmask = cmb_flat.mask[:, zone_key, :]                    # [MG, W]
    zone_adm = ((jnp.take(zmask, zone_bit_words, axis=1)
                 >> zone_bits[None, :].astype(jnp.uint32)) & 1) == 1  # [MG, Z]
    # offering o passes for (mg, t, z) iff available, zone==z, captype admitted
    cap_bit_ok = _offering_value_ok(cmb_flat.mask, captype_key, off_captype)  # [MG,T,O]
    zmatch = off_zone[None, :, :, None] == zone_values[None, None, None, :]   # [1,T,O,Z]
    off_ok_z = jnp.any(off_available[None, :, :, None] & zmatch
                       & cap_bit_ok[:, :, :, None], axis=2)  # [MG, T, Z]
    off_ok_z = off_ok_z & zone_adm[:, None, :]
    off_ok_any = jnp.any(off_ok_z, axis=-1)                  # [MG, T]

    # pods per node
    ppn = feas.pods_per_node(alloc, daemon, group_req)       # [G, M, T]

    ok_base = (it_compat
               & template_its[None, :, :]
               & tol_template[:, :, None]
               & compat_tm.T[:, :, None]
               & (ppn >= 1))
    it_ok_z = (ok_base[:, :, :, None]
               & off_ok_z.reshape(M, G, T, Z).transpose(1, 0, 2, 3))
    # pack the zone axis into a bitfield: Wz fetched words instead of Z+1
    # bool planes (it_ok_any == any bit set, derived host-side). Multi-word
    # so Z > 32 packs losslessly.
    np_dtype, Wz = zone_pack_layout(Z)
    pack_dtype = jnp.dtype(np_dtype)
    word_bits = jnp.iinfo(pack_dtype).bits
    z_pad = Wz * word_bits - Z
    padded_ok = jnp.pad(it_ok_z, ((0, 0), (0, 0), (0, 0), (0, z_pad)))
    weights = (jnp.ones((), pack_dtype)
               << jnp.arange(word_bits, dtype=pack_dtype))
    it_okz_packed = jnp.sum(
        padded_ok.reshape(G, M, T, Wz, word_bits).astype(pack_dtype)
        * weights[None, None, None, None, :], axis=-1,
        dtype=pack_dtype)                                    # [G,M,T,Wz]
    zone_adm_gmz = zone_adm.reshape(M, G, Z).transpose(1, 0, 2)

    if has_exist:
        exist_compat = feas.compatible_matrix(exist, group,
                                              jnp.zeros_like(allow_undefined))  # [N, G]
        exist_ok = exist_compat.T & tol_exist                # [G, N]
        per = jnp.where(group_req[:, None, :] > 0,
                        exist_avail[None, :, :] // jnp.maximum(group_req[:, None, :], 1),
                        jnp.int32(INT32_MAX))
        exist_cap = jnp.clip(jnp.min(per, axis=-1), 0, INT32_MAX).astype(jnp.int32)
        exist_ok = exist_ok & (exist_cap >= 1)
    else:
        exist_ok = jnp.zeros((G, 1), dtype=bool)
        exist_cap = jnp.zeros((G, 1), dtype=jnp.int32)

    ppn16 = jnp.clip(ppn, 0, 32767).astype(jnp.int16)
    return (compat_tm, it_okz_packed, ppn16, zone_adm_gmz, exist_ok, exist_cap)


def _pack_outputs(outs):
    """Flatten the kernel's six outputs into ONE uint8 buffer on device:
    jax.device_get pays a host<->device round trip per array, and through a
    network tunnel (axon) that latency — not bandwidth — dominates the
    fetch. Multi-byte dtypes are bitcast to uint8 lanes; booleans widen."""
    import jax.lax as lax
    parts = []
    for o in outs:
        if o.dtype == jnp.uint8:
            parts.append(o.reshape(-1))
        elif o.dtype == jnp.bool_:
            parts.append(o.astype(jnp.uint8).reshape(-1))
        else:
            parts.append(
                lax.bitcast_convert_type(o.reshape(-1), jnp.uint8).reshape(-1))
    return jnp.concatenate(parts)


def _precompute_packed_kernel(*args, **statics):
    return _pack_outputs(precompute_kernel(*args, **statics))


_precompute_packed = partial(jax.jit, static_argnames=(
    "zone_key", "captype_key", "has_exist"))(_precompute_packed_kernel)


def _split_packed(flat: np.ndarray, shapes_dtypes):
    """Host-side inverse of _pack_outputs."""
    out = []
    off = 0
    for shape, dtype, logical in shapes_dtypes:
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        chunk = flat[off:off + n].view(dtype).reshape(shape)
        off += n
        out.append(chunk.astype(bool) if logical == "bool" else chunk)
    assert off == flat.size, \
        f"packed output layout desync: consumed {off} of {flat.size} bytes"
    return out


def _offering_value_ok(mask_b, key: int, off_val):
    """[B,T,O]: does mask_b admit each offering's single value at `key`
    (-1 == unconstrained)."""
    masks = mask_b[:, key, :]                                # [B, W]
    word = jnp.where(off_val >= 0, off_val // 32, 0)
    bit = jnp.where(off_val >= 0, off_val % 32, 0)
    w = masks[:, word]                                       # [B, T, O]
    has = (w >> bit[None, :, :].astype(jnp.uint32)) & jnp.uint32(1)
    return jnp.where(off_val[None, :, :] >= 0, has == 1, True)


def device_args(p: PackProblem):
    """Build the positional-array / static-kwarg split for precompute_kernel."""
    has_exist = p.exist_enc is not None and p.exist_enc.mask.shape[0] > 0
    dev = lambda e: feas.to_device(e)
    i32 = lambda a: jnp.asarray(np.clip(a, -INT32_MAX - 1, INT32_MAX).astype(np.int32))
    if has_exist:
        exist, exist_avail, tol_exist = (dev(p.exist_enc),
                                         i32(p.exist_avail),
                                         jnp.asarray(p.tol_exist))
    else:
        K, W = p.group_enc.mask.shape[1:]
        exist = feas.Enc(mask=jnp.zeros((1, K, W), jnp.uint32),
                         defined=jnp.zeros((1, K), bool),
                         complement=jnp.zeros((1, K), bool),
                         exempt=jnp.zeros((1, K), bool),
                         gt=jnp.zeros((1, K), jnp.int32),
                         lt=jnp.zeros((1, K), jnp.int32))
        exist_avail = jnp.zeros((1, p.group_req.shape[1]), jnp.int32)
        tol_exist = jnp.zeros((p.group_req.shape[0], 1), bool)
    cache = p.device_cache
    it_side = cache.get("it_side") if cache is not None else None
    if it_side is None:
        it_side = (dev(p.it_enc), i32(p.it_alloc), jnp.asarray(p.off_zone),
                   jnp.asarray(p.off_captype), jnp.asarray(p.off_available),
                   jnp.asarray(p.zone_values), jnp.asarray(p.allow_undefined))
        if cache is not None:
            cache["it_side"] = it_side
    (it_enc_d, it_alloc_d, off_zone_d, off_captype_d, off_avail_d,
     zone_values_d, allow_undef_d) = it_side
    args = (dev(p.group_enc), dev(p.template_enc), it_enc_d,
            i32(p.group_req), i32(p.daemon_overhead),
            it_alloc_d, jnp.asarray(p.template_its),
            off_zone_d, off_captype_d,
            off_avail_d, zone_values_d,
            allow_undef_d, jnp.asarray(p.tol_template),
            exist, exist_avail, tol_exist)
    statics = dict(zone_key=p.zone_key, captype_key=p.captype_key,
                   has_exist=has_exist)
    return args, statics


def _output_layout(p: PackProblem, has_exist: bool):
    """(shape, storage-dtype, logical) per kernel output, matching
    precompute_kernel's return order."""
    G = p.group_req.shape[0]
    M = p.daemon_overhead.shape[0]
    T = p.it_alloc.shape[0]
    Z = p.zone_values.shape[0]
    N = p.exist_avail.shape[0] if has_exist else 1
    pack_dtype, Wz = zone_pack_layout(Z)
    return [
        ((M, G), np.uint8, "bool"),            # compat_tm
        ((G, M, T, Wz), pack_dtype, "raw"),    # it_okz_packed
        ((G, M, T), np.int16, "raw"),          # ppn
        ((G, M, Z), np.uint8, "bool"),         # zone_adm
        ((G, N), np.uint8, "bool"),            # exist_ok
        ((G, N), np.int32, "raw"),             # exist_cap
    ]


def precompute(p: PackProblem) -> PackTensors:
    args, statics = device_args(p)
    # single packed fetch: per-array device_get pays a host<->device round
    # trip per tensor, and through a network tunnel (axon) the LATENCY of
    # those trips — not the bytes — dominates the fetch
    flat = np.asarray(_precompute_packed(*args, **statics))
    compat_tm, it_okz_packed, ppn, zone_adm, exist_ok, exist_cap = \
        _split_packed(flat, _output_layout(p, statics["has_exist"]))
    return unpack_tensors(compat_tm, it_okz_packed, ppn, zone_adm,
                          exist_ok, exist_cap, p.zone_values.shape[0])


def unpack_tensors(compat_tm, it_okz_packed, ppn, zone_adm, exist_ok,
                   exist_cap, Z: int) -> PackTensors:
    """Expand the packed zone bitfield [G,M,T,Wz] back into the packer's bool
    views."""
    word_bits = np.iinfo(it_okz_packed.dtype).bits
    bits = (it_okz_packed[..., None] >> np.arange(word_bits).astype(
        it_okz_packed.dtype)) & 1                      # [G,M,T,Wz,word_bits]
    shape = it_okz_packed.shape[:3] + (-1,)
    it_ok_z = bits.astype(bool).reshape(shape)[..., :Z]
    return PackTensors(compat_tm=compat_tm,
                       it_ok=np.any(it_okz_packed != 0, axis=-1),
                       ppn=ppn.astype(np.int32), it_ok_z=it_ok_z,
                       zone_adm=zone_adm, exist_ok=exist_ok,
                       exist_cap=exist_cap)


# --------------------------------------------------------------------------
# host greedy over groups
# --------------------------------------------------------------------------

@dataclass
class Cohort:
    """n identical in-flight nodes: same template, zone restriction, cumulative
    requests and surviving instance-type set."""
    m: int
    zone: Optional[int]
    it_set: np.ndarray               # bool [T]
    requests: np.ndarray             # int64 [R] per node
    n: int
    enc: EncodedRequirements         # accumulated requirement row
    pods_by_group: Dict[int, int] = field(default_factory=dict)  # per-node fill


@dataclass
class PackResult:
    # (template m, zone idx or None, it_set bool [T], [pod,...]) per new node
    nodes: List[tuple] = field(default_factory=list)
    existing: Dict[int, list] = field(default_factory=dict)  # node idx -> pods
    errors: Dict[str, str] = field(default_factory=dict)     # pod uid -> error
    cohorts: List[Cohort] = field(default_factory=list)
    # a nodepool limit excluded capacity during this pack: WHO gets the
    # scarce budget is order-dependent, so pack errors under limit pressure
    # are not oracle-final (the production scheduler re-solves on the host
    # path instead of trusting them; see TensorScheduler._solve)
    limit_constrained: bool = False


def waterfill(counts: np.ndarray, viable: np.ndarray, admitted: np.ndarray,
              c: int, max_skew: int,
              min_domains: Optional[int] = None,
              zone_names: Optional[np.ndarray] = None,
              min_mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Distribute c pods over zones the way the reference's min-count domain
    selection does (topologygroup.go:181-227): each pod goes to the lowest-count
    admitted+viable zone subject to count+1-min <= maxSkew. The global min is
    taken over `min_mask` — the POD's view of the domain universe
    (topologygroup.go:229-250), which can include zones no template reaches
    (e.g. a cluster pod in a zone the pool excludes pins the min there) —
    defaulting to `admitted`. With minDomains set and fewer min_mask domains
    than it, the global min floors to zero (topologygroup.go:240-247), so the
    skew check binds against absolute counts. Returns per-zone allocation
    (pods that can't place anywhere are simply not allocated; caller errors
    them)."""
    counts = counts.astype(np.int64).copy()
    alloc = np.zeros_like(counts)
    remaining = c
    if min_mask is None:
        min_mask = admitted
    floor_zero = (min_domains is not None
                  and int(min_mask.sum()) < min_domains)
    # fast path: every admitted zone viable AND the pod's min universe is
    # exactly the placement set -> sequential min-fill equals a closed-form
    # water-fill (skew never binds when always filling the min; invalid
    # under the minDomains zero floor or when an unreachable domain pins
    # the global min below the fill level)
    if not floor_zero and admitted.any() and (viable | ~admitted).all() \
            and bool((min_mask == admitted).all()):
        idx = np.where(admitted)[0]
        cz = counts[idx]
        # largest level L with sum(max(0, L - cz)) <= remaining
        lo, hi = int(cz.min()), int(cz.max()) + remaining
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if int(np.maximum(0, mid - cz).sum()) <= remaining:
                lo = mid
            else:
                hi = mid - 1
        add = np.maximum(0, lo - cz)
        rem = remaining - int(add.sum())
        at_level = np.where(cz + add == lo)[0]  # lex order == index order
        for pos in at_level[:rem]:
            add[pos] += 1
        alloc[idx] = add
        return alloc
    while remaining > 0:
        if floor_zero:
            m0 = 0
        else:
            m0 = counts[min_mask].min() if min_mask.any() else 0
        eligible = viable & admitted & (counts + 1 - m0 <= max_skew)
        if not eligible.any():
            break
        cand = np.where(eligible)[0]
        # min count, ties by domain NAME — the host oracle's deterministic
        # tie-break (_next_domain_spread iterates sorted(candidates))
        tie = zone_names[cand] if zone_names is not None else cand
        zi = cand[np.lexsort((tie, counts[cand]))[0]]
        alloc[zi] += 1
        counts[zi] += 1
        remaining -= 1
    return alloc


class Packer:
    """Greedy group packer consuming PackTensors."""

    def __init__(self, p: PackProblem, t: PackTensors, groups,
                 template_limits: List[Optional[dict]],
                 limit_resources: List[str],
                 initial_zone_counts: Optional[np.ndarray] = None,
                 exist_order: Optional[List[int]] = None,
                 exist_counts: Optional[np.ndarray] = None,
                 host_match_total: Optional[np.ndarray] = None,
                 vol_group_counts: Optional[list] = None,
                 vol_node_remaining: Optional[list] = None,
                 group_ports: Optional[list] = None,
                 exist_port_block: Optional[np.ndarray] = None):
        self.p = p
        self.t = t
        self.groups = groups
        self.G = len(groups)
        self.Z = len(p.zone_values)
        self.T = p.it_alloc.shape[0]
        self.M = p.daemon_overhead.shape[0]
        self.template_limits = template_limits  # remaining ResourceList (scaled) or None
        self.limit_resources = limit_resources
        self.zone_counts = (initial_zone_counts.copy() if initial_zone_counts is not None
                            else np.zeros((self.G, self.Z), dtype=np.int64))
        self.exist_order = exist_order if exist_order is not None else (
            list(range(p.exist_avail.shape[0])) if p.exist_avail is not None else [])
        self.exist_avail = (p.exist_avail.copy() if p.exist_avail is not None
                            else np.zeros((0, p.group_req.shape[1]), dtype=np.int64))
        # scheduled cluster pods matching each group's hostname-level
        # selector, per packable existing node [G, N] and in total [G] (the
        # countDomains analog for hostname topologies, topology.go:268-321)
        self.exist_counts = exist_counts
        self.host_match_total = host_match_total
        # CSI attach limits for per-pod (ephemeral) claims, linearized
        # (volumeusage.go:201-208): vol_group_counts[g] = {driver: claims
        # per pod} or None; vol_node_remaining[n] = {driver: remaining
        # slots} for limited drivers only, or None for unlimited nodes.
        # Shared MUTABLE per-node dicts: every group placing on a node
        # draws down the same driver budget.
        self.vol_group_counts = vol_group_counts
        self.vol_node_remaining = vol_node_remaining
        # host-port semantics, tensorized (hostportusage.go:34-90):
        # group_ports[g] = (ip, port, protocol) triples or (); identical
        # specs mean any two pods of a port group conflict -> one pod per
        # node; a precomputed GxG matrix gates cross-group co-location and
        # exist_port_block[G, N] excludes nodes already using the ports
        self.group_ports = group_ports
        self.exist_port_block = exist_port_block
        if group_ports is not None and any(group_ports):
            from ..scheduling.hostports import triples_conflict
            pg = [g for g in range(self.G) if group_ports[g]]
            self._port_conflict = np.zeros((self.G, self.G), dtype=bool)
            for i, gi in enumerate(pg):
                for gj in pg[i:]:
                    if triples_conflict(group_ports[gi], group_ports[gj]):
                        self._port_conflict[gi, gj] = True
                        self._port_conflict[gj, gi] = True
        else:
            self._port_conflict = None
        # domain-name tie-break order for zone selection (host parity)
        self._zone_names = np.array(p.vocab.values[p.zone_key], dtype=object)
        self.result = PackResult()
        # per-group nonzero request columns + per-(m,g) daemon-adjusted
        # allocatable slices, so the per-probe capacity math touches only the
        # resources the group actually requests (hot path: _cohort_capacity)
        self._req_nz = [np.nonzero(p.group_req[g])[0] for g in range(self.G)]
        self._req_vals = [p.group_req[g][self._req_nz[g]] for g in range(self.G)]
        self._alloc_nz_cache: Dict[tuple, np.ndarray] = {}
        self._madj_cache: Dict[int, np.ndarray] = {}

    def _alloc_nz(self, m: int, g: int) -> np.ndarray:
        """[T, nnz(g)] allocatable minus template daemon overhead, restricted
        to group g's requested resources."""
        key = (m, g)
        out = self._alloc_nz_cache.get(key)
        if out is None:
            nz = self._req_nz[g]
            out = self.p.it_alloc[:, nz] - self.p.daemon_overhead[m][nz]
            self._alloc_nz_cache[key] = out
        return out

    # -- helpers ------------------------------------------------------------

    def _viable_templates(self, g: int) -> List[int]:
        return [m for m in range(self.M) if self.t.it_ok[g, m].any()]

    def _open_nodes(self, g: int, m: int, zone: Optional[int], n_pods: int,
                    per_node: int) -> int:
        """Open as many nodes as limits allow for n_pods; returns pods placed."""
        if per_node <= 0:
            return 0
        it_ok = (self.t.it_ok_z[g, m, :, zone] if zone is not None
                 else self.t.it_ok[g, m])
        it_set = it_ok & (self.t.ppn[g, m] >= 1)
        if not it_set.any():
            return 0
        limits = self.template_limits[m]
        cohort_enc = self._node_enc(g, m, zone)
        if limits is None:
            full_nodes, rem = divmod(n_pods, per_node)
            placed = 0
            if full_nodes and self._append_cohort(g, m, zone, it_set, per_node,
                                                  cohort_enc, n=full_nodes):
                placed += full_nodes * per_node
            if rem and self._append_cohort(g, m, zone, it_set, rem,
                                           cohort_enc, n=1):
                placed += rem
            return placed
        placed = 0
        while placed < n_pods:
            it_fit = it_set & self._under_limits(m, it_set)
            if not it_fit.any():
                self.result.limit_constrained = True
                break
            # size the fill from the LIMIT-FILTERED set: per_node came from
            # the unfiltered max-capacity type, which limits may have
            # excluded — overfilling would prune the cohort's options empty
            per_fit = min(per_node, int(self.t.ppn[g, m][it_fit].max()))
            if per_fit <= 0:
                break
            fill = min(per_fit, n_pods - placed)
            # append BEFORE consuming limits: a fill-sizing failure must not
            # leak a phantom node's worth of limit capacity (subtractMax
            # models only nodes that actually open, scheduler.go:388-405)
            if not self._append_cohort(g, m, zone, it_fit, fill, cohort_enc,
                                       n=1):
                break
            self._subtract_max(m, it_fit)
            placed += fill
        return placed

    def _under_limits(self, m: int, it_set: np.ndarray) -> np.ndarray:
        limits = self.template_limits[m]
        ok = np.ones(self.T, dtype=bool)
        for rname in self.limit_resources:
            if rname not in limits:
                continue  # this pool doesn't limit rname (limits.ExceededBy)
            ridx = self.p.vocab.resource_idx.get(rname)
            if ridx is None:
                continue
            ok &= self.p.it_capacity[:, ridx] <= limits[rname]
        return ok

    def _subtract_max(self, m: int, it_set: np.ndarray) -> None:
        """subtractMax pessimism (scheduler.go:388-405)."""
        limits = self.template_limits[m]
        for rname in list(limits):
            ridx = self.p.vocab.resource_idx.get(rname)
            if ridx is None:
                continue
            limits[rname] = limits[rname] - int(self.p.it_capacity[it_set, ridx].max())

    def _node_enc(self, g: int, m: int, zone: Optional[int]) -> EncodedRequirements:
        e = np_combine(_row(self.p.template_enc, m), _row(self.p.group_enc, g))
        if zone is not None:
            e = np_combine(e, self._zone_enc(zone))
        return e

    def _zone_enc(self, zone: int) -> EncodedRequirements:
        K, W = self.p.group_enc.mask.shape[1:]
        mask = np.full((K, W), 0xFFFFFFFF, dtype=np.uint32)
        defined = np.zeros(K, dtype=bool)
        complement = np.ones(K, dtype=bool)
        exempt = np.zeros(K, dtype=bool)
        zk = self.p.zone_key
        row = np.zeros(W, dtype=np.uint32)
        vi = int(self.p.zone_values[zone])
        row[vi // 32] |= np.uint32(1 << (vi % 32))
        mask[zk] = row
        defined[zk] = True
        complement[zk] = False
        return EncodedRequirements(mask=mask, defined=defined, complement=complement,
                                   exempt=exempt,
                                   gt=np.full(K, -2**31, dtype=np.int64),
                                   lt=np.full(K, 2**31 - 1, dtype=np.int64))

    def _adjusted_alloc(self, m: int) -> np.ndarray:
        """[T, R] allocatable minus template m's daemon overhead, memoized
        (pure function of m; _commit_to_cohort sits on the remainder hot
        path)."""
        out = self._madj_cache.get(m)
        if out is None:
            out = self.p.it_alloc - self.p.daemon_overhead[m]
            self._madj_cache[m] = out
        return out

    def _fits_requests(self, m: int, requests: np.ndarray) -> np.ndarray:
        """[T] bool: instance types whose daemon-adjusted allocatable holds
        the cumulative request vector — the tensor twin of the per-pod
        instance-type refiltering (nodeclaim.go:108-117): an IT that fit the
        first pod must leave the set once the accumulated load outgrows it,
        or downstream consumers (price ordering, the consolidation price
        filter, the provider's cheapest-offering pick) see phantom options."""
        return (self._adjusted_alloc(m) >= requests).all(axis=1)

    def _append_cohort(self, g: int, m: int, zone: Optional[int],
                       it_set: np.ndarray, fill: int,
                       cohort_enc: EncodedRequirements, n: int = 1) -> bool:
        """Returns False (placing nothing) when the fill-sizing invariant is
        violated — the fill outgrew every surviving instance type. Callers
        treat that as 0 pods placed, so the group's remainder flows to the
        normal unplaced-pods error path instead of an assert crashing the
        whole batch (and `python -O` silently materializing an empty
        it_set)."""
        req = self.p.group_req[g] * fill
        it_set = it_set & self._fits_requests(m, req)
        if not it_set.any():
            return False
        self.result.cohorts.append(Cohort(
            m=m, zone=zone, it_set=it_set.copy(), requests=req.copy(), n=n,
            enc=cohort_enc, pods_by_group={g: fill}))
        return True

    def _cohort_capacity(self, g: int, cohort: Cohort,
                         zone_override: Optional[int] = None,
                         extra_mask: Optional[np.ndarray] = None
                         ) -> Tuple[int, np.ndarray]:
        """Max additional pods of group g per cohort node + surviving it set.
        Negative free capacity floors the per-IT min below zero, which the
        callers' cap<=0 check treats identically to the old clamp-to-zero.
        zone_override/extra_mask evaluate a PROSPECTIVE zone commitment of a
        zone-free cohort (see _fill_cohorts) without mutating it."""
        zone = cohort.zone if zone_override is None else zone_override
        it_ok = (self.t.it_ok_z[g, cohort.m, :, zone] if zone is not None
                 else self.t.it_ok[g, cohort.m])
        ts = cohort.it_set & it_ok
        if extra_mask is not None:
            ts = ts & extra_mask
        if not ts.any():
            return 0, ts
        nz = self._req_nz[g]
        if nz.size == 0:
            return INT32_MAX, ts
        per = ((self._alloc_nz(cohort.m, g) - cohort.requests[nz])
               // self._req_vals[g]).min(axis=1)
        return int(per[ts].max()), ts

    def _fill_cohorts(self, g: int, remaining: int, zone: Optional[int],
                      per_node_cap: int) -> int:
        """Mix pods of g into compatible existing cohorts (the reference's
        fewest-pods-first in-flight node pass, scheduler.go:276-283)."""
        if remaining <= 0:
            return 0
        allow = self.p.allow_undefined
        cohorts = self.result.cohorts
        fills = [sum(c.pods_by_group.values()) for c in cohorts]
        order = sorted(range(len(cohorts)), key=fills.__getitem__)
        placed_total = 0
        for ci in order:
            if remaining <= 0:
                break
            cohort = self.result.cohorts[ci]
            commit_zone = False
            extra_mask = None
            if zone is not None and cohort.zone != zone:
                if cohort.zone is not None:
                    continue
                # zone-free cohort: a zonal pod joining an in-flight claim
                # NARROWS the claim to its zone in the host scheduler
                # (nodeclaim.go Add intersects requirements) — mirror that
                # by committing the cohort to this zone, provided every
                # group already aboard stays feasible there
                extra_mask = np.ones_like(cohort.it_set)
                ok = True
                for gp in cohort.pods_by_group:
                    if not self.t.zone_adm[gp, cohort.m, zone]:
                        ok = False
                        break
                    extra_mask = extra_mask & \
                        self.t.it_ok_z[gp, cohort.m, :, zone]
                if not ok:
                    continue
                commit_zone = True
            if zone is None and cohort.zone is not None:
                # group must admit the cohort's zone; np_compatible handles it
                pass
            if not self.t.compat_tm[cohort.m, g] or not self.p.tol_template[g, cohort.m]:
                continue
            if not np_compatible(cohort.enc, _row(self.p.group_enc, g), allow):
                continue
            if self._port_conflict is not None and any(
                    self._port_conflict[g, gp]
                    for gp in cohort.pods_by_group):
                continue  # a conflicting host port is already bound aboard
            cap, ts = self._cohort_capacity(
                g, cohort, zone_override=zone if commit_zone else None,
                extra_mask=extra_mask)
            if per_node_cap:
                existing_fill = cohort.pods_by_group.get(g, 0)
                cap = min(cap, max(0, per_node_cap - existing_fill))
            if cap <= 0:
                continue
            # fill each node of the cohort up to cap; split if not all consumed
            fill_nodes = min(cohort.n, -(-remaining // cap))
            if fill_nodes < cohort.n:
                # the UNFILLED nodes keep the cohort's original zone state:
                # only nodes actually receiving zonal pods narrow their zone
                rest = Cohort(m=cohort.m, zone=cohort.zone, it_set=cohort.it_set.copy(),
                              requests=cohort.requests.copy(), n=cohort.n - fill_nodes,
                              enc=cohort.enc, pods_by_group=dict(cohort.pods_by_group))
                cohort.n = fill_nodes
                self.result.cohorts.append(rest)
            # take at most cap per node: when demand exceeds the cohort's
            # total capacity (remaining > cap * n), every node takes exactly
            # cap and the leftover moves on — per_last derived from the raw
            # remaining overfilled the last node past the per-node cap
            # (e.g. 14 hostname-spread pods on one node at maxSkew=1)
            take = min(remaining, cap * fill_nodes)
            per_last = take - cap * (fill_nodes - 1)
            if per_last != cap and fill_nodes > 1:
                # last node takes the remainder; split it off
                last = Cohort(m=cohort.m, zone=cohort.zone, it_set=cohort.it_set.copy(),
                              requests=cohort.requests.copy(), n=1,
                              enc=cohort.enc, pods_by_group=dict(cohort.pods_by_group))
                cohort.n = fill_nodes - 1
                self.result.cohorts.append(last)
                if commit_zone:
                    self._commit_cohort_zone(cohort, zone)
                    self._commit_cohort_zone(last, zone)
                self._commit_to_cohort(last, g, per_last, ts)
                self._commit_to_cohort(cohort, g, cap, ts)
                placed = take
            else:
                fill = per_last if fill_nodes == 1 else cap
                if commit_zone:
                    self._commit_cohort_zone(cohort, zone)
                self._commit_to_cohort(cohort, g, fill, ts)
                placed = fill * fill_nodes
            placed_total += placed
            remaining -= placed
        return placed_total

    def _commit_cohort_zone(self, cohort: Cohort, zone: int) -> None:
        """Pin a zone-free cohort to a zone: both the zone field AND the
        encoded requirements narrow (the enc drives offering admission in
        price ordering and keys the materialize order-cache — a stale
        all-zones enc would rank unreachable offerings and collide cache
        entries across differently-pinned cohorts)."""
        cohort.zone = zone
        cohort.enc = np_combine(cohort.enc, self._zone_enc(zone))

    def _commit_to_cohort(self, cohort: Cohort, g: int, fill: int, ts: np.ndarray):
        cohort.requests = cohort.requests + self.p.group_req[g] * fill
        cohort.it_set = ts & self._fits_requests(cohort.m, cohort.requests)
        cohort.pods_by_group[g] = cohort.pods_by_group.get(g, 0) + fill
        cohort.enc = np_combine(cohort.enc, _row(self.p.group_enc, g))

    def _fill_existing(self, g: int, remaining: int, zone: Optional[int],
                       per_node_cap: int,
                       node_caps: Optional[np.ndarray] = None,
                       max_nodes: int = 0) -> int:
        """Pack into live nodes. node_caps[n] (when given) hard-caps each
        node individually — the hostname-topology cap derived from already-
        scheduled matching pods (0 = excluded); max_nodes > 0 limits how many
        distinct nodes may be used (hostname pod affinity: all on one)."""
        placed_total = 0
        used_nodes = 0
        for n in self.exist_order:
            if remaining <= 0:
                break
            if max_nodes and used_nodes >= max_nodes:
                break
            if not self.t.exist_ok[g, n]:
                continue
            if zone is not None and (self.p.exist_zone is None
                                     or self.p.exist_zone[n] != zone):
                continue
            req = self.p.group_req[g]
            with np.errstate(divide="ignore"):
                per = np.where(req > 0, self.exist_avail[n] // np.maximum(req, 1),
                               INT32_MAX)
            cap = int(per.min()) if per.size else 0
            if per_node_cap:
                cap = min(cap, per_node_cap)
            if node_caps is not None:
                cap = min(cap, int(node_caps[n]))
            vol_counts = (self.vol_group_counts[g]
                          if self.vol_group_counts is not None else None)
            vol_rem = None
            if vol_counts:
                vol_rem = (self.vol_node_remaining[n]
                           if self.vol_node_remaining is not None
                           and n < len(self.vol_node_remaining) else None)
                if vol_rem:
                    cap = min(cap, min(
                        (vol_rem[d] // c for d, c in vol_counts.items()
                         if d in vol_rem), default=INT32_MAX))
            fill = min(cap, remaining)
            if fill <= 0:
                continue
            if vol_counts and vol_rem:
                for d, c in vol_counts.items():
                    if d in vol_rem:
                        vol_rem[d] -= c * fill
            self.exist_avail[n] = self.exist_avail[n] - req * fill
            self.result.existing.setdefault(n, []).append((g, fill))
            placed_total += fill
            remaining -= fill
            used_nodes += 1
        return placed_total

    # -- main ---------------------------------------------------------------

    def pack(self) -> PackResult:
        cpu_idx = self.p.vocab.resource_idx.get("cpu", 0)
        mem_idx = self.p.vocab.resource_idx.get("memory", 0)
        order = sorted(range(self.G), key=lambda g: (
            -self.p.group_req[g][cpu_idx], -self.p.group_req[g][mem_idx]))
        for g in order:
            self._pack_group(g)
        return self.result

    def _error_group(self, g: int, count: int, msg: str) -> None:
        pods = self.groups[g].pods
        start = len(pods) - count
        for pod in pods[start:]:
            self.result.errors[pod.uid] = msg

    def _host_caps(self, g: int, host_spec) -> Tuple[int, Optional[np.ndarray]]:
        """Per-fresh-node cap (0 = unlimited) and per-existing-node caps from
        the group's hostname-level constraint. Self-selecting constraints
        budget against already-scheduled matching pods per node
        (exist_counts); non-self constraints never budget batch pods (they
        don't match the selector) — they only admit or exclude nodes by their
        static matching counts (topologygroup.go:181-227, 316-342 with the
        hostname global-min floored at 0, :232-234)."""
        if host_spec is None:
            return 0, None
        N = self.exist_avail.shape[0]
        cnt = (self.exist_counts[g] if self.exist_counts is not None
               else np.zeros(N, dtype=np.int64))
        if host_spec.kind == "spread-host":
            skew = host_spec.max_skew
            if host_spec.self_select:
                return skew, np.maximum(0, skew - cnt)
            return 0, np.where(cnt > skew, 0, INT32_MAX)
        # anti-host
        if host_spec.self_select:
            return 1, np.where(cnt > 0, 0, 1)
        return 0, np.where(cnt > 0, 0, INT32_MAX)

    def _apply_port_caps(self, g: int, per_node_cap: int,
                         node_caps: Optional[np.ndarray]
                         ) -> Tuple[int, Optional[np.ndarray]]:
        """Identical host-port specs all conflict pairwise, so a port group
        holds at most ONE pod per node (fresh or existing), and nodes whose
        current pods already bind a conflicting port are out entirely."""
        if not self.group_ports or not self.group_ports[g]:
            return per_node_cap, node_caps
        per_node_cap = 1 if per_node_cap == 0 else min(per_node_cap, 1)
        caps = np.ones(self.exist_avail.shape[0], dtype=np.int64)
        if self.exist_port_block is not None:
            # the block covers the REAL nodes; exist_avail may be padded
            blocked = np.nonzero(self.exist_port_block[g])[0]
            caps[blocked] = 0
        # ports bound onto existing nodes EARLIER IN THIS PACK (the
        # pre-solve block can't know them): any conflicting group already
        # placed on a node takes that node out (scheduler.py:329 semantics
        # — the oracle updates usage per placement)
        if self._port_conflict is not None:
            for n, fills in self.result.existing.items():
                for g2, _fill in fills:
                    if self._port_conflict[g, g2]:
                        caps[n] = 0
                        break
        if node_caps is not None:
            caps = np.minimum(caps, node_caps)
        return per_node_cap, caps

    def _pack_group(self, g: int) -> None:
        group = self.groups[g]
        c = group.count
        if c == 0:
            return
        specs = group.topo or []
        zone_spec = next((s for s in specs
                          if s.kind in ("spread-zone", "affinity-zone",
                                        "anti-zone")), None)
        host_spec = next((s for s in specs
                          if s.kind in ("spread-host", "anti-host",
                                        "affinity-host")), None)

        if host_spec is not None and host_spec.kind == "affinity-host":
            self._pack_affinity_host(g, c)  # always alone (grouping)
            return
        per_node_cap, node_caps = self._host_caps(g, host_spec)
        per_node_cap, node_caps = self._apply_port_caps(g, per_node_cap,
                                                        node_caps)

        if zone_spec is None:
            placed = self._fill_existing(g, c, None, per_node_cap, node_caps)
            placed += self._fill_cohorts(g, c - placed, None, per_node_cap)
            placed += self._place_new(g, c - placed, None, per_node_cap)
            if placed < c:
                msg = "no instance type satisfied the pod"
                if host_spec is not None:
                    msg = ("unsatisfiable hostname topology spread"
                           if host_spec.kind == "spread-host"
                           else "unsatisfiable hostname anti-affinity")
                self._error_group(g, c - placed, msg)
        elif zone_spec.kind == "spread-zone":
            if zone_spec.self_select:
                self._pack_spread_zone(g, c, zone_spec, per_node_cap, node_caps)
            else:
                self._pack_spread_zone_static(g, c, zone_spec, per_node_cap,
                                              node_caps)
        elif zone_spec.kind == "affinity-zone":
            self._pack_affinity_zone(g, c, zone_spec, per_node_cap, node_caps)
        else:  # anti-zone (always alone among zone kinds)
            self._pack_anti_zone(g, c, zone_spec, per_node_cap, node_caps)

    def _place_new(self, g: int, remaining: int, zone: Optional[int],
                   per_node_cap: int) -> int:
        if remaining <= 0:
            return 0
        placed = 0
        for m in range(self.M):
            if remaining - placed <= 0:
                break
            ppn_all = self.t.ppn[g, m]
            it_ok = (self.t.it_ok_z[g, m, :, zone] if zone is not None
                     else self.t.it_ok[g, m])
            if not it_ok.any():
                continue
            per = int(ppn_all[it_ok].max())
            if per_node_cap:
                per = min(per, per_node_cap)
            placed += self._open_nodes(g, m, zone, remaining - placed, per)
        return placed

    def _place_one_node(self, g: int, c: int) -> int:
        for m in range(self.M):
            it_ok = self.t.it_ok[g, m]
            if not it_ok.any():
                continue
            limits = self.template_limits[m]
            limit_pruned = False
            if limits is not None:
                it_fit = it_ok & self._under_limits(m, it_ok)
                if not it_fit.any():
                    self.result.limit_constrained = True
                    continue
                limit_pruned = bool((it_fit != it_ok).any())
                it_ok = it_fit
            # fill sized from the (limit-filtered) surviving set
            per = int(self.t.ppn[g, m][it_ok].max())
            fill = min(per, c)
            if fill <= 0:
                if limit_pruned:
                    # the surviving (smaller) types hold zero pods: this
                    # failure exists only because limits pruned the big
                    # ones — not an oracle-final verdict
                    self.result.limit_constrained = True
                continue
            if not self._append_cohort(g, m, None, it_ok, fill,
                                       self._node_enc(g, m, None)):
                continue
            if limits is not None:
                self._subtract_max(m, it_ok)
            return fill
        return 0

    def _zone_admitted_viable(self, g: int) -> Tuple[np.ndarray, np.ndarray]:
        # admitted zones: group+any template admits; viable: some IT offering
        admitted = np.zeros(self.Z, dtype=bool)
        viable = np.zeros(self.Z, dtype=bool)
        for m in self._viable_templates(g):
            admitted |= self.t.zone_adm[g, m]
            viable |= self.t.it_ok_z[g, m].any(axis=0)
        return admitted, viable

    def _zone_min_mask(self, g: int) -> np.ndarray:
        """The pod's view of the domain universe for global-min/minDomains
        arithmetic (topologygroup.go:229-250): every registered domain the
        POD's own requirements admit. The universe spans ALL templates'
        admitted zones — including templates the group can't actually use
        (tainted pools, incompatible requirements): a zero-count zone behind
        an intolerable taint still pins the reference's global min at 0 —
        plus zones holding recorded cluster pods (izc) that no template
        reaches at all."""
        greq = self.groups[g].requirements.get(api_labels.LABEL_TOPOLOGY_ZONE)
        pod_admits = np.fromiter((greq.has(z) for z in self._zone_names),
                                 dtype=bool, count=self.Z)
        # zone_adm[g, m] is already pod-side-intersected (combined reqs)
        return self.t.zone_adm[g].any(axis=0) | \
            (pod_admits & (self.zone_counts[g] > 0))

    def _fill_zone(self, g: int, a: int, z: int, per_node_cap: int,
                   node_caps: Optional[np.ndarray]) -> int:
        placed = self._fill_existing(g, a, z, per_node_cap, node_caps)
        placed += self._fill_cohorts(g, a - placed, z, per_node_cap)
        placed += self._place_new(g, a - placed, z, per_node_cap)
        return placed

    def _pack_spread_zone(self, g: int, c: int, spec, per_node_cap: int = 0,
                          node_caps: Optional[np.ndarray] = None) -> None:
        admitted, viable = self._zone_admitted_viable(g)
        if not admitted.any():
            self._error_group(g, c, "no zone admitted for topology spread")
            return
        alloc = waterfill(self.zone_counts[g], viable, admitted, c,
                          spec.max_skew, spec.min_domains,
                          zone_names=self._zone_names,
                          min_mask=self._zone_min_mask(g))
        placed_total = 0
        for z in np.argsort(-alloc):
            a = int(alloc[z])
            if a <= 0:
                continue
            placed = self._fill_zone(g, a, int(z), per_node_cap, node_caps)
            self.zone_counts[g, z] += placed
            placed_total += placed
        if placed_total < c:
            self._error_group(g, c - placed_total, "unsatisfiable zonal topology spread")

    def _pack_spread_zone_static(self, g: int, c: int, spec,
                                 per_node_cap: int,
                                 node_caps: Optional[np.ndarray]) -> None:
        """Non-self-selecting zonal spread: placing batch pods never changes
        the domain counts, so the skew arithmetic is static. Existing nodes
        in any skew-eligible zone may take pods; fresh nodes all commit to
        the min-count eligible zone, exactly the domain nextDomain would
        return for an unconstrained node (topologygroup.go:181-227)."""
        admitted, viable = self._zone_admitted_viable(g)
        if not admitted.any():
            self._error_group(g, c, "no zone admitted for topology spread")
            return
        counts = self.zone_counts[g]
        min_mask = self._zone_min_mask(g)
        floor_zero = (spec.min_domains is not None
                      and int(min_mask.sum()) < spec.min_domains)
        gmin = 0 if floor_zero else (int(counts[min_mask].min())
                                     if min_mask.any() else 0)
        eligible = admitted & (counts - gmin <= spec.max_skew)
        if not eligible.any():
            self._error_group(g, c, "unsatisfiable zonal topology spread")
            return
        placed = 0
        for z in np.where(eligible)[0]:
            if placed >= c:
                break
            placed += self._fill_existing(g, c - placed, int(z),
                                          per_node_cap, node_caps)
        fresh = eligible & viable
        if placed < c and fresh.any():
            cand = np.where(fresh)[0]
            z = int(cand[np.lexsort((self._zone_names[cand],
                                     counts[cand]))[0]])
            placed += self._fill_cohorts(g, c - placed, z, per_node_cap)
            placed += self._place_new(g, c - placed, z, per_node_cap)
        if placed < c:
            self._error_group(g, c - placed, "unsatisfiable zonal topology spread")

    def _pack_affinity_zone(self, g: int, c: int, spec, per_node_cap: int = 0,
                            node_caps: Optional[np.ndarray] = None) -> None:
        admitted, viable = self._zone_admitted_viable(g)
        counts = self.zone_counts[g]
        # occupancy is judged through the POD's domain view: a matching pod
        # in a zone no template reaches still blocks the bootstrap
        # (nextDomainAffinity returns empty options, not a fresh domain)
        occupied = (counts > 0) & self._zone_min_mask(g)
        if occupied.any():
            occupied &= admitted
            # pods must join an occupied domain (topologygroup.go:253-300);
            # if none of those domains has a viable instance type the pods
            # fail — there is NO bootstrap while matching pods exist
            candidates = np.where(occupied & viable)[0]
            if len(candidates) == 0:
                self._error_group(
                    g, c, "zonal pod affinity: no viable occupied zone")
                return
        elif not spec.self_select:
            # non-self affinity can never self-satisfy (the bootstrap at
            # topologygroup.go:283-287 requires the pod to match its own
            # selector): nothing matches anywhere -> unschedulable
            self._error_group(
                g, c, "zonal pod affinity: no pods match the affinity selector")
            return
        else:
            candidates = np.where(viable)[0]
            if len(candidates) == 0:
                self._error_group(g, c, "no viable zone for zonal pod affinity")
                return
        # host-parity tie-break: first domain by NAME (the oracle's affinity
        # bootstrap iterates sorted(self.domains)), not by vocab index
        z = int(min(candidates, key=self._zone_names.__getitem__))
        placed = self._fill_zone(g, c, z, per_node_cap, node_caps)
        self.zone_counts[g, z] += placed
        if placed < c:
            self._error_group(g, c - placed, "zonal pod affinity: zone capacity exhausted")

    def _pack_anti_zone(self, g: int, c: int, spec,
                        per_node_cap: int = 0,
                        node_caps: Optional[np.ndarray] = None) -> None:
        """Zonal anti-affinity: pods may only land in EMPTY domains
        (topologygroup.go:316-342). Self-selecting: each placement occupies a
        zone, and peers in the same batch are mutually excluded but not yet
        recorded — late committal places one pod per batch
        (topology_test.go:2150-2176). Non-self: batch pods never occupy
        domains, so every pod can go to any statically-empty zone."""
        admitted, viable = self._zone_admitted_viable(g)
        counts = self.zone_counts[g]
        empty = admitted & (counts == 0)
        if spec.self_select:
            placed = 0
            for z in np.where(empty)[0]:
                placed = self._fill_zone(g, 1, int(z), per_node_cap, node_caps)
                if placed:
                    self.zone_counts[g, z] += 1
                    break
            if placed < 1:
                self._error_group(g, c, "unsatisfiable zonal anti-affinity")
            elif c > 1:
                self._error_group(
                    g, c - 1, "zonal anti-affinity: domain undetermined until next batch")
            return
        placed = 0
        for z in np.where(empty)[0]:
            if placed >= c:
                break
            placed += self._fill_zone(g, c - placed, int(z), per_node_cap,
                                      node_caps)
        if placed < c:
            self._error_group(g, c - placed, "unsatisfiable zonal anti-affinity")

    def _pack_affinity_host(self, g: int, c: int) -> None:
        """Hostname pod affinity (self-selecting; grouping keeps non-self on
        the host path). With matching pods already scheduled, the batch must
        join their nodes (no bootstrap, topologygroup.go:253-287); otherwise
        the hostname domain is fixed by the first placement, so everything
        lands on ONE node and overflow is unschedulable."""
        total = (int(self.host_match_total[g])
                 if self.host_match_total is not None else 0)
        if total > 0:
            cnt = (self.exist_counts[g] if self.exist_counts is not None
                   else np.zeros(self.exist_avail.shape[0], dtype=np.int64))
            node_caps = np.where(cnt > 0, INT32_MAX, 0)
            placed = self._fill_existing(g, c, None, 0, node_caps)
            if placed < c:
                self._error_group(
                    g, c - placed,
                    "hostname pod affinity: no co-located capacity")
            return
        placed = self._fill_existing(g, c, None, 0, None, max_nodes=1)
        if placed == 0:
            placed = self._place_one_node(g, c)
        if placed < c:
            self._error_group(g, c - placed,
                              "hostname pod affinity: node capacity exhausted")


def _row(e: EncodedRequirements, i: int) -> EncodedRequirements:
    return EncodedRequirements(mask=e.mask[i], defined=e.defined[i],
                               complement=e.complement[i], exempt=e.exempt[i],
                               gt=e.gt[i], lt=e.lt[i])
