"""Tensor encoding of the constraint algebra.

The host-side ``scheduling.Requirement`` set-or-complement algebra (reference:
pkg/scheduling/requirement.go) is lowered onto fixed-shape arrays:

- A label-key vocabulary of K keys; per key, a value vocabulary of up to D
  values plus one OTHER slot standing for "any value outside the vocab".
  Complement sets (NotIn/Exists/Gt/Lt) include the OTHER bit, which makes
  mask-AND an *exact* implementation of Requirement.Intersection emptiness
  because every concrete value ever compared appears in the vocab.
- Masks are bitpacked into uint32 words: mask[K, W] with W = ceil((D+1)/32).
  Intersection = bitwise AND; emptiness = all words zero.
- Gt/Lt integer bounds ride along as per-key int32 columns; the joint-bound
  crossing rule (requirement.go:163-165: max(gt) >= min(lt) collapses the
  intersection to DoesNotExist) is applied on top of the mask AND, which makes
  bound handling exact as well (known in-vocab values are pre-filtered per side).
- Per key we track defined / complement / exempt (operator in {NotIn,
  DoesNotExist}) flags to reproduce Requirements.Intersects/Compatible corner
  cases (requirements.go:283-304,175-187).

Resources are scaled to int32: cpu -> millicores, memory/storage -> MiB
(requests rounded up, capacity rounded down — conservative in the fit
direction), everything else -> whole units rounded the same way.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..api import labels as api_labels
from ..scheduling.requirement import Requirement
from ..scheduling.requirements import Requirements

INT_MIN = -(2**31)
INT_MAX = 2**31 - 1

MIB = 1024 * 1024

# Per-resource int32 scaling: milli stays for cpu-like, MiB for byte-like.
_BYTE_RESOURCES = ("memory", "ephemeral-storage", "storage")


def scale_request(name: str, milli: int) -> int:
    """Round UP: a request must not shrink when quantized."""
    if name in _BYTE_RESOURCES:
        return -((-milli) // (MIB * 1000))  # milli-bytes -> MiB, ceil
    return milli  # already integer milli


def scale_capacity(name: str, milli: int) -> int:
    """Round DOWN: capacity must not grow when quantized."""
    if name in _BYTE_RESOURCES:
        return milli // (MIB * 1000)
    return milli


class Vocab:
    """Label-key/value vocabulary shared by all encoded entities in one solve."""

    def __init__(self):
        self.keys: List[str] = []
        self.key_idx: Dict[str, int] = {}
        self.values: List[List[str]] = []
        self.value_idx: List[Dict[str, int]] = []
        self.resources: List[str] = []
        self.resource_idx: Dict[str, int] = {}
        self._frozen = False

    def add_key(self, key: str) -> int:
        key = api_labels.NORMALIZED_LABELS.get(key, key)
        if key in self.key_idx:
            return self.key_idx[key]
        assert not self._frozen, f"vocab frozen; unknown key {key}"
        idx = len(self.keys)
        self.keys.append(key)
        self.key_idx[key] = idx
        self.values.append([])
        self.value_idx.append({})
        return idx

    def add_value(self, key: str, value: str) -> int:
        k = self.add_key(key)
        vi = self.value_idx[k]
        if value in vi:
            return vi[value]
        assert not self._frozen, f"vocab frozen; unknown value {key}={value}"
        idx = len(self.values[k])
        self.values[k].append(value)
        vi[value] = idx
        return idx

    def add_resource(self, name: str) -> int:
        if name in self.resource_idx:
            return self.resource_idx[name]
        assert not self._frozen
        idx = len(self.resources)
        self.resources.append(name)
        self.resource_idx[name] = idx
        return idx

    def observe_requirements(self, reqs: Requirements) -> None:
        for key in reqs:
            r = reqs.get(key)
            self.add_key(key)
            for v in sorted(r.values):
                self.add_value(key, v)

    def observe_resources(self, rl: dict) -> None:
        for name in rl:
            self.add_resource(name)

    def freeze(self, domain_bucket: Optional[int] = None) -> None:
        """domain_bucket rounds the mask domain width up to a multiple, so
        solves whose value counts differ only within a bucket share jit
        shapes (SURVEY.md §7 'bucketed padding and recompile management')."""
        self._frozen = True
        self._domain_bucket = domain_bucket

    @property
    def K(self) -> int:
        return len(self.keys)

    @property
    def D(self) -> int:
        """Padded per-key domain width including the OTHER slot."""
        d = (max((len(v) for v in self.values), default=0)) + 1
        bucket = getattr(self, "_domain_bucket", None)
        if bucket:
            d = -(-d // bucket) * bucket
        return d

    @property
    def W(self) -> int:
        return (self.D + 31) // 32

    @property
    def R(self) -> int:
        return len(self.resources)

    def other_bit(self, k: int) -> int:
        """The OTHER slot index for key k (just past its concrete values)."""
        return len(self.values[k])


@dataclass
class EncodedRequirements:
    """One entity's requirement set in tensor form. Rows stack into batches."""
    mask: np.ndarray        # uint32 [K, W]
    defined: np.ndarray     # bool [K]
    complement: np.ndarray  # bool [K]
    exempt: np.ndarray      # bool [K]  (operator in {NotIn, DoesNotExist})
    gt: np.ndarray          # int32 [K] (INT_MIN when unset)
    lt: np.ndarray          # int32 [K] (INT_MAX when unset)


def _int_or_none(s: str):
    try:
        return int(s)
    except (TypeError, ValueError):
        return None


def encode_requirements(vocab: Vocab, reqs: Requirements) -> EncodedRequirements:
    K, W = vocab.K, vocab.W
    mask = np.zeros((K, W), dtype=np.uint32)
    defined = np.zeros(K, dtype=bool)
    complement = np.ones(K, dtype=bool)  # undefined == Exists
    exempt = np.zeros(K, dtype=bool)
    gt = np.full(K, INT_MIN, dtype=np.int64)
    lt = np.full(K, INT_MAX, dtype=np.int64)

    # undefined keys behave as Exists: every bit set (incl. OTHER)
    mask[:, :] = 0xFFFFFFFF
    _trim_tail_bits(vocab, mask)

    for key in reqs:
        r = reqs.get(key)
        k = vocab.key_idx[api_labels.NORMALIZED_LABELS.get(key, key)]
        defined[k] = True
        complement[k] = r.complement
        op = r.operator()
        exempt[k] = op in ("NotIn", "DoesNotExist")
        if r.greater_than is not None:
            gt[k] = r.greater_than
        if r.less_than is not None:
            lt[k] = r.less_than
        row = np.zeros(W, dtype=np.uint32)
        if r.complement:
            # all known values except excluded, filtered by bounds; OTHER set
            # unless individually crossed (it never is at construction)
            for i, v in enumerate(vocab.values[k]):
                if v in r.values:
                    continue
                iv = _int_or_none(v)
                if r.greater_than is not None or r.less_than is not None:
                    if iv is None:
                        continue
                    if r.greater_than is not None and iv <= r.greater_than:
                        continue
                    if r.less_than is not None and iv >= r.less_than:
                        continue
                row[i // 32] |= np.uint32(1 << (i % 32))
            ob = vocab.other_bit(k)
            row[ob // 32] |= np.uint32(1 << (ob % 32))
        else:
            for v in r.values:
                i = vocab.value_idx[k].get(v)
                if i is not None:
                    row[i // 32] |= np.uint32(1 << (i % 32))
                # In-values outside the vocab can never match any other entity;
                # dropping them is exact because the vocab covers all entities
                # in the solve.
        mask[k] = row
    return EncodedRequirements(mask=mask, defined=defined, complement=complement,
                               exempt=exempt, gt=gt.astype(np.int64), lt=lt.astype(np.int64))


def _tail_mask(vocab: Vocab) -> np.ndarray:
    """[K, W] uint32 mask keeping bits up to each key's OTHER slot. Cached
    only on a frozen vocab: an unfrozen vocab can grow a key's value count
    without changing (K, W), which would silently zero the new OTHER bit."""
    if not vocab._frozen:
        return _build_tail_mask(vocab)
    cached = getattr(vocab, "_tail_mask", None)
    if cached is not None and cached.shape == (vocab.K, vocab.W):
        return cached
    mask = _build_tail_mask(vocab)
    vocab._tail_mask = mask
    return mask


def _build_tail_mask(vocab: Vocab) -> np.ndarray:
    K, W = vocab.K, vocab.W
    ob = np.array([vocab.other_bit(k) for k in range(K)])[:, None]  # [K,1]
    lo = (np.arange(W) * 32)[None, :]                               # [1,W]
    keep = np.clip(ob + 1 - lo, 0, 32)
    full = np.uint32(0xFFFFFFFF)
    safe = np.minimum(keep, 31).astype(np.uint32)  # avoid UB shift by 32
    return np.where(keep >= 32, full,
                    (np.uint32(1) << safe) - np.uint32(1)).astype(np.uint32)


def _trim_tail_bits(vocab: Vocab, mask: np.ndarray) -> None:
    """Zero bits beyond each key's OTHER slot so popcounts stay meaningful."""
    mask &= _tail_mask(vocab)


def stack_encoded(items: Sequence[EncodedRequirements]) -> EncodedRequirements:
    return EncodedRequirements(
        mask=np.stack([e.mask for e in items]),
        defined=np.stack([e.defined for e in items]),
        complement=np.stack([e.complement for e in items]),
        exempt=np.stack([e.exempt for e in items]),
        gt=np.stack([e.gt for e in items]),
        lt=np.stack([e.lt for e in items]))


def pad_stacked(e: EncodedRequirements, total: int,
                zero: EncodedRequirements) -> EncodedRequirements:
    """Pad a stacked [B, ...] batch along axis 0 to ``total`` rows with
    copies of ``zero`` (an empty-Requirements row: defined nowhere, so a
    padded row never fails a compatibility check and never packs). The
    row-sliced delta encode uses this to keep the group/node batch axes on
    pow2 shape buckets so the compiled-executable cache keeps hitting."""
    n = e.mask.shape[0]
    if total <= n:
        return e

    def rep(name: str) -> np.ndarray:
        a = getattr(e, name)
        z = getattr(zero, name)
        return np.concatenate(
            [a, np.broadcast_to(z, (total - n,) + z.shape).copy()])

    return EncodedRequirements(
        mask=rep("mask"), defined=rep("defined"),
        complement=rep("complement"), exempt=rep("exempt"),
        gt=rep("gt"), lt=rep("lt"))


def shard_spans(total: int, shards: int) -> "list":
    """Contiguous equal [start, stop) row spans carving a stacked batch
    axis into ``shards`` blocks, or a single full span when the axis does
    not divide evenly (a pow2-bucketed axis always divides a pow2 shard
    count). Shared by the sharded ProblemState's per-shard exist tokens
    and the mesh placer's per-shard upload blocks, so the two sides can
    never disagree about which rows a shard owns."""
    if shards <= 1 or total % shards != 0:
        return [(0, total)]
    rows = total // shards
    return [(s * rows, (s + 1) * rows) for s in range(shards)]


def pow2_bucket(n: int, minimum: int) -> int:
    """Next power of two >= max(n, minimum): bounded distinct jit shapes.
    Shared by the group/node batch-axis buckets (tensor_scheduler) and the
    mesh's per-shard stack padding (parallel/mesh.pad_problem), so every
    padded axis in the system rounds the same way."""
    b = minimum
    while b < n:
        b *= 2
    return b


def pack_bits(a: np.ndarray) -> np.ndarray:
    """Little-endian bitpack of a bool array along its LAST axis:
    [..., Z] bool -> [..., ceil(Z/8)] uint8 with bit i of word w standing
    for position w*8+i. The packer's per-cohort zone-feasibility bitfield
    (ops/binpack.py CohortSet.okz) uses this layout; read single positions
    back with bit_column()."""
    return np.packbits(np.asarray(a, dtype=bool), axis=-1, bitorder="little")


def bit_column(packed: np.ndarray, i: int) -> np.ndarray:
    """Extract logical position ``i`` from a pack_bits() array -> bool
    with the last (word) axis dropped."""
    return (packed[..., i >> 3] >> (i & 7)) & 1 == 1


def encode_resource_vector(vocab: Vocab, rl: dict, *, capacity: bool) -> np.ndarray:
    out = np.zeros(vocab.R, dtype=np.int64)
    for name, milli in rl.items():
        idx = vocab.resource_idx.get(name)
        if idx is None:
            continue
        out[idx] = scale_capacity(name, milli) if capacity else scale_request(name, milli)
    return out
