"""karpenter_tpu: a TPU-native node-autoscaling framework with the capabilities
of sigs.k8s.io/karpenter.

The provisioning bin-packing solver and the disruption (consolidation) search —
the reference's two compute-heavy kernels — run as jit-compiled JAX tensor
programs on TPU (see karpenter_tpu.ops). The surrounding control plane (cluster
state, lifecycle, termination, budgets, observability) is a standalone Python
runtime over an in-memory watchable object store (see karpenter_tpu.controllers,
karpenter_tpu.operator).
"""

__version__ = "0.1.0"
