"""Event constructors for every controller flow.

The reference defines per-flow event packages; this module is their single
tpu-side catalog, one constructor per reference event:

- provisioning: /root/reference/pkg/controllers/provisioning/scheduling/
  events.go:34-62 (Nominated, FailedScheduling)
- disruption: /root/reference/pkg/controllers/disruption/events/
  events.go:31-140 (DisruptionLaunching, DisruptionWaitingReadiness,
  DisruptionTerminating, Unconsolidatable, DisruptionBlocked,
  NodePool budget blocks)
- termination: /root/reference/pkg/controllers/node/termination/terminator/
  events/events.go:30-77 (Evicted, Disrupted, FailedDraining,
  TerminationGracePeriodExpiring)
- lifecycle: /root/reference/pkg/controllers/nodeclaim/lifecycle/
  events.go:28-36 (InsufficientCapacityError)
- health: /root/reference/pkg/controllers/node/health/events.go:28-76
  (NodeRepairBlocked)

Messages follow the reference strings so operators migrating from the
reference can keep their event-based alerting.
"""

from __future__ import annotations

from typing import List, Optional

from .recorder import Event

NORMAL = "Normal"
WARNING = "Warning"

_MAX_MESSAGE = 700  # lifecycle/events.go truncateMessage bound


def _truncate(msg: str) -> str:
    if len(msg) <= _MAX_MESSAGE:
        return msg
    return msg[:_MAX_MESSAGE] + "..."


def _title(reason: str) -> str:
    """cases.Title(NoLower) analog: upper-case the first rune only."""
    return reason[:1].upper() + reason[1:] if reason else reason


# -- provisioning (scheduling/events.go) ------------------------------------

def nominate_pod(pod, node_name: str = "", nodeclaim_name: str = "") -> Event:
    """scheduling/events.go:34-50 NominatePodEvent."""
    info = []
    if nodeclaim_name:
        info.append(f"nodeclaim/{nodeclaim_name}")
    if node_name:
        info.append(f"node/{node_name}")
    return Event(
        object_kind="Pod", object_name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        type=NORMAL, reason="Nominated",
        message=f"Pod should schedule on: {', '.join(info)}",
        dedupe_values=(pod.uid,))


def pod_failed_to_schedule(pod, err: str) -> Event:
    """scheduling/events.go:52-61 PodFailedToScheduleEvent (5 min dedupe)."""
    return Event(
        object_kind="Pod", object_name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        type=WARNING, reason="FailedScheduling",
        message=f"Failed to schedule pod, {err}",
        dedupe_ttl=5 * 60.0, dedupe_values=(pod.uid,))


# -- disruption (disruption/events/events.go) --------------------------------

def disruption_launching(nodeclaim, reason: str) -> Event:
    """events.go:31-39 Launching."""
    return Event(
        object_kind="NodeClaim", object_name=nodeclaim.name,
        type=NORMAL, reason="DisruptionLaunching",
        message=f"Launching NodeClaim: {_title(reason)}",
        dedupe_values=(nodeclaim.name, reason))


def disruption_waiting_on_readiness(nodeclaim) -> Event:
    """events.go:41-48 WaitingOnReadiness."""
    return Event(
        object_kind="NodeClaim", object_name=nodeclaim.name,
        type=NORMAL, reason="DisruptionWaitingReadiness",
        message="Waiting on readiness to continue disruption",
        dedupe_values=(nodeclaim.name,))


def disruption_terminating(node_name: str, nodeclaim_name: str,
                           reason: str) -> List[Event]:
    """events.go:51-69 Terminating: one event on the Node, one on the
    NodeClaim."""
    return [
        Event(object_kind="Node", object_name=node_name,
              type=NORMAL, reason="DisruptionTerminating",
              message=f"Disrupting Node: {_title(reason)}",
              dedupe_values=(node_name, reason)),
        Event(object_kind="NodeClaim", object_name=nodeclaim_name,
              type=NORMAL, reason="DisruptionTerminating",
              message=f"Disrupting NodeClaim: {_title(reason)}",
              dedupe_values=(nodeclaim_name, reason)),
    ]


def unconsolidatable(node_name: str, nodeclaim_name: str,
                     reason: str) -> List[Event]:
    """events.go:73-92 Unconsolidatable (15 min dedupe)."""
    return [
        Event(object_kind="Node", object_name=node_name,
              type=NORMAL, reason="Unconsolidatable", message=reason,
              dedupe_ttl=15 * 60.0, dedupe_values=(node_name,)),
        Event(object_kind="NodeClaim", object_name=nodeclaim_name,
              type=NORMAL, reason="Unconsolidatable", message=reason,
              dedupe_ttl=15 * 60.0, dedupe_values=(nodeclaim_name,)),
    ]


def disruption_blocked(node_name: Optional[str],
                       nodeclaim_name: Optional[str],
                       reason: str) -> List[Event]:
    """events.go:96-116 Blocked."""
    evs = []
    if node_name:
        evs.append(Event(
            object_kind="Node", object_name=node_name,
            type=NORMAL, reason="DisruptionBlocked",
            message=f"Cannot disrupt Node: {reason}",
            dedupe_values=(node_name,)))
    if nodeclaim_name:
        evs.append(Event(
            object_kind="NodeClaim", object_name=nodeclaim_name,
            type=NORMAL, reason="DisruptionBlocked",
            message=f"Cannot disrupt NodeClaim: {reason}",
            dedupe_values=(nodeclaim_name,)))
    return evs


def nodepool_blocked_for_reason(nodepool_name: str, reason: str) -> Event:
    """events.go:118-127 NodePoolBlockedForDisruptionReason (1 min dedupe:
    budgets can change every minute)."""
    return Event(
        object_kind="NodePool", object_name=nodepool_name,
        type=NORMAL, reason="DisruptionBlocked",
        message=(f"No allowed disruptions for disruption reason {reason} "
                 "due to blocking budget"),
        dedupe_ttl=60.0, dedupe_values=(nodepool_name, reason))


def nodepool_blocked(nodepool_name: str) -> Event:
    """events.go:129-140 NodePoolBlocked (1 min dedupe)."""
    return Event(
        object_kind="NodePool", object_name=nodepool_name,
        type=NORMAL, reason="DisruptionBlocked",
        message="No allowed disruptions due to blocking budget",
        dedupe_ttl=60.0, dedupe_values=(nodepool_name,))


# -- termination (terminator/events/events.go) -------------------------------

def evict_pod(pod) -> Event:
    """events.go:30-38 EvictPod."""
    return Event(
        object_kind="Pod", object_name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        type=NORMAL, reason="Evicted", message="Evicted pod",
        dedupe_values=(pod.metadata.name,))


def disrupt_pod_delete(pod, grace_period_seconds, termination_time) -> Event:
    """events.go:40-48 DisruptPodDelete: forced delete when the node's
    terminationGracePeriod expires, bypassing PDBs + do-not-disrupt."""
    return Event(
        object_kind="Pod", object_name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        type=NORMAL, reason="Disrupted",
        message=(f"Deleting the pod to accommodate the terminationTime "
                 f"{termination_time} of the node. The pod was granted "
                 f"{grace_period_seconds} seconds of grace-period of its "
                 f"{pod.spec.termination_grace_period_seconds} "
                 "terminationGracePeriodSeconds. This bypasses the PDB of "
                 "the pod and the do-not-disrupt annotation."),
        dedupe_values=(pod.metadata.name,))


def node_failed_to_drain(node_name: str, err: str) -> Event:
    """events.go:50-58 NodeFailedToDrain."""
    return Event(
        object_kind="Node", object_name=node_name,
        type=WARNING, reason="FailedDraining",
        message=f"Failed to drain node, {err}",
        dedupe_values=(node_name,))


def node_tgp_expiring(node_name: str, termination_time: str) -> Event:
    """events.go:60-68 NodeTerminationGracePeriodExpiring."""
    return Event(
        object_kind="Node", object_name=node_name,
        type=WARNING, reason="TerminationGracePeriodExpiring",
        message=f"All pods will be deleted by {termination_time}",
        dedupe_values=(node_name,))


def nodeclaim_tgp_expiring(nodeclaim_name: str, termination_time: str) -> Event:
    """events.go:70-77 NodeClaimTerminationGracePeriodExpiring."""
    return Event(
        object_kind="NodeClaim", object_name=nodeclaim_name,
        type=WARNING, reason="TerminationGracePeriodExpiring",
        message=f"All pods will be deleted by {termination_time}",
        dedupe_values=(nodeclaim_name,))


# -- nodeclaim lifecycle (lifecycle/events.go) -------------------------------

def insufficient_capacity(nodeclaim, err: str) -> Event:
    """lifecycle/events.go:28-36 InsufficientCapacityErrorEvent."""
    return Event(
        object_kind="NodeClaim", object_name=nodeclaim.name,
        type=WARNING, reason="InsufficientCapacityError",
        message=f"NodeClaim {nodeclaim.name} event: {_truncate(err)}",
        dedupe_values=(nodeclaim.name,))


def registration_timeout(nodeclaim, ttl: float) -> Event:
    """Warning published when liveness deletes a claim that never
    registered within the TTL (liveness.go:41-66 deletes silently; a
    registration drought must be observable, not a disappearing claim)."""
    return Event(
        object_kind="NodeClaim", object_name=nodeclaim.name,
        type=WARNING, reason="FailedRegistration",
        message=(f"NodeClaim {nodeclaim.name} not registered within "
                 f"{int(ttl)}s, deleting"),
        dedupe_values=(nodeclaim.name,))


def offerings_exhausted(pod, detail: str) -> Event:
    """Warning published when every offering compatible with a pod is
    masked by the unavailable-offerings registry: the pod waits for the
    TTL (or fresh capacity), it is not hot-looped through doomed solves.
    Distinct reason from FailedScheduling so drought alerting can key on
    it; deduped per pod so the backoff requeues don't spam."""
    return Event(
        object_kind="Pod", object_name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        type=WARNING, reason="AllOfferingsUnavailable",
        message=("Failed to schedule pod, every compatible offering is "
                 f"marked unavailable: {_truncate(detail)}"),
        dedupe_ttl=5 * 60.0, dedupe_values=(pod.uid,))


# -- fault-tolerant runtime --------------------------------------------------

def reconcile_quarantined(kind: str, name: str, namespace: str,
                          controller: str, err: str) -> Event:
    """Warning published when the manager dead-letters a work item after
    exhausting its retry budget (no reference analog: controller-runtime
    retries forever; see DEVIATIONS.md)."""
    return Event(
        object_kind=kind, object_name=name, namespace=namespace,
        type=WARNING, reason="ReconcileQuarantined",
        message=(f"Quarantined after repeated reconcile failures in "
                 f"{controller}: {_truncate(err)}"),
        dedupe_values=(controller, name))


# -- SLO watcher (obs/slo.py) ------------------------------------------------

def slo_breached(slo: str, trace_id: str, duration: float, budget: float,
                 dump_path: str) -> Event:
    """Warning published when a pass trace exceeds a configured SLO budget
    (no reference analog). Deduped per breaching trace so a replayed
    observation can never double-publish; the message carries the
    flight-recorder dump path so the incident snapshot is one click away."""
    detail = f" (flight recorder: {dump_path})" if dump_path else ""
    return Event(
        object_kind="SLO", object_name=slo,
        type=WARNING, reason="SLOBreached",
        message=(f"Pass {trace_id} took {duration:.3f}s against the "
                 f"{budget:.3f}s {slo} budget{detail}"),
        dedupe_values=(slo, trace_id))


# -- node health (health/events.go) ------------------------------------------

def node_repair_blocked(node_name: str, nodeclaim_name: str,
                        reason: str) -> List[Event]:
    """health/events.go:28-76 NodeRepairBlocked (15 min dedupe). The
    reference emits both events with InvolvedObject=node (events.go:31,39 —
    the second differs only in dedupe key); one per object is the evident
    intent and what operators need. Bare nodes (no NodeClaim) publish the
    Node event only."""
    evs = [Event(object_kind="Node", object_name=node_name,
                 type=WARNING, reason="NodeRepairBlocked", message=reason,
                 dedupe_ttl=15 * 60.0, dedupe_values=(node_name,))]
    if nodeclaim_name:
        evs.append(Event(object_kind="NodeClaim", object_name=nodeclaim_name,
                         type=WARNING, reason="NodeRepairBlocked",
                         message=reason, dedupe_ttl=15 * 60.0,
                         dedupe_values=(nodeclaim_name,)))
    return evs


# -- warm-state integrity (state/audit.py, no reference analog) ---------------

def state_corruption(layer: str, detail: str, seq: int) -> Event:
    """The StateAuditor detected a corrupted warm-cache layer and
    quarantined it to a cold rebuild for the pass. No reference analog:
    the reference re-derives state every pass and has no warm caches to
    corrupt. The incident sequence number rides the dedupe key so every
    DISTINCT incident publishes exactly once — without it the recorder's
    TTL dedupe would swallow a second corruption of the same layer."""
    return Event(
        object_kind="EncodePlane", object_name=layer,
        type=WARNING, reason="StateCorruption",
        message=_truncate(
            f"Warm-state audit: corrupted {layer} quarantined to a cold "
            f"rebuild ({detail or 'content digest mismatch'})"),
        dedupe_values=(layer, str(seq)))
