"""Deduplicating event recorder.

Mirrors /root/reference/pkg/events/recorder.go:47-100: identical events
(involved object + reason + message) within the dedupe TTL are dropped; a
per-key rate limit (10 qps in the reference) bounds bursts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.clock import Clock

DEDUPE_TTL_SECONDS = 120.0   # recorder.go dedupeTimeout
RATE_LIMIT_QPS = 10.0


@dataclass
class Event:
    """events/events.go Event shape."""
    object_kind: str
    object_name: str
    type: str          # Normal | Warning
    reason: str
    message: str
    timestamp: float = 0.0

    def dedupe_key(self) -> str:
        return f"{self.object_kind}/{self.object_name}/{self.reason}/{self.message}"


class Recorder:
    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self.events: List[Event] = []
        self._last_seen: Dict[str, float] = {}
        self._bucket: Dict[str, List[float]] = {}

    def publish(self, *events: Event) -> None:
        now = self.clock.now()
        for ev in events:
            key = ev.dedupe_key()
            last = self._last_seen.get(key)
            if last is not None and now - last < DEDUPE_TTL_SECONDS:
                continue
            window = [t for t in self._bucket.get(key, []) if now - t < 1.0]
            if len(window) >= RATE_LIMIT_QPS:
                continue
            window.append(now)
            self._bucket[key] = window
            self._last_seen[key] = now
            ev.timestamp = now
            self.events.append(ev)

    def for_object(self, name: str) -> List[Event]:
        return [e for e in self.events if e.object_name == name]
