"""Deduplicating event recorder.

Mirrors /root/reference/pkg/events/recorder.go:47-100: identical events
(involved object + reason + message) within the dedupe TTL are dropped; a
per-key rate limit (10 qps in the reference) bounds bursts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..metrics.registry import EVENTS_DROPPED
from ..utils.clock import Clock

DEDUPE_TTL_SECONDS = 120.0   # recorder.go dedupeTimeout
RATE_LIMIT_QPS = 10.0


@dataclass
class Event:
    """events/events.go Event shape. ``dedupe_ttl`` overrides the default
    dedupe window (events.go DedupeTimeout; e.g. Unconsolidatable uses 15
    min, NodePool budget blocks 1 min). ``dedupe_values`` mirrors
    DedupeValues (recorder.go:74: the key is type+reason+values, NOT the
    message — a churning message like a shrinking pod count must still
    dedupe); when unset, the key falls back to the full identity including
    the message."""
    object_kind: str
    object_name: str
    type: str          # Normal | Warning
    reason: str
    message: str
    timestamp: float = 0.0
    namespace: str = ""
    dedupe_ttl: Optional[float] = None
    dedupe_values: tuple = ()

    def dedupe_key(self) -> str:
        if self.dedupe_values:
            return "/".join((self.type, self.reason, self.object_kind)
                            + tuple(self.dedupe_values))
        return (f"{self.object_kind}/{self.namespace}/{self.object_name}/"
                f"{self.reason}/{self.message}")


class Recorder:
    """``sink``, when set, receives every event that survives dedupe/rate
    limiting — the operator's kube backend uses it to POST real v1.Event
    objects through the apiserver adapter; sink errors are swallowed (event
    delivery is best-effort in the reference's client-go recorder too)."""

    def __init__(self, clock: Optional[Clock] = None, sink=None):
        self.clock = clock or Clock()
        self.sink = sink
        self.events: List[Event] = []
        self._last_seen: Dict[str, float] = {}
        self._bucket: Dict[str, List[float]] = {}

    def publish(self, *events: Event) -> None:
        now = self.clock.now()
        for ev in events:
            key = ev.dedupe_key()
            ttl = ev.dedupe_ttl if ev.dedupe_ttl is not None \
                else DEDUPE_TTL_SECONDS
            last = self._last_seen.get(key)
            if last is not None and now - last < ttl:
                continue
            window = [t for t in self._bucket.get(key, []) if now - t < 1.0]
            if len(window) >= RATE_LIMIT_QPS:
                continue
            window.append(now)
            self._bucket[key] = window
            self._last_seen[key] = now
            ev.timestamp = now
            self.events.append(ev)
            if self.sink is not None:
                try:
                    self.sink(ev)
                except Exception:  # noqa: BLE001 — best-effort delivery,
                    # but every drop is counted: silent loss is the one
                    # thing best-effort must not be
                    EVENTS_DROPPED.inc({"reason": "sink_error"})

    def for_object(self, name: str) -> List[Event]:
        return [e for e in self.events if e.object_name == name]

    def reasons_for(self, name: str) -> List[str]:
        return [e.reason for e in self.events if e.object_name == name]


class AsyncSink:
    """Buffered off-thread event delivery — the client-go event
    broadcaster's job (the reference never blocks a reconcile on an event
    POST; record.EventRecorder enqueues and a background watcher flushes).
    Wrap a blocking deliver callable (e.g. KubeApiStore.post_event) and use
    the instance as Recorder.sink. Overflow drops events (best-effort,
    like the broadcaster's bounded queue); delivery errors are swallowed."""

    _CLOSE = object()

    def __init__(self, deliver, maxsize: int = 1024):
        import queue
        import threading
        self._deliver = deliver
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self.dropped = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="karpenter-event-sink")
        self._thread.start()

    def __call__(self, ev: Event) -> None:
        import queue
        try:
            self._q.put_nowait(ev)
        except queue.Full:
            self.dropped += 1
            EVENTS_DROPPED.inc({"reason": "queue_full"})

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is self._CLOSE:
                    return
                try:
                    self._deliver(item)
                except Exception:  # noqa: BLE001 — best-effort delivery
                    EVENTS_DROPPED.inc({"reason": "deliver_error"})
            finally:
                self._q.task_done()

    def flush(self) -> None:
        """Block until everything enqueued so far is delivered (tests and
        operator shutdown)."""
        self._q.join()

    def close(self) -> None:
        self.flush()
        self._q.put(self._CLOSE)
        self._thread.join(timeout=5)
