"""EncodePlane: ONE shared, refcounted encode/cache plane under every solver.

Provisioning passes (PR 6/18), the streaming disruption engine (PR 13), and
sidecar delta sessions (PR 8) all solve over the SAME fleet, yet each used
to own a private ProblemState — three dirty-row trackers, three exist-side
stacks, three invalidation matrices (DEVIATIONS 19/20/24) kept honest
independently. The EncodePlane is the one place the fleet is encoded: a
per-cluster-view, refcounted cache that every subscriber consumes through a
``ProblemState`` handle (``plane.subscribe(name) -> PlaneHandle``; the
handle class IS ``provisioning.problem_state.ProblemState``, so every
existing call site keeps working). Rows are encoded once per revision bump
and shared across subscribers; per-subscriber state shrinks to warm-pack
checkpoints and wire mirrors.

What the PLANE owns (shared across subscribers, content/token-gated so
sharing can never change a decision):

- **node rows** — per-node encoded requirement rows / available vectors /
  zone indices / taint views, keyed ``(name, identity)`` with validity
  token ``(identity, revision)``. TWO generations are kept (``cur`` +
  ``prev``): provisioning encodes the full node list while disruption
  encodes the non-deleting subset, and a single-generation replace (the
  old private-state behavior) would drop the complement on every
  alternation. A row served from either generation is still revision-
  checked, so a stale generation can never leak an outdated encode.
- **node stacks** — the pow2-padded stacked exist tensors, an LRU of the
  last ``MAX_STACKS`` distinct ``exist_token``s (one slot per live node
  subset: provisioning's and disruption's alternating views both stay
  resident instead of rebuilding each other's stack every pass).
- **group rows** — encoded requirement rows + request vectors keyed by the
  content-stable ``grouping.group_signature``; a deployment shape encoded
  by ANY subscriber is a cache hit for every other.
- **topology memos** — per-group cluster topology occupancy keyed by the
  FULL topology token ``(topo_revision, zone_names, node_names,
  scheduled-batch uids)``, an LRU of ``MAX_TOPO_TOKENS`` tokens.
  Provisioning and disruption carry different node tuples / exclusion
  sets, so each gets its own memo dict; the token proves validity, so a
  revisited token may serve its memo (the old single-slot state merely
  discarded it).
- **drought masks + device uploads** — already shared through the
  content-keyed catalog-encoding cache: the masked-offering device slot
  (``device_cache["drought"]``, keyed per live-pattern set) and the
  exist-side device upload (``("exist_side",) + placer namespace`` slot,
  keyed by ``(exist_token, device_token)`` in ``ops/binpack._device_args``)
  live on the vocab's ``device_cache``, so equal content means ONE upload
  serving every subscriber. The plane's row/stack sharing is what makes
  the tokens collide in the first place.
- **topo_revision** — a monotonic revision for WIRE-backed cluster views
  (sidecar sessions): the plane itself is the ``cluster`` object hung off
  the session's WireClusterView, replacing the old per-session
  ``_ClusterRev`` shim. Real ``state.cluster.Cluster`` views carry their
  own revision; this field is only read where no Cluster exists.

What each SUBSCRIBER HANDLE keeps private (see ProblemState):

- warm-pack checkpoints (``seed`` / ``shard_seeds``) — packer state is
  sequential solver memory, valid only against the subscriber's own last
  pack; sharing would replay another solver's decisions.
- mesh attachment (``attach_mesh``) + per-shard exist tokens + the
  cross-shard reconcile memo — bound to the subscriber's mesh carve.
- the tensors memo (group-part/exist-part device tensors of the LAST
  precompute) — a single slot keyed by the subscriber's own group set;
  shared, it would thrash between provisioning's and disruption's group
  axes every alternation.
- per-solve signature memo and ``last``/``stats`` reporting, including
  ``encode_kind`` (cold/delta): reported against the subscriber's OWN
  previous pass, byte-identical to the private-state behavior.

Merged invalidation matrix — every delta a pass can carry, what it costs,
and WHO pays (supersedes the overlap of DEVIATIONS 19/20/24; the sharded
and wire-delta specifics remain in those entries):

| delta                          | plane effect           | subscriber effect |
|--------------------------------|------------------------|-------------------|
| pod arrival/completion         | group rows reused      | warm prefix cut   |
| (known signature)              | (shared hit)           | at first dirty    |
|                                |                        | FFD position      |
| new deployment shape           | ONE group row encoded, | warm prefix cut   |
|                                | shared by all          |                   |
| new vocab entry / catalog      | new vocab object: all  | cold encode       |
| change (masks enumerate the    | row caches for the old | reported per      |
| value universe)                | vocab age out of the   | handle            |
|                                | per-vocab LRUs         |                   |
| node add/remove/update         | dirty rows re-encode   | warm pack         |
|                                | ONCE; clean rows serve | disabled for the  |
|                                | every subscriber; new  | pass (exist_avail |
|                                | exist_token stacks +   | is shared mutable |
|                                | uploads                | packer state)     |
| subscriber node-subset change  | rows shared via the    | none (token-      |
| (provision all / disrupt       | two-generation cache;  | exact)            |
| non-deleting alternation)      | per-subset stack slots |                   |
| scheduled-pod/binding change   | per-token topo memo    | none              |
| (topo_revision bump)           | recomputes misses only |                   |
| daemonset set change           | node caches for that   | warm token        |
|                                | vocab wiped (overhead  | changes           |
|                                | rides avail vectors)   |                   |
| drought mark/expiry            | masked device slot     | warm pack         |
| (unavailable-offerings bump)   | re-keyed per pattern   | invalidated via   |
|                                | set (vocab-shared)     | global token      |
| mesh attach/detach/shard flip  | none (rows, stacks,    | per-shard seeds + |
|                                | memos shard-agnostic)  | reconcile memo    |
|                                |                        | dropped           |
| subscriber join/leave          | refcount only — caches | fresh handle      |
|                                | never invalidate       | starts cold on    |
|                                |                        | its private state |

Anything the matrix cannot express falls back to a cold encode/pack; the
fallback is always decision-equivalent, never semantic. Pinned by: the
churn fuzzer (tests/test_problem_state.py), the streaming-disruption
fuzzer, the sidecar parity probes, the sim-regression goldens, and the
combined-loop fuzzer (tests/test_state_plane.py) which interleaves all
three subscribers over ONE plane and asserts bit-identical decisions vs
three private states.

NOT thread-safe (same contract as ProblemState): a plane is owned by one
single-threaded solver loop — or one sidecar session whose lock serializes
solves — and handles borrow it one at a time. Only the process-wide live-
plane registry (the subscriber gauge + /debug/stateplane) is locked.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..api import labels as api_labels
from ..ops import encode as enc
from ..scheduling.requirements import Requirements, label_requirements
from ..utils import resources as res
from . import audit as _audit

# bound on signature-keyed caches: distinct deployment shapes seen across
# the plane's lifetime. Past it the cache clears wholesale (simple + rare:
# a production cluster cycles far fewer shapes than this).
MAX_SIG_ENTRIES = 4096
# distinct vocab objects kept resident per cache family: provisioning and
# disruption normally share ONE content-keyed catalog encoding, so 2 covers
# a catalog roll (old + new) without thrash
MAX_NODE_VOCABS = 2
# distinct exist_token stacks kept per vocab: the provisioning (all nodes)
# and disruption (non-deleting) views alternate, so both stay resident
MAX_STACKS = 2
# distinct full topology tokens kept resident (provisioning + disruption
# carry different node tuples/exclusion sets, plus one catalog-roll spare)
MAX_TOPO_TOKENS = 4

# process-wide registry of live planes: feeds the subscriber gauge and the
# /debug/stateplane endpoint; weak so an evicted session's plane vanishes
_LIVE_PLANES: "weakref.WeakSet" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()


def live_planes() -> list:
    with _LIVE_LOCK:
        return sorted(_LIVE_PLANES, key=lambda p: p.name)


def refresh_subscriber_gauge() -> None:
    """Re-derive karpenter_state_plane_subscribers from the live planes:
    prune-then-set so a garbage-collected plane's series disappears instead
    of freezing at its last value."""
    from ..metrics.registry import STATE_PLANE_SUBSCRIBERS
    planes = live_planes()
    STATE_PLANE_SUBSCRIBERS.prune([{"plane": p.name} for p in planes])
    for p in planes:
        STATE_PLANE_SUBSCRIBERS.set(
            float(sum(p.subscribers.values())), {"plane": p.name})


class _NodeCache:
    """Per-vocab node-row state: two row generations + the stack LRU."""

    __slots__ = ("ds_token", "cur", "prev", "stacks")

    def __init__(self, ds_token):
        self.ds_token = ds_token
        self.cur: Dict[tuple, tuple] = {}
        self.prev: Dict[tuple, tuple] = {}
        self.stacks: "OrderedDict[tuple, tuple]" = OrderedDict()


class EncodePlane:
    """The shared encode/cache plane. Subscribers hold ProblemState handles
    (``subscribe``); every cache below is content/token-gated, so sharing
    is invisible to scheduling truth by construction."""

    def __init__(self, name: str = "private"):
        self.name = name
        # optional StateAuditor (state/audit.py): when attached, every row
        # serve is digest-verified and each pass runs sampled shadow
        # audits; None keeps the pre-audit fast path byte-identical
        self.auditor = None
        # monotonic revision for wire-backed cluster views (sidecar): the
        # plane IS the `cluster` object on the session's WireClusterView
        self.topo_revision = 0
        # subscriber name -> live handle count (refcount)
        self.subscribers: Dict[str, int] = {}
        # vocab -> _NodeCache (strong vocab refs keep ids from recycling,
        # exactly like the old per-state `_node_vocab` field did)
        self._node_caches: "OrderedDict" = OrderedDict()
        # vocab -> {signature -> (enc_row, req_vec)}
        self._group_caches: "OrderedDict" = OrderedDict()
        # full topology token -> {signature -> (izc, exist, host_total)}
        self._topo_memos: "OrderedDict" = OrderedDict()
        self.stats = {
            "node_rows_encoded": 0, "node_rows_shared": 0,
            "group_rows_encoded": 0, "group_rows_shared": 0,
            "stack_builds": 0, "stack_hits": 0,
        }
        with _LIVE_LOCK:
            _LIVE_PLANES.add(self)

    # -- subscriber lifecycle ------------------------------------------------

    def subscribe(self, subscriber: str = "subscriber"):
        """New PlaneHandle (a ProblemState bound to this plane)."""
        from ..provisioning.problem_state import ProblemState
        return ProblemState(plane=self, subscriber=subscriber)

    def _attach(self, subscriber: str) -> None:
        self.subscribers[subscriber] = self.subscribers.get(subscriber, 0) + 1
        refresh_subscriber_gauge()

    def release(self, subscriber: str) -> None:
        """Drop one refcount; caches are never invalidated by membership
        (they are content-gated), so release only updates accounting."""
        n = self.subscribers.get(subscriber, 0) - 1
        if n <= 0:
            self.subscribers.pop(subscriber, None)
        else:
            self.subscribers[subscriber] = n
        refresh_subscriber_gauge()

    def bump_topo_revision(self) -> int:
        self.topo_revision += 1
        return self.topo_revision

    # -- node rows -----------------------------------------------------------

    def _node_cache(self, vocab, ds_token) -> _NodeCache:
        cache = self._node_caches.get(vocab)
        if cache is None:
            cache = _NodeCache(ds_token)
            self._node_caches[vocab] = cache
            while len(self._node_caches) > MAX_NODE_VOCABS:
                self._node_caches.popitem(last=False)
        else:
            self._node_caches.move_to_end(vocab)
            if cache.ds_token != ds_token:
                # daemonset overhead rides inside every avail vector
                cache.cur = {}
                cache.prev = {}
                cache.stacks.clear()
                cache.ds_token = ds_token
        return cache

    def _encode_node_row(self, vocab, zone_key: int, sn, daemonset_pods,
                         rev, remaining_daemons) -> tuple:
        """Cold-encode ONE node row (the auditor's shadow audits reuse
        exactly this path, so a shadow compare is a true cold replay)."""
        reqs = label_requirements(sn.labels())
        known = Requirements(
            r for r in reqs.values()
            if api_labels.NORMALIZED_LABELS.get(r.key, r.key)
            in vocab.key_idx)
        avail = res.subtract(
            sn.available(), remaining_daemons(sn, daemonset_pods))
        z = sn.labels().get(api_labels.LABEL_TOPOLOGY_ZONE, "")
        return (rev,
                enc.encode_requirements(vocab, known),
                enc.encode_resource_vector(vocab, avail, capacity=True),
                vocab.value_idx[zone_key].get(z, -1),
                sn.taints())

    def _quarantine_node_layer(self, cache: _NodeCache, auditor) -> None:
        """Per-layer quarantine: one corrupted row means neither
        generation (nor any stack built from them) can be trusted — drop
        them all and rebuild cold within the same pass."""
        cache.cur = {}
        cache.prev = {}
        cache.stacks.clear()
        auditor.quarantine_stacks()

    def node_rows(self, vocab, zone_key: int, state_nodes, daemonset_pods,
                  ds_token: tuple, exist_shards: int, subscriber: str
                  ) -> tuple:
        """(exist_enc, exist_avail, exist_zone, taint_lists, exist_token,
        reencoded, shard_tokens, shard_dirty) — byte-identical to what
        build_problem's cold path constructs, with only dirty rows
        re-encoded ONCE for every subscriber. With an auditor attached,
        rows carry a trailing content digest (consumers index fields 0-4,
        so the extra element is invisible to them) verified on every
        serve; a mismatch quarantines the layer and the outer loop
        restarts ONCE over the now-cold caches — the second attempt
        re-encodes everything, so it cannot quarantine again."""
        from ..provisioning.tensor_scheduler import (_node_remaining_daemons,
                                                     _pow2_bucket)
        auditor = self.auditor
        cache = self._node_cache(vocab, ds_token)
        for _attempt in (0, 1):
            cur, prev = cache.cur, cache.prev
            reencoded = 0
            dirty_idx: List[int] = []
            fresh: Dict[tuple, tuple] = {}
            keys = []
            quarantined = False
            for i, sn in enumerate(state_nodes):
                # cache key (name, identity); row-validity token (identity,
                # revision). The identity distinguishes both a deleted-and-
                # recreated node under the same name (whose replayed event
                # sequence can land on the same revision count) and two live
                # StateNodes sharing a name (placeholder + claim entries) —
                # name alone would alias their rows in the stacked tensors.
                key = (sn.name(), getattr(sn, "identity", None))
                keys.append(key)
                rev = (key[1], getattr(sn, "revision", None))
                row = cur.get(key)
                if row is None:
                    row = prev.get(key)
                if row is None or rev[0] is None or rev[1] is None \
                        or row[0] != rev:
                    row = self._encode_node_row(vocab, zone_key, sn,
                                                daemonset_pods, rev,
                                                _node_remaining_daemons)
                    if auditor is not None:
                        row = row + (_audit.row_digest(row),)
                    reencoded += 1
                    dirty_idx.append(i)
                elif auditor is not None and len(row) > 5 \
                        and _audit.row_digest(row) != row[5]:
                    auditor.incident("node_rows",
                                     f"row {key[0]!r} failed its serve-time "
                                     "digest")
                    self._quarantine_node_layer(cache, auditor)
                    quarantined = True
                    break
                elif auditor is not None and len(row) <= 5:
                    # adopted: encoded while no auditor was attached, so
                    # digest it on first audited serve (verify_group's
                    # adopt semantics) — from here on it is verifiable
                    row = row + (_audit.row_digest(row),)
                fresh[key] = row
            if not quarantined and auditor is not None \
                    and reencoded < len(state_nodes):
                # sampled shadow audit: re-encode K clean rows cold and
                # byte-compare — catches a row whose digest was recorded
                # over already-wrong content (the lazy check cannot)
                dirty = set(dirty_idx)
                clean = [i for i in range(len(state_nodes))
                         if i not in dirty]
                for j in auditor.sample_indices(len(clean)):
                    i = clean[j]
                    sn = state_nodes[i]
                    row = fresh[keys[i]]
                    cold = self._encode_node_row(vocab, zone_key, sn,
                                                 daemonset_pods, row[0],
                                                 _node_remaining_daemons)
                    if _audit.row_digest(cold) != _audit.row_digest(row):
                        auditor.incident(
                            "node_rows",
                            f"row {sn.name()!r} diverged from its cold "
                            "shadow re-encode")
                        self._quarantine_node_layer(cache, auditor)
                        quarantined = True
                        break
                    auditor.audited("node_rows")
            if not quarantined:
                break
        cache.prev = cache.cur
        cache.cur = fresh
        self.stats["node_rows_encoded"] += reencoded
        shared = len(state_nodes) - reencoded
        self.stats["node_rows_shared"] += shared
        if reencoded or shared:
            from ..metrics.registry import STATE_PLANE_ROWS
            if reencoded:
                STATE_PLANE_ROWS.inc({"subscriber": subscriber,
                                      "outcome": "reencoded"},
                                     value=reencoded)
            if shared:
                STATE_PLANE_ROWS.inc({"subscriber": subscriber,
                                      "outcome": "shared"}, value=shared)
        revs = tuple((k, getattr(sn, "revision", None))
                     for k, sn in zip(keys, state_nodes))
        exist_token = (vocab, ds_token, revs)
        N = len(state_nodes)
        Np = _pow2_bucket(N, 16)
        # per-shard exist tokens over contiguous Np/S row spans: a dirty
        # row only breaks ITS span's token, so the mesh placer re-uploads
        # one shard's block (rows past N are padding — constant, so they
        # ride the span token implicitly via s/S/Np)
        S = int(exist_shards)
        shard_tokens = None
        shard_dirty = None
        if S > 1 and Np % S == 0:
            from ..metrics.registry import PROBLEM_STATE_SHARD_ROWS
            shard_dirty = {}
            toks = []
            for s, (start, stop) in enumerate(enc.shard_spans(Np, S)):
                real = max(0, min(stop, N) - start)
                d = sum(1 for i in dirty_idx if start <= i < stop)
                shard_dirty[s] = d
                toks.append((vocab, ds_token, revs[start:start + real],
                             s, S, Np))
                if d:
                    PROBLEM_STATE_SHARD_ROWS.inc(
                        {"shard": str(s), "outcome": "reencoded"}, value=d)
                if real - d:
                    PROBLEM_STATE_SHARD_ROWS.inc(
                        {"shard": str(s), "outcome": "clean"},
                        value=real - d)
            shard_tokens = tuple(toks)
        stack = cache.stacks.get(exist_token)
        if stack is not None and auditor is not None:
            # the slot digest guards the stacked tensors themselves: rows
            # are verified above, but a stack is a cached COPY of them
            if auditor.verify_stack(exist_token, stack):
                auditor.audited("exist_stack")
            else:
                auditor.incident("exist_stack",
                                 f"slot of {N} rows failed its digest")
                cache.stacks.clear()
                auditor.quarantine_stacks()
                stack = None
        if stack is not None:
            cache.stacks.move_to_end(exist_token)
            self.stats["stack_hits"] += 1
            return stack + (exist_token, reencoded, shard_tokens,
                            shard_dirty)
        encs = [fresh[k][1] for k in keys]
        taint_lists = [fresh[k][4] for k in keys]
        if Np > N:
            zero = enc.encode_requirements(vocab, Requirements())
            encs = encs + [zero] * (Np - N)
        exist_enc = enc.stack_encoded(encs)
        avail = np.stack([fresh[k][2] for k in keys])
        exist_avail = np.concatenate(
            [avail, np.zeros((Np - N,) + avail.shape[1:], avail.dtype)]) \
            if Np > N else avail
        zones = np.array([fresh[k][3] for k in keys], dtype=np.int32)
        exist_zone = np.concatenate([zones, np.full(Np - N, -1, np.int32)]) \
            if Np > N else zones
        stack = (exist_enc, exist_avail, exist_zone, taint_lists)
        cache.stacks[exist_token] = stack
        while len(cache.stacks) > MAX_STACKS:
            cache.stacks.popitem(last=False)
        self.stats["stack_builds"] += 1
        if auditor is not None:
            auditor.record_stack(exist_token, stack)
        return stack + (exist_token, reencoded, shard_tokens, shard_dirty)

    # -- group rows ----------------------------------------------------------

    def group_row(self, vocab, sig: tuple, g, subscriber: str) -> tuple:
        """((enc_row, req_vec), encoded) for one group, signature-cached
        per vocab and shared by every subscriber."""
        from ..metrics.registry import STATE_PLANE_ROWS
        rows = self._group_caches.get(vocab)
        if rows is None:
            rows = {}
            self._group_caches[vocab] = rows
            while len(self._group_caches) > MAX_NODE_VOCABS:
                self._group_caches.popitem(last=False)
        else:
            self._group_caches.move_to_end(vocab)
        auditor = self.auditor
        row = rows.get(sig)
        if row is not None and auditor is not None:
            # lazy digest check on reuse; group rows must stay 2-tuples
            # (callers unpack them), so digests live in the auditor's
            # side table rather than on the row
            if not auditor.verify_group(vocab, sig, row):
                auditor.incident("group_rows",
                                 "cached row failed its serve-time digest")
                rows.clear()
                auditor.quarantine_groups(vocab)
                row = None
            elif auditor.take_group_audit():
                cold = (enc.encode_requirements(vocab, g.requirements),
                        enc.encode_resource_vector(vocab, g.requests,
                                                   capacity=False))
                if _audit.content_digest(cold) != _audit.content_digest(row):
                    auditor.incident(
                        "group_rows",
                        "cached row diverged from its cold shadow re-encode")
                    rows.clear()
                    auditor.quarantine_groups(vocab)
                    row = None
                else:
                    auditor.audited("group_rows")
        if row is not None:
            self.stats["group_rows_shared"] += 1
            STATE_PLANE_ROWS.inc({"subscriber": subscriber,
                                  "outcome": "shared"})
            return row, False
        if len(rows) >= MAX_SIG_ENTRIES:
            rows.clear()
        row = (enc.encode_requirements(vocab, g.requirements),
               enc.encode_resource_vector(vocab, g.requests,
                                          capacity=False))
        rows[sig] = row
        if auditor is not None:
            auditor.record_group(vocab, sig, row)
        self.stats["group_rows_encoded"] += 1
        STATE_PLANE_ROWS.inc({"subscriber": subscriber,
                              "outcome": "reencoded"})
        return row, True

    # -- topology memos ------------------------------------------------------

    def topo_memo(self, token: tuple) -> dict:
        """The signature->counts memo dict for one FULL topology token.
        The token (topo_revision, zone names, node names, scheduled-batch
        uids) proves validity on its own, so distinct subscribers' tokens
        coexist and a revisited token may serve its memo. Callers mutate
        the returned dict in place (including the overflow wipe)."""
        memo = self._topo_memos.get(token)
        if memo is None:
            memo = {}
            self._topo_memos[token] = memo
            while len(self._topo_memos) > MAX_TOPO_TOKENS:
                self._topo_memos.popitem(last=False)
        else:
            self._topo_memos.move_to_end(token)
        return memo

    # -- introspection (/debug/stateplane) -----------------------------------

    def debug_view(self) -> dict:
        # iterate COPIED views: the owning solver loop mutates these
        # OrderedDicts mid-pass while the /debug/stateplane HTTP thread
        # renders them (the caller still retries a lost race, see
        # operator/server._debug_stateplane)
        node_caches = []
        for vocab, cache in list(self._node_caches.items()):
            node_caches.append({
                "vocab": hex(id(vocab)),
                "rows_cur": len(cache.cur), "rows_prev": len(cache.prev),
                "stacks": len(cache.stacks),
            })
        view = {
            "name": self.name,
            "subscribers": dict(self.subscribers),
            "topo_revision": self.topo_revision,
            "node_caches": node_caches,
            "group_rows": {hex(id(v)): len(rows)
                           for v, rows in list(self._group_caches.items())},
            "topo_tokens": len(self._topo_memos),
            "stats": dict(self.stats),
        }
        if self.auditor is not None:
            view["audit"] = {
                "passes": self.auditor.passes,
                "incidents": len(self.auditor.incidents),
                "stats": dict(self.auditor.stats),
            }
        return view
