"""Informer wiring: store watch events -> Cluster updates.

The reference runs five trivial informer controllers pumping API-server watch
events into state.Cluster (pkg/controllers/state/informer/{pod,node,nodeclaim,
nodepool,daemonset}.go). Here the store's watch fan-out is synchronous, so the
Cluster is always consistent with the store before any controller reconciles —
the property the reference approximates with Synced() (cluster.go:96-150).
"""

from __future__ import annotations

from ..api.nodeclaim import NodeClaim
from ..api.nodepool import NodePool
from ..api.objects import Node, Pod
from ..kube.store import ADDED, DELETED, MODIFIED, Event, Store
from .cluster import Cluster


def wire_informers(store: Store, cluster: Cluster) -> None:
    def on_event(ev: Event) -> None:
        if ev.kind is Pod:
            if ev.type == DELETED:
                cluster.delete_pod(ev.obj)
            else:
                cluster.update_pod(ev.obj)
        elif ev.kind is Node:
            if ev.type == DELETED:
                cluster.delete_node(ev.obj.name)
            else:
                cluster.update_node(ev.obj)
            cluster.mark_unconsolidated()
        elif ev.kind is NodeClaim:
            if ev.type == DELETED:
                cluster.delete_nodeclaim(ev.obj.name)
            else:
                cluster.update_nodeclaim(ev.obj)
            cluster.mark_unconsolidated()
        elif ev.kind is NodePool:
            cluster.mark_unconsolidated()

    store.watch(on_event)
