"""Anti-entropy for the warm state: the StateAuditor (ISSUE 20).

Every hot path is warm and incremental — the shared EncodePlane's
node/group rows and exist stacks, the topo-count memos, the warm-pack
checkpoints — and each one promises decisions bit-identical to a cold
rebuild *by contract*. The auditor enforces that contract continuously:

* **Lazy digest checks on reuse.** Each cached artifact carries (or is
  shadowed by) a crc32 content digest recorded when it was built. Every
  serve re-derives the digest from the bytes about to be served and
  compares; a corrupted entry is therefore detected BEFORE its content
  reaches a solve.
* **Sampled shadow audits every pass.** Digests catch mutation of the
  stored bytes but not a stale-build (digest recorded over already-wrong
  content). So each pass additionally re-encodes K randomly chosen
  node rows cold, re-encodes a sampled group row, and recomputes one
  topo-memo entry from the cluster, byte-comparing against the cache.
  K is a knob; the work is amortized so headline overhead stays <= 5%
  (asserted by BENCH_MODE=audit).
* **Quarantine, per layer.** On mismatch the offending LAYER drops to a
  cold rebuild for the pass (node-row generations + stacks wiped, group
  rows cleared, topo memo cleared, warm seed dropped) and exactly one
  incident fires: `karpenter_state_audit_total{layer,outcome="corrupt"}`,
  a `StateCorruption` warning event, and a flight-recorder dump. The
  pass still produces correct decisions. Quarantine is per-layer, not
  per-row: one detected flip means the layer's invariants can no longer
  be trusted (the corruptor that hit one row may have hit its siblings),
  and a layer rebuild is exactly one cold pass — cheap insurance.

The device-loss half of the anti-entropy story (the degradation ladder)
lives in parallel/mesh.resilient_precompute; its breaker outcomes share
the `karpenter_state_audit_total` family under layer="device".
"""

from __future__ import annotations

import random
import struct
import time
import zlib
from collections import Counter, OrderedDict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

#: cache layers the auditor guards (utils/chaos.StateCorruptor mirrors it)
LAYERS = ("node_rows", "group_rows", "exist_stack", "topo_memo",
          "warm_checkpoint")


# -- content digests ---------------------------------------------------------


def content_digest(obj: Any) -> int:
    """Order-stable crc32 over the CONTENT of a nested artifact: ndarray
    bytes (dtype + shape + raw buffer), scalars, strings, containers, and
    dataclass-ish objects (PackSeed/PackCheckpoint/EncodedRequirements)
    via their field dicts. Anything else digests by repr — stable for the
    lifetime of the cached object, which is the window the digest guards."""
    return _crc(obj, 0)


def _crc(obj: Any, crc: int, _crc32=zlib.crc32, _pack=struct.pack) -> int:
    # this runs once per cached artifact per SERVE (the lazy reuse check),
    # so the common leaves — ndarrays, ints, strs — take the fast exits:
    # buffer-protocol crc32 with no tobytes() copy, struct-packed floats,
    # and no repr-keyed sorting on the hot paths
    if isinstance(obj, np.ndarray):
        crc = _crc32(f"a{obj.dtype.str}{obj.shape}".encode(), crc)
        if not obj.flags.c_contiguous:
            obj = np.ascontiguousarray(obj)
        return _crc32(obj, crc)
    if obj is None:
        return _crc32(b"\x00n", crc)
    if isinstance(obj, bool):
        return _crc32(b"\x01" if obj else b"\x02", crc)
    if isinstance(obj, int):
        return _crc32(b"i" + str(obj).encode(), crc)
    if isinstance(obj, float):
        return _crc32(b"f" + _pack("<d", obj), crc)
    if isinstance(obj, str):
        return _crc32(b"s" + obj.encode("utf-8", "replace"), crc)
    if isinstance(obj, (bytes, bytearray)):
        return _crc32(bytes(obj), _crc32(b"b", crc))
    if isinstance(obj, (tuple, list)):
        crc = _crc32(b"(", crc)
        for item in obj:
            crc = _crc(item, crc)
        return crc
    if isinstance(obj, dict):
        # plain data dicts sort so key order can't alias; repr-keying is
        # only needed for the rare non-string key
        crc = _crc32(b"{", crc)
        try:
            keys = sorted(obj)
        except TypeError:
            keys = sorted(obj, key=repr)
        for k in keys:
            crc = _crc(k, crc)
            crc = _crc(obj[k], crc)
        return crc
    if isinstance(obj, (set, frozenset)):
        crc = _crc32(b"#", crc)
        for item in sorted(obj, key=repr):
            crc = _crc(item, crc)
        return crc
    fields = getattr(obj, "__dict__", None)
    if fields is not None:
        # field ORDER is class-construction order — deterministic between
        # the recorded and the recomputed digest of the same type, so the
        # dict branch's sort (and its cost) is skipped
        crc = _crc32(b"o" + type(obj).__name__.encode(), crc)
        for k, v in fields.items():
            crc = _crc32(k.encode(), crc)
            crc = _crc(v, crc)
        return crc
    return _crc32(b"r" + repr(obj).encode("utf-8", "replace"), crc)


_CHECKPOINT_FIELDS = ("pos", "C", "rows", "existing", "error_log",
                      "exist_avail", "limits", "limit_constrained",
                      "g_of_pos")


def warm_digest(seed, shard_seeds) -> Optional[int]:
    """Digest of the warm-pack checkpoint state whose SILENT corruption
    could replay wrong decisions: each seed's per-group prefix tokens plus
    its checkpoints' numeric packer state. The global token (which embeds
    the whole vocab — megabytes of encoding the digest must not walk every
    pass) and pods_by_group (a live object graph) are excluded
    deliberately: corrupting either breaks the token/prefix match and
    forces a cold pack — self-healing, never silent."""
    seeds = [seed] if seed is not None else []
    seeds += [s for s in (shard_seeds or []) if s is not None]
    if not seeds:
        return None
    crc = 0
    for s in seeds:
        crc = zlib.crc32(b"S", crc)
        crc = _crc(getattr(s, "ffd_tokens", None), crc)
        for ck in getattr(s, "checkpoints", None) or ():
            crc = zlib.crc32(b"C", crc)
            for f in _CHECKPOINT_FIELDS:
                crc = _crc(getattr(ck, f, None), crc)
    return crc


def row_digest(row: tuple, _crc32=zlib.crc32) -> int:
    """Digest of a node-row's CONTENT fields (everything past the revision
    token, excluding a trailing digest element if one is present).

    Hand-specialized over the row's known shape — (rev, encoded
    requirements, avail vector, zone idx, taints) — because this runs once
    per cached row per SERVE: at fleet scale the generic walker's dispatch
    overhead IS the auditor's headline cost. Raw buffers crc directly
    (no tobytes() copy, no per-array dtype/shape header: the array count
    and order are fixed by the row layout, and every corruption kind the
    layer admits — flip, stale value, truncation — changes the byte
    stream). Falls back to the generic walker on any unexpected shape."""
    e = row[1]
    try:
        crc = _crc32(e.mask, 0)
        crc = _crc32(e.defined, crc)
        crc = _crc32(e.complement, crc)
        crc = _crc32(e.exempt, crc)
        crc = _crc32(e.gt, crc)
        crc = _crc32(e.lt, crc)
        crc = _crc32(row[2], crc)
        crc = _crc32(b"i%d" % row[3], crc)
    except (AttributeError, BufferError, TypeError, ValueError):
        return content_digest(row[1:5])
    taints = row[4]
    return _crc(taints, crc) if taints else crc


# -- the auditor -------------------------------------------------------------


class StateAuditor:
    """Clock-injectable integrity auditor attached to one EncodePlane
    (``auditor.attach(plane)``); ProblemState handles find it through
    ``plane.auditor``. One auditor serves every subscriber of the plane —
    corruption is a property of the shared caches, not of a consumer."""

    def __init__(self, seed: int = 0, sample_rows: int = 4,
                 now: Optional[Callable[[], float]] = None,
                 recorder=None, flightrec=None):
        self.rng = random.Random(seed)
        self.sample_rows = int(sample_rows)
        self._now = now or time.monotonic
        self.recorder = recorder
        self.flightrec = flightrec
        self.passes = 0
        self.stats: Counter = Counter()
        self.incidents: List[dict] = []
        self._seq = 0
        # side tables for artifacts whose shape is frozen by consumers
        # (group rows stay 2-tuples, stack slots stay 4-tuples): digests
        # live here, keyed the way the plane keys the artifact
        self._group_digests: "OrderedDict[Any, Dict[Any, int]]" = \
            OrderedDict()
        self._stack_digests: "OrderedDict[Any, int]" = OrderedDict()
        # per-pass shadow-audit budgets (begin_pass resets)
        self._group_budget = 0
        self._topo_budget = 0

    def attach(self, plane) -> "StateAuditor":
        plane.auditor = self
        return self

    # -- pass lifecycle ------------------------------------------------------

    def begin_pass(self) -> None:
        """Called from ProblemState.begin_solve: resets the per-pass
        shadow-audit budgets so every consumer pass pays the same bounded
        audit cost regardless of how many layers it touches."""
        self.passes += 1
        self._group_budget = 1
        self._topo_budget = 1

    # -- incident machinery --------------------------------------------------

    def incident(self, layer: str, detail: str = "") -> dict:
        """Record ONE corruption incident: metric + warning event +
        flight-recorder dump + in-memory ledger. The caller quarantines
        the layer immediately after, so a single fault cannot fire twice
        (the rebuilt layer has nothing left to re-detect)."""
        from ..metrics.registry import STATE_AUDIT
        self._seq += 1
        rec = {"seq": self._seq, "layer": layer, "detail": detail,
               "at": self._now()}
        self.incidents.append(rec)
        self.stats["corrupt:" + layer] += 1
        STATE_AUDIT.inc({"layer": layer, "outcome": "corrupt"})
        if self.recorder is not None:
            try:
                from ..events import catalog
                self.recorder.publish(
                    catalog.state_corruption(layer, detail, self._seq))
            except Exception:  # noqa: BLE001 — auditing must not cost a pass
                pass
        if self.flightrec is not None:
            try:
                self.flightrec.capture_corruption(layer, detail,
                                                  seq=self._seq)
            except Exception:  # noqa: BLE001
                pass
        return rec

    def audited(self, layer: str, n: int = 1) -> None:
        from ..metrics.registry import STATE_AUDIT
        self.stats["audited:" + layer] += n
        STATE_AUDIT.inc({"layer": layer, "outcome": "audited"}, n)

    # -- sampling helpers ----------------------------------------------------

    def sample_indices(self, n: int, k: Optional[int] = None) -> List[int]:
        k = self.sample_rows if k is None else k
        if n <= 0 or k <= 0:
            return []
        if n <= k:
            return list(range(n))
        return self.rng.sample(range(n), k)

    def take_group_audit(self) -> bool:
        if self._group_budget <= 0:
            return False
        self._group_budget -= 1
        return True

    def take_topo_audit(self) -> bool:
        if self._topo_budget <= 0:
            return False
        self._topo_budget -= 1
        return True

    # -- group-row digests (side table, keyed like the plane) ----------------

    def _group_table(self, vocab) -> Dict[Any, int]:
        table = self._group_digests.get(vocab)
        if table is None:
            table = self._group_digests[vocab] = {}
            while len(self._group_digests) > 4:
                self._group_digests.popitem(last=False)
        return table

    def record_group(self, vocab, sig, row) -> None:
        self._group_table(vocab)[sig] = content_digest(row)

    def verify_group(self, vocab, sig, row) -> bool:
        """True if the cached group row matches its recorded digest; a
        row with no recorded digest (the auditor attached after it was
        cached, or the side table was trimmed) is adopted as-is."""
        table = self._group_table(vocab)
        want = table.get(sig)
        if want is None:
            table[sig] = content_digest(row)
            return True
        return content_digest(row) == want

    def quarantine_groups(self, vocab) -> None:
        self._group_digests.pop(vocab, None)

    # -- exist-stack digests -------------------------------------------------

    def record_stack(self, token, stack) -> None:
        self._stack_digests[token] = content_digest(stack)
        while len(self._stack_digests) > 16:
            self._stack_digests.popitem(last=False)

    def verify_stack(self, token, stack) -> bool:
        want = self._stack_digests.get(token)
        if want is None:
            self.record_stack(token, stack)
            return True
        return content_digest(stack) == want

    def quarantine_stacks(self) -> None:
        self._stack_digests.clear()
