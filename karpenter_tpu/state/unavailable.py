"""TTL'd unavailable-offerings registry: the capacity-failure feedback loop.

The reference's typed error taxonomy (types.go:313-399) exists so capacity
failures can change future decisions, and the solvers already consume an
``off_available`` tensor (ops/binpack.py, ops/feasibility.py) — this module
is the piece that flips it. Adapted from the AWS provider's
InsufficientCapacityError cache (aws/pkg/cache/unavailableofferings.go):
launch failures mark ``(instance_type, zone, capacity_type)`` keys —
wildcard forms included, so a zone-wide drought is ONE entry, not one per
type — and every solver pass masks live entries out of its offering
tensors, so the very next pass routes pods to surviving offerings instead
of hot-looping on the dry one.

Deviations from the AWS cache (DEVIATIONS.md):

- escalating TTL: repeated exhaustion of the SAME key within the strike
  window doubles the TTL (capped) instead of the AWS flat 3 minutes — a
  zone that keeps running dry backs off harder;
- the registry is karpenter-side (one instance shared by the lifecycle
  controller, both solvers, and the simulated providers) rather than
  buried in one provider implementation.

Clock-injected and lock-free mutation-wise (single-threaded manager owns
all writers; readers tolerate a stale view for one pass).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils.clock import Clock

WILDCARD = "*"

# base TTL matches the AWS provider's UnavailableOfferingsTTL (3 minutes);
# escalation doubles per repeated strike up to the cap
UNAVAILABLE_TTL_SECONDS = 3 * 60.0
UNAVAILABLE_TTL_CAP_SECONDS = 30 * 60.0
TTL_ESCALATION_FACTOR = 2.0

OfferingKey = Tuple[str, str, str]  # (instance_type, zone, capacity_type)


@dataclass
class _Entry:
    expires_at: float
    ttl: float
    reason: str
    strikes: int
    marked_at: float


class UnavailableOfferings:
    """Clock-injected TTL cache of offering keys known to be dry.

    ``version`` bumps on every state change (mark, expiry) — consumers use
    it as a cheap change signal: the provisioner's exhausted-pod hold
    releases on a bump, and the tensor scheduler keys its device-resident
    masked-offering cache on the live pattern set.
    """

    def __init__(self, clock: Optional[Clock] = None,
                 ttl: float = UNAVAILABLE_TTL_SECONDS,
                 escalation: float = TTL_ESCALATION_FACTOR,
                 max_ttl: float = UNAVAILABLE_TTL_CAP_SECONDS):
        self.clock = clock or Clock()
        self.ttl = ttl
        self.escalation = escalation
        self.max_ttl = max_ttl
        self.version = 0
        self._entries: Dict[OfferingKey, _Entry] = {}
        # strike memory outlives the entries: a key that expires and is
        # re-marked within the strike window escalates instead of starting
        # over (the drought didn't end, the TTL just guessed short).
        # Keyed as (strikes, expires_at-of-last-entry): the clearance test
        # must measure time the key STAYED CLEAR (past expiry), not time
        # since the last mark — re-probes only happen after expiry, so an
        # inter-MARK gap approximates the previous TTL and a since-mark
        # window would reset the escalation exactly when it hits the cap.
        self._strikes: Dict[OfferingKey, Tuple[int, float]] = {}

    # -- writers ------------------------------------------------------------

    def mark(self, instance_type: str = WILDCARD, zone: str = WILDCARD,
             capacity_type: str = WILDCARD,
             reason: str = "insufficient_capacity") -> float:
        """Record a key as unavailable; returns the TTL applied (escalating
        on repeated exhaustion of the same key, capped at max_ttl)."""
        now = self.clock.now()
        key = (instance_type or WILDCARD, zone or WILDCARD,
               capacity_type or WILDCARD)
        from ..metrics import registry as metrics
        strikes, prev_expiry = self._strikes.get(key, (0, -float("inf")))
        entry = self._entries.get(key)
        if entry is not None and entry.expires_at > now:
            # re-mark while the entry is LIVE (several in-flight claims
            # failing on the same drought in one episode): more failures
            # are not re-probe evidence, so refresh the window at the
            # current TTL instead of escalating — escalation is reserved
            # for a failed re-probe AFTER expiry (the AWS cache refreshes
            # the same way)
            entry.expires_at = now + entry.ttl
            entry.marked_at = now
            entry.reason = reason
            self._strikes[key] = (strikes, entry.expires_at)
            self.version += 1
            metrics.OFFERINGS_MARKED.inc({"reason": reason})
            self._publish_gauge()
            return entry.ttl
        if now - prev_expiry > self.max_ttl:
            strikes = 0  # stayed clear past the cap after expiry: over
        ttl = min(self.ttl * (self.escalation ** strikes), self.max_ttl)
        self._strikes[key] = (strikes + 1, now + ttl)
        self._entries[key] = _Entry(expires_at=now + ttl, ttl=ttl,
                                    reason=reason, strikes=strikes + 1,
                                    marked_at=now)
        self.version += 1
        metrics.OFFERINGS_MARKED.inc({"reason": reason})
        self._publish_gauge()
        return ttl

    def expire(self) -> List[OfferingKey]:
        """Prune expired entries; returns the keys that just expired so the
        caller (the provisioner pass) can react to capacity recovery."""
        now = self.clock.now()
        expired = [k for k, e in self._entries.items() if e.expires_at <= now]
        for k in expired:
            del self._entries[k]
        if expired:
            self.version += 1
            self._publish_gauge()
        return expired

    # -- readers ------------------------------------------------------------

    def live(self) -> Tuple[OfferingKey, ...]:
        """Sorted live keys (pruned). Stable across escalation re-marks of
        the same keys, so it doubles as the mask-content cache key."""
        self.expire()
        return tuple(sorted(self._entries))

    def __len__(self) -> int:
        now = self.clock.now()
        return sum(1 for e in self._entries.values() if e.expires_at > now)

    def is_unavailable(self, instance_type: str, zone: str,
                       capacity_type: str) -> bool:
        """Does any live entry — exact or wildcard — cover this offering?"""
        if not self._entries:
            return False
        now = self.clock.now()
        for it_k in (instance_type, WILDCARD):
            for z_k in (zone, WILDCARD):
                for ct_k in (capacity_type, WILDCARD):
                    e = self._entries.get((it_k, z_k, ct_k))
                    if e is not None and e.expires_at > now:
                        return True
        return False

    def next_expiry(self) -> Optional[float]:
        now = self.clock.now()
        times = [e.expires_at for e in self._entries.values()
                 if e.expires_at > now]
        return min(times) if times else None

    def snapshot(self) -> List[dict]:
        """Live entries for the /debug/offerings operator surface. Served
        from HTTP handler threads while the operator thread marks/expires:
        copy first with a retry — CPython dict iteration under concurrent
        mutation raises rather than going stale (same hazard and remedy as
        the flightrec materialize path)."""
        now = self.clock.now()
        for attempt in range(3):
            try:
                items = sorted(self._entries.items())
                break
            except RuntimeError:
                if attempt == 2:
                    raise
        out = []
        for (it, z, ct), e in items:
            if e.expires_at <= now:
                continue
            out.append({"instance_type": it, "zone": z, "capacity_type": ct,
                        "reason": e.reason, "ttl": e.ttl,
                        "strikes": e.strikes,
                        "expires_in": e.expires_at - now})
        return out

    # -- internal -----------------------------------------------------------

    def _publish_gauge(self) -> None:
        from ..metrics import registry as metrics
        metrics.OFFERINGS_UNAVAILABLE.set(float(len(self)))


def mask_instance_types_for(its, patterns) -> list:
    """Object-level mask against an EXPLICIT pattern set (no clock reads):
    offerings covered by a pattern become available=False COPIES
    (provider-owned catalog objects are never mutated); untouched instance
    types pass through as-is, so an empty pattern set is a no-op returning
    the original list. Pure on purpose — the host-oracle fallback and the
    flight recorder pin the patterns THEIR solve used, so a TTL lapsing
    mid-capture can't shift the mask under them."""
    from ..cloudprovider.types import Offering, Offerings
    if not patterns:
        return its
    pats = tuple(patterns)

    def covered(name: str, zone: str, capacity_type: str) -> bool:
        for pit, pz, pct in pats:
            if pit in (WILDCARD, name) and pz in (WILDCARD, zone) \
                    and pct in (WILDCARD, capacity_type):
                return True
        return False

    out = []
    for it in its:
        masked = None
        for i, o in enumerate(it.offerings):
            if o.available and covered(it.name, o.zone, o.capacity_type):
                if masked is None:
                    masked = list(it.offerings)
                masked[i] = Offering(requirements=o.requirements,
                                     price=o.price, available=False)
        out.append(dataclasses.replace(it, offerings=Offerings(masked))
                   if masked is not None else it)
    return out


def mask_catalog(instance_types: dict, patterns) -> dict:
    """mask_instance_types_for over a per-nodepool catalog dict — THE
    shape the host-oracle fallback and the flight recorder's captured
    catalogs share, so a future change to catalog-mask semantics lands in
    every consumer at once. No-op (same dict back) for empty patterns."""
    if not patterns:
        return instance_types
    return {name: mask_instance_types_for(its, patterns)
            for name, its in instance_types.items()}
