"""Cluster: the in-memory mirror both solvers read.

Mirrors /root/reference/pkg/controllers/state/cluster.go: nodes/nodeclaims
unified into StateNodes keyed by providerID (with name-keyed aliases while a
providerID is still unknown), pod->node bindings, the consolidated-state
timestamp that memoizes "nothing to consolidate" (cluster.go:397-423), pod
scheduling ack/decision timestamps feeding latency metrics (:321-376), the
daemonset pod cache (:437-468), and the Synced() superset check against the
store standing in for the API server (:96-150).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import labels as api_labels
from ..api.nodeclaim import NodeClaim
from ..api.objects import Node, Pod
from ..kube.store import Store
from ..utils.clock import Clock
from ..utils.pod import is_terminal
from .statenode import StateNode

# nomination window: how long a node is reserved for a nominated pod
# (cluster.go nominationWindow ~ 20s)
NOMINATION_WINDOW_SECONDS = 20.0
# forced consolidation revalidation period (cluster.go:404-410)
CONSOLIDATION_TIMEOUT_SECONDS = 300.0


def _pod_key(pod: Pod) -> str:
    return f"{pod.namespace}/{pod.name}"


class Cluster:
    def __init__(self, store: Store, clock: Optional[Clock] = None):
        self.store = store
        self.clock = clock or store.clock
        self.nodes: Dict[str, StateNode] = {}          # providerID -> StateNode
        self.node_name_to_provider_id: Dict[str, str] = {}
        self.nodeclaim_name_to_provider_id: Dict[str, str] = {}
        # pod key -> (node name, pod uid). The uid rides along so a pod that
        # was deleted and re-created under the same name on a different node
        # (missed DELETE event) can still be cleaned off the old node
        # (cluster.go cleanupOldBindings:630-646).
        self.bindings: Dict[str, Tuple[str, str]] = {}
        self.daemonset_pods: Dict[str, Pod] = {}       # daemonset key -> sample pod
        self.pod_acks: Dict[str, float] = {}
        self.pod_scheduling_decisions: Dict[str, float] = {}
        self.pod_to_nominated_node: Dict[str, str] = {}
        self._anti_affinity_pods: Dict[str, Pod] = {}  # pod key -> pod
        self._unsynced_start: Optional[float] = None
        # timestamp of the last consolidation-relevant cluster change
        # (cluster.go clusterState); methods memoize it per-method
        self._cluster_state: float = 0.0
        # monotone revision of everything topology counting reads: the
        # scheduled-pod set (bindings) and node identity/labels. The
        # persistent ProblemState memoizes per-group cluster topology
        # counts against this; an unchanged revision proves the counts.
        # Conservative over-bumping is safe (just a recompute).
        self.topo_revision: int = 0

    # -- sync ---------------------------------------------------------------

    def synced(self) -> bool:
        """Superset check (cluster.go:96-150): every Node/NodeClaim the store
        knows must be tracked here. With synchronous informers this is always
        true after a drain; kept for API parity and for tests that bypass
        informers."""
        for nc in self.store.list(NodeClaim):
            name = nc.name
            pid = nc.status.provider_id
            if pid:
                if pid not in self.nodes:
                    return False
            elif name not in self.nodeclaim_name_to_provider_id:
                return False
        for node in self.store.list(Node):
            pid = node.spec.provider_id
            if pid:
                if pid not in self.nodes:
                    return False
            elif node.name not in self.node_name_to_provider_id:
                return False
        return True

    # -- node / nodeclaim tracking -----------------------------------------

    def update_nodeclaim(self, nodeclaim: NodeClaim) -> None:
        pid = nodeclaim.status.provider_id or f"nodeclaim://{nodeclaim.name}"
        self.nodeclaim_name_to_provider_id[nodeclaim.name] = pid
        # migrate a placeholder entry once the real providerID appears
        placeholder = f"nodeclaim://{nodeclaim.name}"
        if pid != placeholder and placeholder in self.nodes:
            sn = self.nodes.pop(placeholder)
            self.nodes[pid] = sn
        sn = self.nodes.get(pid)
        if sn is None:
            sn = StateNode(nodeclaim=nodeclaim)
            self.nodes[pid] = sn
        else:
            sn.nodeclaim = nodeclaim
        sn.bump()
        if sn.node is None and nodeclaim.status.node_name:
            node = self.store.get(Node, nodeclaim.status.node_name)
            if node is not None:
                sn.node = node
        self.topo_revision += 1

    def delete_nodeclaim(self, name: str) -> None:
        pid = self.nodeclaim_name_to_provider_id.pop(name, None)
        if pid is None:
            return
        sn = self.nodes.get(pid)
        if sn is None:
            return
        sn.nodeclaim = None
        sn.bump()
        if sn.node is None:
            del self.nodes[pid]
        self.topo_revision += 1

    def update_node(self, node: Node) -> None:
        pid = node.spec.provider_id or f"node://{node.name}"
        first_seen = node.name not in self.node_name_to_provider_id
        self.node_name_to_provider_id[node.name] = pid
        placeholder = f"node://{node.name}"
        if pid != placeholder and placeholder in self.nodes:
            self.nodes[pid] = self.nodes.pop(placeholder)
        sn = self.nodes.get(pid)
        if sn is None:
            # match an existing nodeclaim-only entry by nodeclaim providerID
            sn = StateNode(node=node)
            self.nodes[pid] = sn
        else:
            sn.node = node
        sn.bump()
        self.topo_revision += 1
        if first_seen:
            self._populate_resource_requests(sn, node.name)

    def _populate_resource_requests(self, sn: StateNode, node_name: str) -> None:
        """Hydrate usage from pods that bound before the node was tracked
        (cluster.go populateResourceRequests:574-593)."""
        from ..scheduling.volumeusage import get_volumes
        for pod in self.store.list(Pod,
                                   field_selector=f"spec.nodeName={node_name}"):
            if is_terminal(pod):
                continue
            sn.update_pod(pod, get_volumes(self.store, pod))
            self.bindings[_pod_key(pod)] = (node_name, pod.uid)

    def delete_node(self, name: str) -> None:
        pid = self.node_name_to_provider_id.pop(name, None)
        if pid is None:
            return
        sn = self.nodes.get(pid)
        if sn is None:
            return
        sn.node = None
        sn.bump()
        if sn.nodeclaim is None:
            del self.nodes[pid]
        self.topo_revision += 1

    # -- pods ---------------------------------------------------------------

    def update_pod(self, pod: Pod) -> None:
        key = _pod_key(pod)
        if pod.metadata.deletion_timestamp is not None and pod.spec.node_name == "":
            self.delete_pod(pod)
            return
        self._update_anti_affinity_index(pod)
        if pod.spec.node_name or key in self.bindings:
            # the scheduled-pod set (or a scheduled pod's content) changed:
            # memoized topology counts are no longer proven
            self.topo_revision += 1
        if is_terminal(pod):
            # a Failed/Succeeded pod no longer consumes node resources
            # (cluster.go UpdatePod:312 -> updateNodeUsageFromPodCompletion)
            binding = self.bindings.pop(key, None)
            if binding:
                self._unbind(binding[1], binding[0])
            return
        old = self.bindings.get(key)
        if pod.spec.node_name:
            if old and (old[0] != pod.spec.node_name or old[1] != pod.uid):
                # pod name re-used (missed DELETE) on a different node — or on
                # the SAME node under a new uid: clean the old binding with
                # the uid we tracked, not the new pod's uid
                self._unbind(old[1], old[0])
            self.bindings[key] = (pod.spec.node_name, pod.uid)
            sn = self._node_by_name(pod.spec.node_name)
            if sn is not None:
                from ..scheduling.volumeusage import get_volumes
                sn.update_pod(pod, get_volumes(self.store, pod))
            self.mark_pod_schedulable(pod)
        elif old:
            self._unbind(old[1], old[0])
            del self.bindings[key]
        if pod.is_daemonset_pod:
            dkey = self._daemonset_key(pod)
            cached = self.daemonset_pods.get(dkey)
            # keep the newest pod as the daemonset exemplar (daemonset.go)
            if cached is None or pod.metadata.creation_timestamp >= \
                    cached.metadata.creation_timestamp:
                self.daemonset_pods[dkey] = pod

    def delete_pod(self, pod: Pod) -> None:
        key = _pod_key(pod)
        binding = self.bindings.pop(key, None)
        if binding:
            self._unbind(binding[1], binding[0])
            self.topo_revision += 1
        self._anti_affinity_pods.pop(key, None)
        self.pod_acks.pop(key, None)
        self.pod_scheduling_decisions.pop(key, None)
        self.pod_to_nominated_node.pop(key, None)
        if pod.is_daemonset_pod:
            dkey = self._daemonset_key(pod)
            cached = self.daemonset_pods.get(dkey)
            if cached is not None and cached.uid == pod.uid:
                # the exemplar died: fall back to any surviving sibling, else
                # drop the cache entry (daemonset deleted)
                siblings = [p for p in self.store.list(Pod,
                                                       namespace=pod.namespace)
                            if p.is_daemonset_pod and p.uid != pod.uid
                            and self._daemonset_key(p) == dkey]
                if siblings:
                    self.daemonset_pods[dkey] = max(
                        siblings, key=lambda p: p.metadata.creation_timestamp)
                else:
                    del self.daemonset_pods[dkey]
        self.mark_unconsolidated()

    def _unbind(self, pod_uid: str, node_name: str) -> None:
        sn = self._node_by_name(node_name)
        if sn is not None:
            sn.cleanup_pod(pod_uid)

    def _node_by_name(self, name: str) -> Optional[StateNode]:
        pid = self.node_name_to_provider_id.get(name)
        if pid is None:
            return None
        return self.nodes.get(pid)

    def _daemonset_key(self, pod: Pod) -> str:
        for ref in pod.metadata.owner_refs:
            if ref.kind == "DaemonSet":
                return f"{pod.namespace}/{ref.name}"
        return _pod_key(pod)

    def _update_anti_affinity_index(self, pod: Pod) -> None:
        aff = pod.spec.affinity
        has_required_anti = (aff is not None and aff.pod_anti_affinity is not None
                             and bool(aff.pod_anti_affinity.required))
        key = _pod_key(pod)
        if has_required_anti:
            self._anti_affinity_pods[key] = pod
        else:
            self._anti_affinity_pods.pop(key, None)

    def anti_affinity_pods(self) -> List[Pod]:
        return list(self._anti_affinity_pods.values())

    def daemonset_pod_list(self) -> List[Pod]:
        return list(self.daemonset_pods.values())

    # -- scheduling latency bookkeeping (cluster.go:321-376) ----------------

    def ack_pods(self, pods: List[Pod]) -> None:
        now = self.clock.now()
        for p in pods:
            self.pod_acks.setdefault(_pod_key(p), now)

    def mark_pod_scheduling_decisions(self, pod_errors: Dict[str, str],
                                      nominations: Dict[str, str]) -> None:
        now = self.clock.now()
        for key in nominations:
            self.pod_scheduling_decisions.setdefault(key, now)
            self.pod_to_nominated_node[key] = nominations[key]
        for key in pod_errors:
            self.pod_scheduling_decisions.setdefault(key, now)

    def mark_pod_schedulable(self, pod: Pod) -> None:
        self.pod_acks.pop(_pod_key(pod), None)

    def pod_ack_duration(self, pod: Pod) -> Optional[float]:
        t = self.pod_acks.get(_pod_key(pod))
        return None if t is None else self.clock.since(t)

    # -- disruption coordination -------------------------------------------

    def mark_for_deletion(self, *provider_ids: str) -> None:
        for pid in provider_ids:
            sn = self.nodes.get(pid)
            if sn is not None:
                sn.mark_for_deletion = True
        self.mark_unconsolidated()

    def unmark_for_deletion(self, *provider_ids: str) -> None:
        for pid in provider_ids:
            sn = self.nodes.get(pid)
            if sn is not None:
                sn.mark_for_deletion = False
        self.mark_unconsolidated()

    def nominate_node_for_pod(self, node_name: str, pod: Pod) -> None:
        sn = self._node_by_name(node_name)
        if sn is not None:
            sn.nominated_until = self.clock.now() + NOMINATION_WINDOW_SECONDS
        self.pod_to_nominated_node[_pod_key(pod)] = node_name

    def consolidation_state(self) -> float:
        """Timestamp of the last time the cluster changed with respect to
        consolidation. Consolidation methods memoize this token per-method
        and skip work while it's unchanged; after 5 minutes of no change the
        token is force-bumped so watchers revalidate against external drift
        (e.g. instance-type availability) we can't observe
        (cluster.go:404-423)."""
        if self.clock.since(self._cluster_state) < CONSOLIDATION_TIMEOUT_SECONDS:
            return self._cluster_state
        return self.mark_unconsolidated()

    def mark_unconsolidated(self) -> float:
        """Called on any change that could make the cluster consolidatable
        (cluster.go:394-403)."""
        self._cluster_state = self.clock.now()
        return self._cluster_state

    # -- views --------------------------------------------------------------

    def state_nodes(self, deep_copy: bool = True) -> List[StateNode]:
        """cluster.Nodes(): deep copies so a solve can't race informer updates
        (cluster.go:188-195)."""
        out = [sn.deep_copy() if deep_copy else sn for sn in self.nodes.values()]
        out.sort(key=lambda sn: sn.name())
        return out

    def deleting_nodes(self) -> List[StateNode]:
        return [sn for sn in self.nodes.values() if sn.deleting()]

    def reset(self) -> None:
        self.__init__(self.store, self.clock)
