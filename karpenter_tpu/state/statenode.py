"""StateNode: the Node + NodeClaim union tracked by cluster state.

Mirrors /root/reference/pkg/controllers/state/statenode.go: per-pod request
tracking, daemonset accounting, the taint view that hides ephemeral/startup
taints before initialization (statenode.go:279-309), Available() =
Allocatable - PodRequests (:364-366), and the disruption validation gates
(:183-232).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from ..api import labels as api_labels
from ..api.nodeclaim import COND_INITIALIZED, NodeClaim
from ..api.objects import Node, Pod, Taint
from ..scheduling.hostports import HostPortUsage, get_host_ports
from ..scheduling.taints import KNOWN_EPHEMERAL_TAINTS
from ..scheduling.volumeusage import Volumes, VolumeUsage
from ..utils import resources as res


class StateNode:
    def __init__(self, node: Optional[Node] = None, nodeclaim: Optional[NodeClaim] = None):
        self.node = node
        self.nodeclaim = nodeclaim
        self.pod_requests: Dict[str, dict] = {}
        self.pod_limits: Dict[str, dict] = {}
        self.daemonset_pod_requests: Dict[str, dict] = {}
        self._host_port_usage = HostPortUsage()
        self._volume_usage = VolumeUsage()
        self.pod_volumes: Dict[str, Volumes] = {}
        self.mark_for_deletion = False
        self.nominated_until: float = 0.0
        # monotone content revision, bumped by Cluster on every mutation
        # that can change what a solver encode reads off this node (labels,
        # taints, allocatable, pod usage, ports, volumes), paired with a
        # process-unique creation identity. The persistent ProblemState
        # keys its per-node encoded rows on (identity, revision): the
        # identity makes a deleted-and-recreated node under the same name
        # a NEW cache key even when its event sequence replays the same
        # revision count (revision alone would collide and serve the old
        # node's stale row). deep_copy preserves both.
        self.revision: int = 0
        self.identity: int = next(StateNode._IDENT_SEQ)

    _IDENT_SEQ = itertools.count(1)

    def bump(self) -> None:
        self.revision += 1

    # --- identity ----------------------------------------------------------

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.spec.provider_id:
            return self.node.spec.provider_id
        if self.nodeclaim is not None:
            return self.nodeclaim.status.provider_id
        return ""

    def name(self) -> str:
        if self.node is not None:
            return self.node.name
        if self.nodeclaim is not None:
            return self.nodeclaim.name
        return ""

    def hostname(self) -> str:
        return self.labels().get(api_labels.LABEL_HOSTNAME, self.name())

    def labels(self) -> dict:
        if self.node is not None:
            return self.node.labels
        if self.nodeclaim is not None:
            return self.nodeclaim.metadata.labels
        return {}

    def annotations(self) -> dict:
        if self.node is not None:
            return self.node.metadata.annotations
        if self.nodeclaim is not None:
            return self.nodeclaim.metadata.annotations
        return {}

    def managed(self) -> bool:
        """A node is Karpenter-managed when owned by a NodeClaim or labeled with
        a nodepool."""
        return self.nodeclaim is not None or \
            api_labels.NODEPOOL_LABEL_KEY in self.labels()

    def nodepool_name(self) -> str:
        return self.labels().get(api_labels.NODEPOOL_LABEL_KEY, "")

    # --- lifecycle views ---------------------------------------------------

    def initialized(self) -> bool:
        """Node registered + initialized label set (statenode.go semantics: the
        lifecycle controller stamps karpenter.sh/initialized on the node)."""
        if self.node is not None:
            return self.node.labels.get(api_labels.NODE_INITIALIZED_LABEL_KEY) == "true"
        return False

    def deleting(self) -> bool:
        if self.mark_for_deletion:
            return True
        if self.node is not None and self.node.metadata.deletion_timestamp is not None:
            return True
        if self.nodeclaim is not None and self.nodeclaim.metadata.deletion_timestamp is not None:
            return True
        return False

    def nominated(self, now: float) -> bool:
        return now < self.nominated_until

    def taints(self) -> List[Taint]:
        """statenode.go:279-309 — before initialization, ephemeral taints and the
        nodepool's startup taints are expected to disappear, so hide them."""
        source = []
        if self.node is not None:
            source = list(self.node.spec.taints)
        elif self.nodeclaim is not None:
            source = list(self.nodeclaim.spec.taints) + list(self.nodeclaim.spec.startup_taints)
        if self.initialized() or not self.managed():
            return source
        startup = list(self.nodeclaim.spec.startup_taints) if self.nodeclaim is not None else []
        out = []
        for t in source:
            if any(t.matches(e) for e in KNOWN_EPHEMERAL_TAINTS):
                continue
            if any(t.matches(s) for s in startup):
                continue
            out.append(t)
        return out

    # --- resources ---------------------------------------------------------

    def capacity(self) -> dict:
        if self.node is not None and self.node.status.capacity:
            return self.node.status.capacity
        if self.nodeclaim is not None:
            return self.nodeclaim.status.capacity
        return {}

    def allocatable(self) -> dict:
        if self.node is not None and self.node.status.allocatable:
            return self.node.status.allocatable
        if self.nodeclaim is not None:
            return self.nodeclaim.status.allocatable
        return {}

    def pod_request_total(self) -> dict:
        return res.merge(*self.pod_requests.values()) if self.pod_requests else {}

    def daemonset_requests(self) -> dict:
        return res.merge(*self.daemonset_pod_requests.values()) \
            if self.daemonset_pod_requests else {}

    def available(self) -> dict:
        """Allocatable minus everything scheduled here (statenode.go:364-366)."""
        return res.subtract(self.allocatable(), self.pod_request_total())

    def host_port_usage(self) -> HostPortUsage:
        return self._host_port_usage

    # --- pod tracking ------------------------------------------------------

    def update_pod(self, pod: Pod, volumes: Optional[Volumes] = None) -> None:
        self.revision += 1
        requests = pod.requests()
        self.pod_requests[pod.uid] = requests
        if pod.is_daemonset_pod:
            self.daemonset_pod_requests[pod.uid] = requests
        self._host_port_usage.delete_pod(pod.uid)
        self._host_port_usage.add(pod, get_host_ports(pod))
        if volumes:
            old = self.pod_volumes.pop(pod.uid, None)
            if old:
                self._volume_usage.delete_pod_volumes(old)
            self.pod_volumes[pod.uid] = volumes
            self._volume_usage.add(volumes)

    def cleanup_pod(self, pod_uid: str) -> None:
        self.revision += 1
        self.pod_requests.pop(pod_uid, None)
        self.pod_limits.pop(pod_uid, None)
        self.daemonset_pod_requests.pop(pod_uid, None)
        self._host_port_usage.delete_pod(pod_uid)
        old = self.pod_volumes.pop(pod_uid, None)
        if old:
            self._volume_usage.delete_pod_volumes(old)

    def volume_usage(self) -> VolumeUsage:
        return self._volume_usage

    # --- disruption gates --------------------------------------------------

    def validate_node_disruptable(self, now: float) -> Optional[str]:
        """statenode.go:183-208: do-not-disrupt annotation, nomination, missing
        nodeclaim, uninitialized all block disruption."""
        if self.nodeclaim is None:
            return "node isn't managed by a nodeclaim"
        if self.annotations().get(api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true":
            return f"disruption is blocked through the {api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY} annotation"
        if not self.initialized():
            return "node is not initialized"
        if self.nominated(now):
            return "node is nominated for a pending pod"
        if self.deleting():
            return "node is deleting or marked for deletion"
        return None

    def deep_copy(self) -> "StateNode":
        out = StateNode(node=self.node, nodeclaim=self.nodeclaim)
        out.pod_requests = dict(self.pod_requests)
        out.pod_limits = dict(self.pod_limits)
        out.daemonset_pod_requests = dict(self.daemonset_pod_requests)
        out._host_port_usage = self._host_port_usage.copy()
        out._volume_usage = self._volume_usage.copy()
        out.pod_volumes = dict(self.pod_volumes)
        out.mark_for_deletion = self.mark_for_deletion
        out.nominated_until = self.nominated_until
        out.revision = self.revision
        out.identity = self.identity
        return out
