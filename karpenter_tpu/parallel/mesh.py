"""Multi-chip sharded execution of the feasibility precompute.

The solve's device program (ops/binpack.py precompute_kernel) is an outer
product over (pod groups x templates x instance types x zones): every axis is
embarrassingly shardable. We map it over a 2-D ``jax.sharding.Mesh``:

- ``groups``  axis — data parallelism over pod equivalence classes (the
  workload dimension; 50k pods collapse to O(100) groups but adversarial
  batches can be group-heavy, e.g. every pod distinct);
- ``catalog`` axis — model parallelism over the instance-type catalog (2k+
  instance types at the north-star scale).

The kernel has no contractions over sharded axes, so XLA/GSPMD lowers it with
zero collectives on the forward pass; the only communication is the implicit
all-gather when the host fetches the packed result tensors. Multi-host scale
(DCN) therefore costs one result gather per solve.

Reference analog: none — the Go scheduler is single-threaded per solve
(scheduler.go:207-265); sharding the feasibility precompute is the TPU-native
scale-out replacing the reference's pre-filter/truncate/timeout coping
strategies (SURVEY.md §5 long-context note).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import binpack
from ..ops import feasibility as feas

GROUPS_AXIS = "groups"
CATALOG_AXIS = "catalog"


def make_solver_mesh(n_devices: Optional[int] = None,
                     devices=None) -> Mesh:
    """A (groups, catalog) mesh over the available devices. The groups axis
    gets the larger factor: group count dominates at scale."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    catalog = 1
    for f in (2, 3):
        if n % f == 0 and n // f > 1:
            catalog = f
            break
    grid = mesh_utils.create_device_mesh((n // catalog, catalog),
                                         devices=np.array(devices))
    return Mesh(grid, (GROUPS_AXIS, CATALOG_AXIS))


def _pad_to(a: np.ndarray, axis: int, size: int, fill=0) -> np.ndarray:
    cur = a.shape[axis]
    if cur >= size:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, size - cur)
    return np.pad(a, pad, constant_values=fill)


def _pad_enc(e, axis: int, size: int):
    from ..ops.encode import EncodedRequirements
    return EncodedRequirements(
        mask=_pad_to(e.mask, axis, size),
        defined=_pad_to(e.defined, axis, size),
        complement=_pad_to(e.complement, axis, size),
        exempt=_pad_to(e.exempt, axis, size),
        gt=_pad_to(e.gt, axis, size),
        lt=_pad_to(e.lt, axis, size))


def pad_problem(p: binpack.PackProblem, g_mult: int, t_mult: int
                ) -> Tuple[binpack.PackProblem, int, int]:
    """Pad the group and catalog axes up to multiples of the mesh dims.
    Padded groups have empty masks (never compatible); padded instance types
    are excluded via template_its=False. Returns (padded, G, T) with the
    original sizes for un-padding results."""
    import dataclasses

    G = p.group_req.shape[0]
    T = p.it_alloc.shape[0]
    Gp = math.ceil(G / g_mult) * g_mult
    Tp = math.ceil(T / t_mult) * t_mult
    if Gp == G and Tp == T:
        # drop the single-device catalog cache: sharded dispatch must not
        # receive arrays already committed to one device
        return dataclasses.replace(p, device_cache=None), G, T
    q = binpack.PackProblem(
        vocab=p.vocab,
        group_enc=_pad_enc(p.group_enc, 0, Gp),
        group_req=_pad_to(p.group_req, 0, Gp),
        group_count=_pad_to(p.group_count, 0, Gp),
        template_enc=p.template_enc,
        daemon_overhead=p.daemon_overhead,
        tol_template=_pad_to(p.tol_template, 0, Gp),
        it_enc=_pad_enc(p.it_enc, 0, Tp),
        it_alloc=_pad_to(p.it_alloc, 0, Tp),
        it_capacity=_pad_to(p.it_capacity, 0, Tp),
        it_price=_pad_to(p.it_price, 0, Tp, fill=np.inf),
        template_its=_pad_to(p.template_its, 1, Tp),
        off_zone=_pad_to(p.off_zone, 0, Tp, fill=-1),
        off_captype=_pad_to(p.off_captype, 0, Tp, fill=-1),
        off_available=_pad_to(p.off_available, 0, Tp),
        off_price=(_pad_to(p.off_price, 0, Tp, fill=np.inf)
                   if p.off_price is not None else None),
        zone_key=p.zone_key, captype_key=p.captype_key,
        zone_values=p.zone_values,
        exist_enc=p.exist_enc, exist_avail=p.exist_avail,
        exist_zone=p.exist_zone,
        tol_exist=(_pad_to(p.tol_exist, 0, Gp)
                   if p.tol_exist is not None else None),
        allow_undefined=p.allow_undefined,
        min_its=(_pad_to(p.min_its, 1, Gp)
                 if p.min_its is not None else None))
    return q, G, T


def _arg_shardings(mesh: Mesh):
    """PartitionSpecs matching precompute_kernel's positional args."""
    g = P(GROUPS_AXIS)
    t = P(CATALOG_AXIS)
    rep = P()
    enc_g = feas.Enc(mask=g, defined=g, complement=g, exempt=g, gt=g, lt=g)
    enc_t = feas.Enc(mask=t, defined=t, complement=t, exempt=t, gt=t, lt=t)
    enc_rep = feas.Enc(*([rep] * 6))
    specs = (enc_g,        # group
             enc_rep,      # template
             enc_t,        # it
             g,            # group_req
             rep,          # daemon
             t,            # alloc
             P(None, CATALOG_AXIS),  # template_its [M,T]
             t, t, t,      # off_zone/off_captype/off_available [T,O]
             rep,          # zone_values
             rep,          # allow_undefined
             g,            # tol_template [G,M]
             enc_rep,      # exist
             rep,          # exist_avail
             g)            # tol_exist [G,N]
    to_ns = lambda s: NamedSharding(mesh, s)
    return jax.tree.map(to_ns, specs,
                        is_leaf=lambda x: isinstance(x, P))


def _out_shardings(mesh: Mesh):
    g0 = NamedSharding(mesh, P(GROUPS_AXIS))
    mg = NamedSharding(mesh, P(None, GROUPS_AXIS))
    gmt = NamedSharding(mesh, P(GROUPS_AXIS, None, CATALOG_AXIS))
    # (compat_tm, it_okz_packed, ppn, zone_adm, exist_ok, exist_cap)
    return (mg, gmt, gmt, g0, g0, g0)


from collections import OrderedDict

_sharded_cache: OrderedDict = OrderedDict()
_SHARDED_CACHE_MAX = 16


def is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh's devices span more than this process."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _to_global(arr, sharding: NamedSharding):
    """Lift a fully-replicated host copy into a global sharded jax.Array.

    Multi-host contract (SURVEY §5 distributed backend): every process holds
    the SAME problem — the cluster store is replicated, exactly like every
    reference scheduler replica sees the same apiserver state — so each
    process materializes only its addressable shards from its local copy.
    """
    host = np.asarray(arr)
    return jax.make_array_from_process_local_data(
        sharding, host, global_shape=host.shape)


def _fetch_replicated(arr) -> np.ndarray:
    """Host copy of a fully-replicated (P()) multi-process array: any local
    shard holds the complete value."""
    return np.asarray(arr.addressable_shards[0].data)


def _assemble_local(arr) -> np.ndarray:
    """Zeros-filled global-shape host buffer holding only this process's
    shards (the caller restricts reads to local_result_slice() spans)."""
    out = np.zeros(arr.shape, dtype=arr.dtype)
    for shard in arr.addressable_shards:
        out[shard.index] = np.asarray(shard.data)
    return out


def _run_sharded_kernel(p: binpack.PackProblem, mesh: Mesh, replicate_out: bool):
    """Shared dispatch: pad to the mesh grid, shard inputs, run the kernel
    under GSPMD. Returns (out_arrays, padded, G, T). In a multi-process mesh
    the inputs are distributed via jax.make_array_from_process_local_data;
    out_shardings stay sharded unless ``replicate_out``, in which case XLA
    inserts one all-gather (ICI/DCN) inside the program so every process
    holds the full result."""
    multiproc = is_multiprocess(mesh)
    g_mult, t_mult = mesh.shape[GROUPS_AXIS], mesh.shape[CATALOG_AXIS]
    padded, G, T = pad_problem(p, g_mult, t_mult)
    args, statics = binpack.device_args(padded)
    in_sh = _arg_shardings(mesh)
    if multiproc:
        args = jax.tree.map(_to_global, args, in_sh)
    key = (mesh, replicate_out, tuple(sorted(statics.items())))
    fn = _sharded_cache.get(key)
    if fn is None:
        if len(_sharded_cache) >= _SHARDED_CACHE_MAX:
            # LRU single eviction (was: clear-all, a recompile storm when
            # two meshes alternate at the cap)
            _sharded_cache.popitem(last=False)
        out_sh = (tuple(NamedSharding(mesh, P()) for _ in range(6))
                  if replicate_out else _out_shardings(mesh))
        fn = jax.jit(
            lambda *a: binpack.precompute_kernel(*a, **statics),
            in_shardings=in_sh,
            out_shardings=out_sh)
        _sharded_cache[key] = fn
    else:
        _sharded_cache.move_to_end(key)
    return fn(*args), padded, G, T


def _unpad_tensors(raw, padded: binpack.PackProblem, G: int, T: int
                   ) -> binpack.PackTensors:
    compat_tm, it_okz_packed, ppn, zone_adm, exist_ok, exist_cap = raw
    t = binpack.unpack_tensors(compat_tm, it_okz_packed, ppn, zone_adm,
                               exist_ok, exist_cap,
                               padded.zone_values.shape[0])
    return binpack.PackTensors(
        compat_tm=t.compat_tm[:, :G],
        it_ok=t.it_ok[:G, :, :T],
        ppn=t.ppn[:G, :, :T],
        it_ok_z=t.it_ok_z[:G, :, :T],
        zone_adm=t.zone_adm[:G],
        exist_ok=t.exist_ok[:G],
        exist_cap=t.exist_cap[:G])


def sharded_precompute(p: binpack.PackProblem, mesh: Mesh) -> binpack.PackTensors:
    """precompute() over a device mesh: pads to the mesh grid, shards inputs,
    runs the same kernel under GSPMD, gathers + un-pads the result.

    Works for single-process meshes (any number of local devices) and for
    meshes spanning multiple processes (a multi-host fleet joined via
    init_multihost()). In the multi-process case every process receives the
    FULL result — the downstream greedy pack (binpack.pack / the host
    oracle) is deterministic over identical tensors, so every host arrives
    at byte-identical launch decisions without any leader, the way the
    reference's scheduler replicas converge through the shared apiserver.
    The gather is a single XLA all-gather of the packed bitfields riding
    ICI/DCN; callers that post-process per group-row instead can use
    sharded_precompute_local() to skip it."""
    multiproc = is_multiprocess(mesh)
    out, padded, G, T = _run_sharded_kernel(p, mesh, replicate_out=multiproc)
    if multiproc:
        raw = tuple(_fetch_replicated(o) for o in out)
    else:
        raw = jax.device_get(out)
    return _unpad_tensors(raw, padded, G, T)


def sharded_precompute_local(p: binpack.PackProblem, mesh: Mesh
                             ) -> "Tuple[binpack.PackTensors, list]":
    """Multi-host bandwidth optimization: compute the sharded precompute and
    fetch ONLY this process's group rows, skipping the cross-host result
    gather entirely. Returns ``(tensors, spans)`` where ``spans`` is
    local_result_slice()'s [start, stop) group-row list; tensor rows outside
    the spans are zeros and must not be read.

    Requires every local groups-axis row to be catalog-complete on this
    process (true for make_solver_mesh() grids, where a process's devices
    tile whole rows); raises ValueError otherwise rather than returning
    rows with silent holes."""
    multiproc = is_multiprocess(mesh)
    if multiproc:
        me = jax.process_index()
        for r in range(mesh.devices.shape[0]):
            row_procs = {d.process_index for d in mesh.devices[r]}
            if me in row_procs and row_procs != {me}:
                raise ValueError(
                    f"groups-axis row {r} spans processes {sorted(row_procs)}; "
                    "local fetch needs catalog-complete rows — use "
                    "sharded_precompute() (replicated gather) instead")
    out, padded, G, T = _run_sharded_kernel(p, mesh, replicate_out=False)
    if multiproc:
        raw = tuple(_assemble_local(o) for o in out)
    else:
        raw = jax.device_get(out)
    tensors = _unpad_tensors(raw, padded, G, T)
    Gp = padded.group_req.shape[0]
    spans = [(start, min(stop, G))
             for start, stop in local_result_slice(mesh, Gp)
             if start < G]
    return tensors, spans


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   auto: bool = False) -> int:
    """Join a multi-host solver fleet via JAX's distributed runtime, the
    analog of the reference's NCCL/MPI bootstrap (SURVEY §5 distributed
    backend). Idempotent; returns the process count.

    Each host contributes its local chips to the global device set;
    `make_solver_mesh()` then builds the (groups × catalog) mesh over
    `jax.devices()` — which, after initialization, spans every host — and
    GSPMD partitions the feasibility precompute across them. The kernel
    has no cross-shard contractions, so the only DCN traffic is the result
    gather (one packed-bitfield fetch per solve; see sharded_precompute).

    Parameters default to the standard JAX env bootstrap
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID or the
    cloud-TPU metadata server). Call before any other JAX API; single-host
    runs skip the distributed service entirely."""
    import os
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    # NOTE: deliberately no TPU_WORKER_HOSTNAMES sniffing — single-host TPU
    # plugins set it too; multi-host intent must be explicit. On a cloud-TPU
    # pod slice where the coordinator comes from the metadata server (no env
    # vars at all), pass auto=True to hand bootstrap entirely to JAX.
    bootstrap_available = (auto
                           or coordinator_address is not None
                           or num_processes is not None
                           or process_id is not None
                           or "JAX_COORDINATOR_ADDRESS" in os.environ)
    if num_processes == 1 or not bootstrap_available:
        return 1  # explicitly (or evidently) single host: no service needed
    already = getattr(jax.distributed, "is_initialized", None)
    if already is None or not already():
        # None values pass through so jax can auto-detect from its own
        # bootstrap sources (env vars / cloud-TPU metadata)
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    return jax.process_count()


def local_result_slice(mesh: Mesh, n_groups: int,
                       process_index: Optional[int] = None
                       ) -> "list[Tuple[int, int]]":
    """The [start, stop) group-row spans this process computed — multi-host
    callers that shard the DOWNSTREAM packing per host use these to skip
    fetching rows another host owns (the gather at sharded_precompute
    otherwise pulls the full result to every host).

    Returns a list of contiguous spans: mesh_utils.create_device_mesh may
    reorder devices for topology, so one process's groups-axis rows need
    not be contiguous — collapsing them to a single [min, max) range would
    overlap other hosts' slices and double-pack their groups."""
    if process_index is None:
        process_index = jax.process_index()
    n_shards = mesh.shape[GROUPS_AXIS]
    per = math.ceil(n_groups / n_shards)
    local_rows = sorted(
        {idx[0] for idx, dev in np.ndenumerate(mesh.devices)
         if dev.process_index == process_index})
    spans: "list[Tuple[int, int]]" = []
    for row in local_rows:
        start = row * per
        stop = min((row + 1) * per, n_groups)
        if start >= stop:
            continue
        if spans and spans[-1][1] == start:
            spans[-1] = (spans[-1][0], stop)  # merge adjacent rows
        else:
            spans.append((start, stop))
    return spans
