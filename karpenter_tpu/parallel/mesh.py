"""Multi-chip sharded execution of the feasibility precompute and the
pods/groups-sharded pack.

The solve's device program (ops/binpack.py precompute_kernel) is an outer
product over (pod groups x templates x instance types x zones): every axis is
embarrassingly shardable. We map it over a 2-D ``jax.sharding.Mesh``:

- ``pods_groups`` axis — data parallelism over pod equivalence classes (the
  workload dimension; 50k pods collapse to O(100) groups but adversarial
  batches can be group-heavy, e.g. a million pods over thousands of
  deployments);
- ``catalog`` axis — model parallelism over the instance-type catalog (2k-4k
  instance types at the north-star scales).

Dispatch rides the SAME compiled-executable cache, device-upload cache and
tracing spans as the single-device path (ops/binpack._run_precompute /
device_args with a mesh ArgPlacer) — the round-5 dual-lineage split, where
the mesh compiled its own jit wrapper keyed on the Mesh OBJECT and re-uploaded
the catalog every solve, is gone. Executables are keyed on device identity +
mesh grid + padded shapes, so a recreated mesh over the same devices hits the
cache; both axes pad to power-of-two PER-SHARD stacks so group/catalog count
wobble stays within a bucket instead of recompiling.

The kernel has no contractions over sharded axes, so XLA/GSPMD lowers it with
zero collectives on the forward pass; the existing-node side is replicated
(P()) and the only communication is the result gather when the host fetches
the packed tensors. Multi-host scale (DCN) therefore costs one result gather
per solve.

Past the precompute, ``sharded_pack`` carves the host-side greedy pack along
the same pods_groups axis: round-robin interleaved blocks of the FFD order
pack in parallel against per-shard cohort sets, then a cross-shard reconcile
re-offers each shard's remainder-node cohorts to the merged cohort winners so
stragglers coalesce. Decisions may differ from the sequential oracle only in
remainder-node composition (DEVIATIONS 22); the exact global pack remains the
default everywhere.

Reference analog: none — the Go scheduler is single-threaded per solve
(scheduler.go:207-265); sharding the feasibility precompute and the pack is
the TPU-native scale-out replacing the reference's pre-filter/truncate/
timeout coping strategies (SURVEY.md §5 long-context note).
"""

from __future__ import annotations

import functools
import math
import os
from typing import List, Optional, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import binpack
from ..ops import encode as enc
from ..ops import feasibility as feas

PODS_GROUPS_AXIS = "pods_groups"
# back-compat alias: the axis was named "groups" before the pods/groups
# shard axis generalized it (same axis, same sharding role)
GROUPS_AXIS = PODS_GROUPS_AXIS
CATALOG_AXIS = "catalog"

# per-shard pow2 floors: small enough that toy problems stay cheap, large
# enough that real group/catalog counts land in few distinct buckets
_GROUP_SHARD_MIN = 8
_CATALOG_SHARD_MIN = 64


def make_solver_mesh(n_devices: Optional[int] = None,
                     devices=None) -> Mesh:
    """A (pods_groups, catalog) mesh over the available devices. The
    pods_groups axis gets the larger factor: group count dominates at
    scale."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    catalog = 1
    for f in (2, 3):
        if n % f == 0 and n // f > 1:
            catalog = f
            break
    grid = mesh_utils.create_device_mesh((n // catalog, catalog),
                                         devices=np.array(devices))
    return Mesh(grid, (PODS_GROUPS_AXIS, CATALOG_AXIS))


def mesh_cache_key(mesh: Mesh) -> tuple:
    """Device identity + grid shape: what the compiled executable actually
    depends on. Two Mesh OBJECTS over the same devices in the same grid are
    interchangeable for execution, so keying caches on this (not the Mesh)
    means a recreated mesh never recompiles (the PR-3 compile-cache fix,
    applied to the sharded path)."""
    return (tuple(int(d.id) for d in mesh.devices.flat),
            tuple(int(s) for s in mesh.devices.shape))


def _pad_to(a: np.ndarray, axis: int, size: int, fill=0) -> np.ndarray:
    cur = a.shape[axis]
    if cur >= size:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, size - cur)
    return np.pad(a, pad, constant_values=fill)


def _pad_enc(e, axis: int, size: int):
    from ..ops.encode import EncodedRequirements
    return EncodedRequirements(
        mask=_pad_to(e.mask, axis, size),
        defined=_pad_to(e.defined, axis, size),
        complement=_pad_to(e.complement, axis, size),
        exempt=_pad_to(e.exempt, axis, size),
        gt=_pad_to(e.gt, axis, size),
        lt=_pad_to(e.lt, axis, size))


def padded_sizes(G: int, T: int, g_mult: int, t_mult: int) -> Tuple[int, int]:
    """(Gp, Tp): both mesh axes padded to ``mult x pow2`` per-shard stacks.
    Pow2 bucketing (not plain next-multiple) keeps the executable cache
    hitting when group or catalog counts wobble between solves — the same
    contract the single-device path gets from the ProblemState's group-axis
    bucket."""
    Gp = g_mult * enc.pow2_bucket(-(-G // g_mult), _GROUP_SHARD_MIN)
    Tp = t_mult * enc.pow2_bucket(-(-T // t_mult), _CATALOG_SHARD_MIN)
    return Gp, Tp


def pad_problem(p: binpack.PackProblem, g_mult: int, t_mult: int,
                pad_catalog: bool = True
                ) -> Tuple[binpack.PackProblem, int, int]:
    """Pad the group-major and catalog axes up to pow2 per-shard stacks for
    the mesh grid. Padded groups have empty masks (never compatible); padded
    instance types are excluded via template_its=False / off_available=False.
    ``pad_catalog=False`` skips the catalog-side copies — the caller only
    does that when the padded+sharded catalog upload is already cached
    (device_args never reads the host catalog arrays on a cache hit).
    Returns (padded, G, T) with the original sizes for un-padding results.

    The existing-node side is NOT padded: it is replicated (P()) across the
    mesh, exactly as every reference scheduler replica holds the full
    cluster state."""
    import dataclasses

    G = p.group_req.shape[0]
    T = p.it_alloc.shape[0]
    Gp, Tp = padded_sizes(G, T, g_mult, t_mult)
    if Gp == G and Tp == T:
        return p, G, T
    fields = dict(
        group_enc=_pad_enc(p.group_enc, 0, Gp),
        group_req=_pad_to(p.group_req, 0, Gp),
        group_count=_pad_to(p.group_count, 0, Gp),
        tol_template=_pad_to(p.tol_template, 0, Gp),
        template_its=_pad_to(p.template_its, 1, Tp),
        tol_exist=(_pad_to(p.tol_exist, 0, Gp)
                   if p.tol_exist is not None else None),
        min_its=(_pad_to(p.min_its, 1, Gp)
                 if p.min_its is not None else None))
    if pad_catalog and Tp > T:
        fields.update(
            it_enc=_pad_enc(p.it_enc, 0, Tp),
            it_alloc=_pad_to(p.it_alloc, 0, Tp),
            it_capacity=_pad_to(p.it_capacity, 0, Tp),
            it_price=_pad_to(p.it_price, 0, Tp, fill=np.inf),
            off_zone=_pad_to(p.off_zone, 0, Tp, fill=-1),
            off_captype=_pad_to(p.off_captype, 0, Tp, fill=-1),
            off_available=_pad_to(p.off_available, 0, Tp),
            off_price=(_pad_to(p.off_price, 0, Tp, fill=np.inf)
                       if p.off_price is not None else None))
    return dataclasses.replace(p, **fields), G, T


# NamedSharding construction is pure metadata but happens on every dispatch
# (dozens of leaves); placements are a function of device identity + grid
# alone, so one cache entry per mesh shape serves every recreated Mesh over
# the same devices (the same contract mesh_cache_key gives the executable
# cache). Bounded: meshes come and go with process topology, not workload.
_SHARDING_CACHE: dict = {}
_SHARDING_CACHE_MAX = 8


def _cached_shardings(mesh: Mesh, kind: str, build):
    key = (mesh_cache_key(mesh), kind)
    hit = _SHARDING_CACHE.get(key)
    if hit is None:
        if len(_SHARDING_CACHE) >= _SHARDING_CACHE_MAX:
            _SHARDING_CACHE.clear()
        hit = _SHARDING_CACHE[key] = build(mesh)
    return hit


def _replicated(mesh: Mesh) -> NamedSharding:
    return _cached_shardings(mesh, "rep",
                             lambda m: NamedSharding(m, P()))


def _arg_shardings(mesh: Mesh):
    return _cached_shardings(mesh, "args", _build_arg_shardings)


def _build_arg_shardings(mesh: Mesh):
    """PartitionSpecs matching precompute_kernel's positional args."""
    g = P(PODS_GROUPS_AXIS)
    t = P(CATALOG_AXIS)
    rep = P()
    enc_g = feas.Enc(mask=g, defined=g, complement=g, exempt=g, gt=g, lt=g)
    enc_t = feas.Enc(mask=t, defined=t, complement=t, exempt=t, gt=t, lt=t)
    enc_rep = feas.Enc(*([rep] * 6))
    specs = (enc_g,        # group
             enc_rep,      # template
             enc_t,        # it
             g,            # group_req
             rep,          # daemon
             t,            # alloc
             P(None, CATALOG_AXIS),  # template_its [M,T]
             t, t, t,      # off_zone/off_captype/off_available [T,O]
             rep,          # zone_values
             rep,          # allow_undefined
             g,            # tol_template [G,M]
             enc_rep,      # exist (replicated node side)
             rep,          # exist_avail
             g)            # tol_exist [G,N]
    to_ns = lambda s: NamedSharding(mesh, s)
    return jax.tree.map(to_ns, specs,
                        is_leaf=lambda x: isinstance(x, P))


# catalog-side arg sharding specs, matching device_args' it_side tuple order:
# (it_enc, it_alloc, off_zone, off_captype, off_available, zone_values,
#  allow_undefined)
def _it_side_shardings(mesh: Mesh):
    return _cached_shardings(mesh, "it_side", _build_it_side_shardings)


def _build_it_side_shardings(mesh: Mesh):
    t = NamedSharding(mesh, P(CATALOG_AXIS))
    rep = NamedSharding(mesh, P())
    enc_t = feas.Enc(*([t] * 6))
    return (enc_t, t, t, t, t, rep, rep)


def _out_shardings(mesh: Mesh):
    return _cached_shardings(mesh, "out", _build_out_shardings)


def _build_out_shardings(mesh: Mesh):
    g0 = NamedSharding(mesh, P(PODS_GROUPS_AXIS))
    mg = NamedSharding(mesh, P(None, PODS_GROUPS_AXIS))
    gmt = NamedSharding(mesh, P(PODS_GROUPS_AXIS, None, CATALOG_AXIS))
    # (compat_tm, it_okz_packed, ppn, zone_adm, exist_ok, exist_cap)
    return (mg, gmt, gmt, g0, g0, g0)


# exist-side delta splice: write a dirty per-shard row block into the
# resident replicated stack IN PLACE (the stack buffer is donated, so on
# backends that honor donation no second full-stack allocation exists and
# the clean rows never move). `start` is static: shard spans are fixed per
# (N, S), so the compile count is bounded by the shard count.
@functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(2,))
def _donated_row_splice(buf, block, start: int):
    return jax.lax.dynamic_update_slice_in_dim(buf, block, start, axis=0)


class _MeshPlacer(binpack.ArgPlacer):
    """device_args placement for a sharded dispatch: group-side arrays stay
    host numpy (the compiled executable auto-places uncommitted inputs per
    its in_shardings), the catalog side is device_put WITH its NamedSharding
    once and cached under a device-identity slot, and the existing-node side
    is replicated. Under a multi-process mesh nothing is device_put here —
    every arg goes through jax.make_array_from_process_local_data instead
    (the caller's _to_global pass)."""

    def __init__(self, mesh: Mesh, multiproc: bool, Tp: int):
        self.mesh = mesh
        self.multiproc = multiproc
        # Tp in the namespace: the cached upload's shapes depend on it, and
        # two catalog paddings must never collide in one slot
        self.cache_ns = ("mesh", mesh_cache_key(mesh), Tp)

    def enc(self, e) -> feas.Enc:
        return feas.host_enc(e)

    def i32(self, a):
        return np.clip(a, -binpack.INT32_MAX - 1,
                       binpack.INT32_MAX).astype(np.int32)

    def array(self, a):
        return np.asarray(a)

    def put_it_side(self, it_side):
        if self.multiproc:
            return it_side
        return jax.tree.map(jax.device_put, it_side,
                            _it_side_shardings(self.mesh))

    def put_exist_side(self, exist, exist_avail, p=None):
        if self.multiproc:
            return exist, exist_avail
        rep = _replicated(self.mesh)
        tokens = getattr(p, "exist_shard_tokens", None) \
            if p is not None else None
        cache = getattr(p, "device_cache", None) if p is not None else None
        N = int(exist_avail.shape[0])
        if (not tokens or len(tokens) < 2 or cache is None
                or N % len(tokens) != 0):
            put = lambda x: jax.device_put(x, rep)
            return feas.Enc(*(put(x) for x in exist)), put(exist_avail)
        # delta upload: the sharded ProblemState carved the exist stack into
        # contiguous per-shard row blocks (encode.shard_spans) with one
        # content token each. Only blocks whose token changed cross the
        # host->device boundary: dirty spans are SPLICED into the resident
        # full device buffers through a donated row-update (no device-side
        # re-concatenation of clean blocks — PR-18 leftover b); clean spans
        # never move. This only runs on a full-token MISS (all-clean passes
        # reuse the whole cached pair via device_args' exist_side slot).
        from ..metrics.registry import (EXIST_SPLICE_BYTES,
                                        PROBLEM_STATE_SHARD_ROWS)
        spans = enc.shard_spans(N, len(tokens))
        key = ("exist_shards",) + self.cache_ns
        host_leaves = tuple(exist) + (exist_avail,)
        prev = cache.get(key)
        if prev is not None and (
                len(prev[0]) != len(tokens)
                or any(d.shape != np.shape(h) or d.dtype != np.asarray(h).dtype
                       for d, h in zip(prev[1], host_leaves))):
            # padded axis or vocab width moved: the resident buffers can't
            # host a row splice — fall through to a whole-stack upload
            prev = None
        if prev is None:
            put = lambda x: jax.device_put(np.asarray(x), rep)
            dev = tuple(put(x) for x in host_leaves)
            for s, (start, stop) in enumerate(spans):
                PROBLEM_STATE_SHARD_ROWS.inc(
                    {"shard": str(s), "outcome": "uploaded"},
                    value=stop - start)
            EXIST_SPLICE_BYTES.inc(
                {"outcome": "uploaded"},
                value=float(sum(np.asarray(h).nbytes for h in host_leaves)))
        else:
            dev = list(prev[1])
            # the donated input is resident-only by construction: the
            # exist_shards slot and the exist_side slot are both replaced
            # with the spliced result below, so nothing can feed the
            # pre-splice (deleted) buffers into a later dispatch. CPU
            # backends decline donation (copy instead) — suppress the
            # compile-time warning; semantics are identical.
            import warnings
            with warnings.catch_warnings():
                warnings.filterwarnings("ignore", message=".*[Dd]onat")
                for s, (start, stop) in enumerate(spans):
                    if prev[0][s] == tokens[s]:
                        PROBLEM_STATE_SHARD_ROWS.inc(
                            {"shard": str(s), "outcome": "upload_skipped"},
                            value=stop - start)
                        EXIST_SPLICE_BYTES.inc(
                            {"outcome": "skipped"},
                            value=float(sum(
                                np.asarray(h)[start:stop].nbytes
                                for h in host_leaves)))
                        continue
                    PROBLEM_STATE_SHARD_ROWS.inc(
                        {"shard": str(s), "outcome": "uploaded"},
                        value=stop - start)
                    up = 0
                    for i, hx in enumerate(host_leaves):
                        block = jax.device_put(
                            np.ascontiguousarray(
                                np.asarray(hx)[start:stop]), rep)
                        up += block.nbytes
                        dev[i] = _donated_row_splice(dev[i], block, start)
                    EXIST_SPLICE_BYTES.inc({"outcome": "uploaded"},
                                           value=float(up))
            dev = tuple(dev)
        cache[key] = (tuple(tokens), dev)
        return feas.Enc(*dev[:6]), dev[6]

    def device_token(self) -> tuple:
        return ("mesh", mesh_cache_key(self.mesh))

    def it_side_valid(self, p, it_side) -> bool:
        # the slot key embeds (device identity, Tp): a hit under a
        # pad_catalog=False fast path sees the UNPADDED problem, so the
        # default shape check would falsely invalidate it
        return True


def is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh's devices span more than this process."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _to_global(arr, sharding: NamedSharding):
    """Lift a fully-replicated host copy into a global sharded jax.Array.

    Multi-host contract (SURVEY §5 distributed backend): every process holds
    the SAME problem — the cluster store is replicated, exactly like every
    reference scheduler replica sees the same apiserver state — so each
    process materializes only its addressable shards from its local copy.
    """
    host = np.asarray(arr)
    return jax.make_array_from_process_local_data(
        sharding, host, global_shape=host.shape)


def _fetch_replicated(arr) -> np.ndarray:
    """Host copy of a fully-replicated (P()) multi-process array: any local
    shard holds the complete value."""
    return np.asarray(arr.addressable_shards[0].data)


def _assemble_local(arr) -> np.ndarray:
    """Zeros-filled global-shape host buffer holding only this process's
    shards (the caller restricts reads to local_result_slice() spans)."""
    out = np.zeros(arr.shape, dtype=arr.dtype)
    for shard in arr.addressable_shards:
        out[shard.index] = np.asarray(shard.data)
    return out


def _sharded_dispatch(p: binpack.PackProblem, mesh: Mesh,
                      replicate_out: bool):
    """The dispatch setup shared by execution and memory analysis: pad to
    the mesh grid's pow2 per-shard stacks, place/shard inputs, assemble the
    executable-cache shard key. Returns (args, statics, shard, padded, G,
    T) with ``shard`` in binpack._get_executable's (key, in_shardings,
    out_shardings) form. In a multi-process mesh the inputs are distributed
    via jax.make_array_from_process_local_data; out_shardings stay sharded
    unless ``replicate_out``, in which case XLA inserts one all-gather
    (ICI/DCN) inside the program so every process holds the full result."""
    multiproc = is_multiprocess(mesh)
    g_mult = mesh.shape[PODS_GROUPS_AXIS]
    t_mult = mesh.shape[CATALOG_AXIS]
    G = p.group_req.shape[0]
    T = p.it_alloc.shape[0]
    _, Tp = padded_sizes(G, T, g_mult, t_mult)
    placer = _MeshPlacer(mesh, multiproc, Tp)
    # the padded catalog-side copies are only consumed when the sharded
    # upload cache misses; skip them entirely on a hit (they are the bulk
    # of pad_problem's host cost at 2k-4k instance types)
    cache = p.device_cache
    cached = (cache is not None
              and cache.get(("it_side",) + placer.cache_ns) is not None)
    padded, G, T = pad_problem(p, g_mult, t_mult, pad_catalog=not cached)
    args, statics = binpack.device_args(padded, placer)
    in_sh = _arg_shardings(mesh)
    if multiproc:
        args = jax.tree.map(_to_global, args, in_sh)
    out_sh = (tuple(NamedSharding(mesh, P()) for _ in range(6))
              if replicate_out else _out_shardings(mesh))
    shard_key = ("mesh", mesh_cache_key(mesh), bool(replicate_out))
    return args, statics, (shard_key, in_sh, out_sh), padded, G, T


def _run_sharded_kernel(p: binpack.PackProblem, mesh: Mesh, replicate_out: bool):
    """Run the ONE precompute kernel under GSPMD through binpack's
    persistent executable cache. Returns (out_arrays, padded, G, T)."""
    args, statics, shard, padded, G, T = _sharded_dispatch(
        p, mesh, replicate_out)
    out = binpack._run_precompute(args, statics, shard=shard)
    return out, padded, G, T


def sharded_memory_analysis(p: binpack.PackProblem, mesh: Mesh) -> int:
    """Per-device peak bytes (args + temps + output) of the compiled
    sharded precompute program, from XLA's own memory analysis — the
    memory-ceiling number the mesh exists to lower. Compiles (and caches)
    the executable if this problem shape hasn't run yet."""
    args, statics, shard, _, _, _ = _sharded_dispatch(
        p, mesh, replicate_out=False)
    exe, _, key = binpack._get_executable(args, statics, shard=shard)
    m = exe.memory_analysis()
    peak = int(m.temp_size_in_bytes + m.argument_size_in_bytes
               + m.output_size_in_bytes)
    # feed the continuous per-device watermark gauges too: the one-shot
    # bench probe and the live dispatch path share the same truth
    from ..obs.device import DEVICE_TIME
    DEVICE_TIME.register(key, exe, "mesh",
                         shapes=binpack._shape_summary(args),
                         devices=[str(d.id) for d in mesh.devices.flat])
    return peak


def _unpad_tensors(raw, padded: binpack.PackProblem, G: int, T: int
                   ) -> binpack.PackTensors:
    compat_tm, it_okz_packed, ppn, zone_adm, exist_ok, exist_cap = raw
    t = binpack.unpack_tensors(compat_tm, it_okz_packed, ppn, zone_adm,
                               exist_ok, exist_cap,
                               padded.zone_values.shape[0])
    return binpack.PackTensors(
        compat_tm=t.compat_tm[:, :G],
        it_ok=t.it_ok[:G, :, :T],
        ppn=t.ppn[:G, :, :T],
        it_ok_z=t.it_ok_z[:G, :, :T],
        zone_adm=t.zone_adm[:G],
        exist_ok=t.exist_ok[:G],
        exist_cap=t.exist_cap[:G])


def sharded_precompute(p: binpack.PackProblem, mesh: Mesh) -> binpack.PackTensors:
    """precompute() over a device mesh: pads to the mesh grid, shards inputs,
    runs the same kernel under GSPMD, gathers + un-pads the result.

    Works for single-process meshes (any number of local devices) and for
    meshes spanning multiple processes (a multi-host fleet joined via
    init_multihost()). In the multi-process case every process receives the
    FULL result — the downstream greedy pack (binpack.pack / the host
    oracle) is deterministic over identical tensors, so every host arrives
    at byte-identical launch decisions without any leader, the way the
    reference's scheduler replicas converge through the shared apiserver.
    The gather is a single XLA all-gather of the packed bitfields riding
    ICI/DCN; callers that post-process per group-row instead can use
    sharded_precompute_local() to skip it."""
    from ..obs.tracer import TRACER
    multiproc = is_multiprocess(mesh)
    out, padded, G, T = _run_sharded_kernel(p, mesh, replicate_out=multiproc)
    with TRACER.span("device.fetch"):
        if multiproc:
            raw = tuple(_fetch_replicated(o) for o in out)
        else:
            raw = jax.device_get(out)
    return _unpad_tensors(raw, padded, G, T)


def sharded_precompute_local(p: binpack.PackProblem, mesh: Mesh
                             ) -> "Tuple[binpack.PackTensors, list]":
    """Multi-host bandwidth optimization: compute the sharded precompute and
    fetch ONLY this process's group rows, skipping the cross-host result
    gather entirely. Returns ``(tensors, spans)`` where ``spans`` is
    local_result_slice()'s [start, stop) group-row list; tensor rows outside
    the spans are zeros and must not be read.

    Requires every local pods_groups-axis row to be catalog-complete on this
    process (true for make_solver_mesh() grids, where a process's devices
    tile whole rows); raises ValueError otherwise rather than returning
    rows with silent holes."""
    from ..obs.tracer import TRACER
    multiproc = is_multiprocess(mesh)
    if multiproc:
        me = jax.process_index()
        for r in range(mesh.devices.shape[0]):
            row_procs = {d.process_index for d in mesh.devices[r]}
            if me in row_procs and row_procs != {me}:
                raise ValueError(
                    f"pods_groups-axis row {r} spans processes "
                    f"{sorted(row_procs)}; local fetch needs catalog-"
                    "complete rows — use sharded_precompute() (replicated "
                    "gather) instead")
    out, padded, G, T = _run_sharded_kernel(p, mesh, replicate_out=False)
    with TRACER.span("device.fetch"):
        if multiproc:
            raw = tuple(_assemble_local(o) for o in out)
        else:
            raw = jax.device_get(out)
    tensors = _unpad_tensors(raw, padded, G, T)
    Gp = padded.group_req.shape[0]
    spans = [(start, min(stop, G))
             for start, stop in local_result_slice(mesh, Gp)
             if start < G]
    return tensors, spans


# --------------------------------------------------------------------------
# pods/groups-sharded pack
# --------------------------------------------------------------------------

def pack_shardable(p: binpack.PackProblem, template_limits,
                   group_ports, vol_group_counts) -> bool:
    """True when the hierarchical per-shard pack may engage: every shape
    whose shared mutable state couples groups ACROSS shards must be absent —
    existing nodes (shared capacity draw-down), nodepool limits (shared
    budget), host ports (cross-group conflict state), volume attach budgets
    (shared per-node dicts), minValues floors. The same conservative gate
    the warm-start restore uses, extended with the exist/limit rows."""
    has_exist = p.exist_enc is not None and p.exist_enc.mask.shape[0] > 0
    return (not has_exist
            and all(lm is None for lm in template_limits)
            and (group_ports is None or not any(group_ports))
            and vol_group_counts is None
            and (p.min_its is None or not bool((p.min_its > 0).any())))


def _shard_blocks(order: List[int], n_shards: int) -> List[List[int]]:
    """Round-robin interleave of the FFD order, one block per shard: every
    shard sees the full pod-size spectrum in descending order, so its local
    FFD keeps the gap-filling density the global order has. (Contiguous
    blocks hand shard 0 all the big pods and the small-pod shards nothing
    to fill gaps with — measured +17% nodes over interleave at the 100k x
    4k x 2000-group shape.)"""
    return [order[i::n_shards] for i in range(max(1, n_shards))]


def sharded_pack(p: binpack.PackProblem, t: binpack.PackTensors, groups,
                 n_shards: int,
                 initial_zone_counts: Optional[np.ndarray] = None,
                 exist_counts: Optional[np.ndarray] = None,
                 host_match_total: Optional[np.ndarray] = None,
                 max_workers: Optional[int] = None,
                 warm: Optional[binpack.WarmStart] = None
                 ) -> binpack.PackResult:
    """Hierarchical pods/groups-sharded pack (DEVIATIONS 22): carve the FFD
    order into ``n_shards`` round-robin interleaved blocks (_shard_blocks),
    pack each against its own cohort set in parallel (numpy releases the
    GIL on the wide scans), then
    reconcile cross-shard: merge the cohort sets and re-offer every shard's
    single-group remainder nodes to the merged winners so stragglers
    coalesce onto spare capacity another shard opened.

    ``warm`` composes the PR-6 checkpoint restore with the shard carve:
    each block packs under its own per-shard WarmStart (global token +
    shard identity, seed from warm.shard_seeds) and leaves its fresh seed
    in warm.result_shard_seeds; restore/match stats aggregate onto the
    parent. A group whose FFD position moved it to another shard breaks
    both affected blocks' token prefixes from its position on — that shard
    pair re-packs (cold past the prefix) while untouched shards replay.

    Decision contract vs the sequential oracle (pinned in
    tests/test_parallel_mesh.py):
    - pod_errors are EXACT: with the pack_shardable() gate holding (no
      existing nodes, limits, ports, volumes, minValues), placement failure
      is a per-group property of the tensors — boarding only redistributes
      pods that would place anyway.
    - claims may differ only in remainder-node composition; total placed
      pods are identical and the reconcile pass strictly reduces node count
      toward the oracle's.
    - a warm restore replays checkpointed per-shard state recorded from an
      identical-token prefix, so warm decisions are byte-identical to the
      cold sharded pack (the sharded churn fuzzer pins this).
    """
    from concurrent.futures import ThreadPoolExecutor

    from ..obs.tracer import TRACER

    def make_packer(w: Optional[binpack.WarmStart] = None):
        return binpack.Packer(
            p, t, groups, [None] * p.daemon_overhead.shape[0], [],
            initial_zone_counts=initial_zone_counts,
            exist_counts=exist_counts, host_match_total=host_match_total,
            warm=w)

    probe = make_packer()
    order = probe.ffd_order()
    blocks = _shard_blocks(order, max(1, n_shards))
    if len(blocks) <= 1:
        # degenerate single block == the sequential pack: the parent warm
        # applies directly (its seed interoperates with sequential passes)
        if warm is not None:
            return make_packer(warm).pack(order=order)
        return probe.pack(order=order)

    shard_warms: List[Optional[binpack.WarmStart]] = [None] * len(blocks)
    if warm is not None:
        seeds = (warm.shard_seeds
                 if warm.shard_seeds is not None
                 and len(warm.shard_seeds) == len(blocks)
                 else [None] * len(blocks))
        shard_warms = [
            binpack.WarmStart(
                global_token=warm.global_token + ("shard", i, len(blocks)),
                tokens=warm.tokens, seed=seeds[i])
            for i in range(len(blocks))]

    with TRACER.span("pack.shards", shards=len(blocks)):
        if warm is not None:
            packers = [make_packer(w) for w in shard_warms]
        else:
            packers = [probe] + [make_packer() for _ in blocks[1:]]

        def run(i: int) -> binpack.PackResult:
            return packers[i].pack(order=blocks[i])

        workers = max_workers or min(len(blocks), os.cpu_count() or 1)
        if workers > 1:
            with ThreadPoolExecutor(max_workers=workers) as ex:
                results = list(ex.map(run, range(len(blocks))))
        else:
            results = [run(i) for i in range(len(blocks))]

    if warm is not None:
        warm.result_shard_seeds = [w.result_seed for w in shard_warms]
        warm.restored_pos = sum(w.restored_pos for w in shard_warms)
        warm.matched = sum(w.matched for w in shard_warms)

    with TRACER.span("pack.reconcile") as sp:
        merged = _reconcile(p, t, groups, packers, results,
                            initial_zone_counts, exist_counts,
                            host_match_total, sp, blocks=blocks, warm=warm)
    return merged


def _group_per_node_cap(groups, g: int) -> Optional[int]:
    """The per-fresh-node cap the sequential pack applies to group g from
    its hostname-level constraint (0 = uncapped), or None when the group
    must not be re-offered at all (hostname pod affinity: all pods must
    share ONE node, which a split re-offer could violate)."""
    specs = groups[g].topo or []
    host_spec = next((s for s in specs
                      if s.kind in ("spread-host", "anti-host",
                                    "affinity-host")), None)
    if host_spec is None:
        return 0
    if host_spec.kind == "affinity-host":
        return None
    if host_spec.kind == "spread-host":
        return host_spec.max_skew if host_spec.self_select else 0
    return 1 if host_spec.self_select else 0


def _donor_rows(p, cs, groups, shards: int) -> np.ndarray:
    """[C] bool: single-node rows whose best surviving instance type still
    has >= the group-size-aware donor bar (binpack.donor_headroom) of
    relative headroom over the accumulated requests — the per-shard tail
    fragments the cross-shard pass coalesces. A row holding several groups
    takes the MOST EAGER (smallest) of its groups' bars: any small-group
    fragment aboard makes the re-offer worthwhile."""
    C = cs.C
    if C == 0:
        return np.zeros(0, dtype=bool)
    m_c = cs.m[:C]
    bar = np.fromiter(
        (min((binpack.donor_headroom(len(groups[g].pods), shards)
              for g in cs.pods_by_group[ci]),
             default=binpack.DONOR_HEADROOM_DENSE)
         for ci in range(C)),
        dtype=np.float64, count=C)
    need = p.daemon_overhead[m_c] + np.ceil(
        cs.requests[:C] * (1.0 + bar[:, None])).astype(np.int64)
    fits = (p.it_alloc[None, :, :] >= need[:, None, :]).all(axis=2)  # [C,T]
    return (cs.n[:C] == 1) & (fits & cs.it_set[:C]).any(axis=1)


def _reconcile(p, t, groups, packers, results, izc, exist_counts,
               host_match_total, span, blocks=None, warm=None
               ) -> binpack.PackResult:
    """Cross-shard pass over the merged cohort winners: fold every shard's
    cohorts into one set, holding back each shard's underfilled single-node
    tail rows (see _donor_rows); then re-pack the held-back pods through a
    sequential mini-pack over the merged set — boarding scan first, fresh
    efficient cohorts for the leftovers, original-template re-open as the
    guaranteed floor. Items run in global FFD order, so fragments from
    different shards recombine exactly the way the sequential pack mixes
    groups; a row holding a hostname-pod-affinity group is never held back
    (its pods must stay on ONE node, which a split re-offer could
    violate).

    With a ``warm`` whose tokens fully match the recorded pass, the fold is
    memoized (warm.reconcile_memo, persisted across passes by the
    ProblemState): the merged rows and the donor pool restore from the
    snapshot with group indices positionally remapped — the same trick as
    Packer._remap_checkpoint — and the per-row donor scan is skipped. The
    donor re-pack itself always runs (it consults current tensors and
    per-group caps), so decisions stay byte-identical either way."""
    rp = binpack.Packer(
        p, t, groups, [None] * p.daemon_overhead.shape[0], [],
        initial_zone_counts=izc, exist_counts=exist_counts,
        host_match_total=host_match_total)
    merged = rp.cohorts
    ffd_pos = {g: i for i, g in enumerate(rp.ffd_order())}
    # pods to re-pack, AGGREGATED per (group, zone, cap): one group's tail
    # fragments can sit in many donor rows across shards; one combined
    # re-offer makes the mini-pack cost O(distinct groups), not O(row
    # boardings), with identical placement semantics (_fill_cohorts splits
    # a combined fill across receivers exactly as per-fragment calls would)
    pool: dict = {}  # (g, zone_or_None, cap) -> [fill, donor_template_m]
    held = 0
    memo_token = None
    order_flat: tuple = ()
    if warm is not None and blocks is not None:
        memo_token = (warm.global_token,
                      tuple(tuple(warm.tokens[g] for g in b) for b in blocks))
        order_flat = tuple(g for b in blocks for g in b)
    memo = warm.reconcile_memo if warm is not None else None
    hit = (memo is not None and memo_token is not None
           and memo["token"] == memo_token
           and len(memo["order"]) == len(order_flat))
    if hit:
        # identical per-block tokens => the shard packs replayed the
        # recorded pass byte-for-byte (modulo group renumbering), so the
        # fold's output is the snapshot with indices remapped positionally
        remap = dict(zip(memo["order"], order_flat))
        C = memo["C"]
        cap = merged._cap
        while cap < max(C, 1):
            cap *= 2
        merged._cap = cap
        for name in binpack.CohortSet._ROW_FIELDS:
            src = memo["rows"][name]
            if name == "aboard":
                rem = np.zeros_like(src)
                for og, ng in remap.items():
                    rem[:, ng] = src[:, og]
                src = rem
            out = np.zeros((cap,) + src.shape[1:], src.dtype)
            out[:C] = src[:C]
            setattr(merged, name, out)
        merged.C = C
        merged.pods_by_group = [{remap[g]: f for g, f in d.items()}
                                for d in memo["pods_by_group"]]
        merged._okz_rows = {}
        pool = {(remap[g], zone, pc): list(v)
                for (g, zone, pc), v in memo["pool"].items()}
        held = memo["held"]
    else:
        for res in results:
            cs = res.cohorts
            donor = _donor_rows(p, cs, groups, len(results))
            for ci in range(cs.C):
                pbg = cs.pods_by_group[ci]
                caps = ([_group_per_node_cap(groups, g) for g in pbg]
                        if donor[ci] else [])
                if donor[ci] and all(c is not None for c in caps):
                    zone = int(cs.zone[ci])
                    zone = None if zone < 0 else zone
                    m = int(cs.m[ci])
                    held += 1
                    for (g, fill), cap in zip(pbg.items(), caps):
                        slot = pool.setdefault((g, zone, cap), [0, m])
                        slot[0] += fill
                else:
                    merged.append_row_from(cs, ci)
        if memo_token is not None:
            # snapshot BEFORE the donor re-pack mutates merged; indices in
            # the snapshot are THIS pass's — future hits remap positionally
            warm.reconcile_memo = {
                "token": memo_token, "order": order_flat, "C": merged.C,
                "rows": {name: getattr(merged, name)[:merged.C].copy()
                         for name in binpack.CohortSet._ROW_FIELDS},
                "pods_by_group": [dict(d) for d in merged.pods_by_group],
                "pool": {k: list(v) for k, v in pool.items()},
                "held": held}
    # merge shard errors (disjoint by group: each group packs in one shard)
    errors: dict = {}
    limit_constrained = False
    for res in results:
        errors.update(res.errors)
        limit_constrained |= res.limit_constrained
    boarded = 0
    # zone None (uncommitted) sorts as -1: one group can pool both a
    # zone-free and a zone-committed tail, and a mixed-type tuple compare
    # would raise on the tie through (ffd_pos, g, fill, m)
    items = sorted(((ffd_pos[g], g, fill, m, zone, cap)
                    for (g, zone, cap), (fill, m) in pool.items()),
                   key=lambda t: t[:4] + (-1 if t[4] is None else t[4], t[5]))
    for _, g, fill, m, zone, cap in items:
        placed = rp._fill_cohorts(g, fill, zone, cap)
        boarded += placed
        left = fill - placed
        if left > 0:
            left -= rp._place_new(g, left, zone, cap)
        if left > 0:
            # guaranteed floor: re-open on a donor's own template — the
            # donated pods fit there before, so they fit a fresh node too
            it_ok = (t.it_ok_z[g, m, :, zone] if zone is not None
                     else t.it_ok[g, m])
            per = rp._fill_ceiling(g, m, t.ppn[g, m], it_set) \
                if (it_set := it_ok & (t.ppn[g, m] >= 1)).any() else 0
            if cap:
                per = min(per, cap)
            opened = rp._open_nodes(g, m, zone, left, per) if per > 0 else 0
            if opened < left:
                raise RuntimeError(
                    "sharded-pack reconcile lost capacity re-opening "
                    f"tail fragments of group {g} ({left - opened} pods)")
    span.set(donor_rows=held, items=len(items), boarded_pods=boarded,
             merged="memo" if hit else "fold")
    out = binpack.PackResult()
    out.errors = errors
    out.limit_constrained = limit_constrained
    out.cohorts = merged
    return out


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   auto: bool = False) -> int:
    """Join a multi-host solver fleet via JAX's distributed runtime, the
    analog of the reference's NCCL/MPI bootstrap (SURVEY §5 distributed
    backend). Idempotent; returns the process count.

    Each host contributes its local chips to the global device set;
    `make_solver_mesh()` then builds the (pods_groups × catalog) mesh over
    `jax.devices()` — which, after initialization, spans every host — and
    GSPMD partitions the feasibility precompute across them. The kernel
    has no cross-shard contractions, so the only DCN traffic is the result
    gather (one packed-bitfield fetch per solve; see sharded_precompute).

    Parameters default to the standard JAX env bootstrap
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID or the
    cloud-TPU metadata server). Call before any other JAX API; single-host
    runs skip the distributed service entirely."""
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    # NOTE: deliberately no TPU_WORKER_HOSTNAMES sniffing — single-host TPU
    # plugins set it too; multi-host intent must be explicit. On a cloud-TPU
    # pod slice where the coordinator comes from the metadata server (no env
    # vars at all), pass auto=True to hand bootstrap entirely to JAX.
    bootstrap_available = (auto
                           or coordinator_address is not None
                           or num_processes is not None
                           or process_id is not None
                           or "JAX_COORDINATOR_ADDRESS" in os.environ)
    if num_processes == 1 or not bootstrap_available:
        return 1  # explicitly (or evidently) single host: no service needed
    already = getattr(jax.distributed, "is_initialized", None)
    if already is None or not already():
        # None values pass through so jax can auto-detect from its own
        # bootstrap sources (env vars / cloud-TPU metadata)
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    return jax.process_count()


def local_result_slice(mesh: Mesh, n_groups: int,
                       process_index: Optional[int] = None
                       ) -> "list[Tuple[int, int]]":
    """The [start, stop) group-row spans this process computed — multi-host
    callers that shard the DOWNSTREAM packing per host use these to skip
    fetching rows another host owns (the gather at sharded_precompute
    otherwise pulls the full result to every host).

    Returns a list of contiguous spans: mesh_utils.create_device_mesh may
    reorder devices for topology, so one process's pods_groups-axis rows
    need not be contiguous — collapsing them to a single [min, max) range
    would overlap other hosts' slices and double-pack their groups."""
    if process_index is None:
        process_index = jax.process_index()
    n_shards = mesh.shape[PODS_GROUPS_AXIS]
    per = math.ceil(n_groups / n_shards)
    local_rows = sorted(
        {idx[0] for idx, dev in np.ndenumerate(mesh.devices)
         if dev.process_index == process_index})
    spans: "list[Tuple[int, int]]" = []
    for row in local_rows:
        start = row * per
        stop = min((row + 1) * per, n_groups)
        if start >= stop:
            continue
        if spans and spans[-1][1] == start:
            spans[-1] = (spans[-1][0], stop)  # merge adjacent rows
        else:
            spans.append((start, stop))
    return spans


# -- device-loss degradation ladder (ISSUE 20) --------------------------------
# A device error mid-dispatch used to fail the whole sharded pass and trip
# the GLOBAL solver breaker (host fallback for every subsequent pass until
# cooldown). The ladder instead re-places the solve WITHIN the same pass:
# full mesh -> the largest pow2 carve of surviving devices -> a single
# surviving device -> (exhausted) the caller's host oracle. Each lost
# device feeds its OWN SolverCircuitBreaker, so a healthy fleet minus one
# chip keeps solving on silicon, and the half-open probe re-admits the
# device once it answers again. Decision parity across rungs is free:
# sharded_precompute is bit-identical to binpack.precompute for ANY mesh
# (pinned by the parity tests), so every rung yields the same tensors.

#: per-device breaker tuning: a lost chip usually stays lost for seconds
#: (preemption, link flap), so a short threshold opens fast and the
#: half-open probe re-admits on the first healthy dispatch
DEVICE_BREAKER_THRESHOLD = int(os.environ.get(
    "KARPENTER_DEVICE_BREAKER_THRESHOLD", "3"))
DEVICE_BREAKER_COOLDOWN = float(os.environ.get(
    "KARPENTER_DEVICE_BREAKER_COOLDOWN", "30"))

_DEVICE_BREAKERS: dict = {}
_CARVE_CACHE: dict = {}


class DeviceLadderExhausted(Exception):
    """Every rung of the device-loss ladder failed this pass. The caller
    (TensorScheduler._solve) serves the host oracle WITHOUT counting the
    global breaker — each lost device already fed its own."""


def device_breaker(device_id: int, now=None):
    """The per-device SolverCircuitBreaker (process-wide: device identity
    outlives any one mesh object). publish=False — only the global solver
    breaker owns the circuit-state gauge."""
    from ..provisioning.tensor_scheduler import SolverCircuitBreaker
    b = _DEVICE_BREAKERS.get(int(device_id))
    if b is None:
        b = SolverCircuitBreaker(threshold=DEVICE_BREAKER_THRESHOLD,
                                 cooldown=DEVICE_BREAKER_COOLDOWN, now=now)
        _DEVICE_BREAKERS[int(device_id)] = b
    return b


def reset_device_breakers() -> None:
    """Test/bench isolation: drop every per-device breaker (and the carve
    cache, whose meshes may reference revived devices)."""
    _DEVICE_BREAKERS.clear()
    _CARVE_CACHE.clear()


def _carve_mesh(live) -> Mesh:
    """A mesh over the largest power-of-two prefix of the surviving
    devices (pow2 keeps the padded shard shapes in the compile-cache
    buckets; the carve is cached by device-id tuple so a repeated
    degradation never rebuilds it)."""
    n = 1 << (len(live).bit_length() - 1)
    picked = tuple(sorted(live, key=lambda d: int(d.id))[:n])
    key = tuple(int(d.id) for d in picked)
    m = _CARVE_CACHE.get(key)
    if m is None:
        m = make_solver_mesh(devices=list(picked))
        _CARVE_CACHE[key] = m
    return m


def resilient_precompute(p: binpack.PackProblem, mesh: Mesh
                         ) -> binpack.PackTensors:
    """sharded_precompute behind the degradation ladder: on a device loss
    the pass re-places itself on the surviving carve (then a single
    survivor) instead of failing. Raises DeviceLadderExhausted only when
    no device is willing to solve."""
    from ..metrics.registry import STATE_AUDIT
    devices = list(mesh.devices.flat)
    down: set = set()
    while True:
        live = [d for d in devices
                if int(d.id) not in down and device_breaker(d.id).allow()]
        probing = [d for d in live
                   if device_breaker(d.id).state != "closed"]
        try:
            if len(live) == len(devices):
                binpack.check_devices([int(d.id) for d in live])
                out = sharded_precompute(p, mesh)
                rung = "mesh"
            elif len(live) >= 1:
                carve = _carve_mesh(live)
                live = list(carve.devices.flat)
                probing = [d for d in live
                           if device_breaker(d.id).state != "closed"]
                binpack.check_devices([int(d.id) for d in live])
                out = sharded_precompute(p, carve)
                rung = "carve" if len(live) > 1 else "single"
            else:
                raise DeviceLadderExhausted(
                    f"all {len(devices)} mesh devices down or "
                    "breaker-open")
        except DeviceLadderExhausted:
            raise
        except binpack.DeviceLossError as e:
            device_breaker(e.device_id).record_failure()
            down.add(int(e.device_id))
            STATE_AUDIT.inc({"layer": "device", "outcome": "killed"})
            continue
        except Exception:
            # un-attributed dispatch failure: every participant takes the
            # blame and the pass drops a rung. Over-counting is safe — a
            # healthy device's breaker re-closes on the next pass's
            # half-open probe — while under-counting would retry the same
            # dead rung forever.
            for d in live:
                device_breaker(d.id).record_failure()
                down.add(int(d.id))
            STATE_AUDIT.inc({"layer": "device", "outcome": "killed"},
                            len(live))
            if not live:
                raise
            continue
        for d in live:
            device_breaker(d.id).record_success()
        if probing:
            STATE_AUDIT.inc({"layer": "device", "outcome": "readmitted"},
                            len(probing))
        if rung != "mesh":
            STATE_AUDIT.inc({"layer": "device", "outcome": rung})
        return out
