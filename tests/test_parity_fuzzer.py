"""Adversarial tensor-vs-oracle parity fuzzer.

BASELINE.md's north-star clause says the tensor path must land within 2% of
the solver it replaces; the scenario batteries pin known shapes, this
fuzzer sweeps the space BETWEEN them: seeded random pods x pools x zones x
taints x spreads x affinities, solved by both paths, asserting

- exact agreement on WHICH pods fail (by name, not just count), and
- node-count delta <= max(1, 2%).

Every case is seed-pinned (deterministic rng), so a divergence reproduces
by running its seed. The generator stays inside the tensor kernel's
supported feature set (zone/hostname spreads, zone/hostname affinity,
hostname anti-affinity, selectors, taints) with per-deployment unique
label values — kernel-unsupported shapes have their own fallback tests in
test_partition.py / test_binpack_parity.py.
"""

import random

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import Taint, Toleration
from karpenter_tpu.cloudprovider import kwok

from factories import (affinity_term, make_nodepool, make_pod,
                       make_scheduler, spread_hostname, spread_zone)
from test_binpack_parity import host_solve, tensor_solve

ZONES = ("test-zone-a", "test-zone-b", "test-zone-c")
CPUS = ("100m", "250m", "500m", "1", "1500m", "2", "3")
MEMS = ("128Mi", "256Mi", "512Mi", "1Gi", "2Gi", "4Gi")


def gen_nodepools(rng: random.Random):
    pools = []
    n_pools = rng.choice((1, 1, 1, 2, 2, 3))
    for i in range(n_pools):
        kwargs = {"name": f"pool-{i}"}
        if rng.random() < 0.35:
            kwargs["taints"] = [Taint(key=f"team-{i}", value="x")]
        if rng.random() < 0.3:
            from karpenter_tpu.api.objects import NodeSelectorRequirement
            zones = rng.sample(ZONES, rng.choice((1, 2)))
            kwargs["requirements"] = [NodeSelectorRequirement(
                key=api_labels.LABEL_TOPOLOGY_ZONE, operator="In",
                values=tuple(zones))]
        if rng.random() < 0.25:
            kwargs["limits"] = {"cpu": str(rng.choice((8, 16, 64)))}
        kwargs["weight"] = rng.choice((None, 1, 10, 50))
        pools.append(make_nodepool(**kwargs))
    return pools


def gen_pods(rng: random.Random, pools):
    """2-6 deployments of 3-18 pods each; every deployment gets its own
    label value so selectors never span groups (a kernel support
    boundary with its own fallback tests)."""
    pods = []
    n_deploys = rng.randint(2, 6)
    for d in range(n_deploys):
        n = rng.randint(3, 18)
        label_val = f"d{d}"
        kwargs = {
            "cpu": rng.choice(CPUS),
            "memory": rng.choice(MEMS),
            "labels": {"app": label_val},
        }
        tainted = [p for p in pools if p.spec.template.spec.taints]
        if tainted and rng.random() < 0.5:
            kwargs["tolerations"] = [
                Toleration(key=t.key, operator="Exists")
                for p in tainted for t in p.spec.template.spec.taints]
        if rng.random() < 0.25:
            kwargs["node_selector"] = {
                api_labels.LABEL_TOPOLOGY_ZONE: rng.choice(ZONES)}
        shape = rng.random()
        if shape < 0.2:
            kwargs["spread"] = [spread_zone(
                max_skew=rng.choice((1, 1, 2)), key="app", value=label_val)]
        elif shape < 0.3:
            kwargs["spread"] = [spread_hostname(
                max_skew=1, key="app", value=label_val)]
        elif shape < 0.4:
            kwargs["pod_affinity"] = [affinity_term(
                rng.choice((api_labels.LABEL_TOPOLOGY_ZONE,
                            api_labels.LABEL_HOSTNAME)),
                key="app", value=label_val)]
        elif shape < 0.5:
            kwargs["pod_anti_affinity"] = [affinity_term(
                api_labels.LABEL_HOSTNAME, key="app", value=label_val)]
        if rng.random() < 0.06:
            kwargs["cpu"] = "1000"  # unschedulable: no type holds 1000 cores
        for i in range(n):
            pods.append(make_pod(name=f"fz-{d}-{i:03d}", **kwargs))
    return pods


def gen_catalog(rng: random.Random):
    its = kwok.construct_instance_types()
    n = rng.choice((24, 48, 96, 144))
    if n >= len(its):
        return its
    # a contiguous prefix keeps small/large family balance; an offset adds
    # variety without dropping every small type
    off = rng.choice((0, 0, 4, 8))
    return its[off:off + n]


def names(pods):
    return sorted(p.metadata.name for p in pods)


def error_names(results, pods):
    by_uid = {p.uid: p.metadata.name for p in pods}
    return sorted(by_uid.get(uid, uid) for uid in results.pod_errors)


def run_seed(seed: int):
    """The PRODUCTION parity contract per scenario:

    1. If the production TensorScheduler fell back (documented reasons
       only: limit-pressure errors, relaxable preferences, inexpressible
       batch), its results ARE host results — exact equality.
    2. Otherwise tensor pod_errors must be a SUBSET of the oracle's, by
       name: the tensor path never strands a pod the oracle places.
    3. With equal error sets, node count within max(1, 2%) (BASELINE.md
       north-star clause).
    4. A strict subset (tensor places MORE pods) happens only when the
       oracle's greedy order strands required-affinity pods behind a
       shared in-flight claim — pinned in DEVIATIONS.md — and then the
       extra placements may add nodes, so the bound widens by the number
       of extra pods placed.
    """
    from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
    rng = random.Random(seed)
    pools = gen_nodepools(rng)
    its = {p.name: gen_catalog(rng) for p in pools}
    # identical pod batches for each path: the generator is deterministic
    # per seed, and solving mutates pod state (topology records,
    # preference relaxation), so each path gets its own copy
    pods_t = gen_pods(random.Random(seed + 1), pools)
    pods_h = gen_pods(random.Random(seed + 1), pools)
    assert names(pods_t) == names(pods_h)
    ts = TensorScheduler(pools, its)  # production config: fallback armed
    t = ts.solve(pods_t)
    h = host_solve(pools, its, pods_h)
    et, eh = error_names(t, pods_t), error_names(h, pods_h)
    th, hh = len(t.new_nodeclaims), len(h.new_nodeclaims)
    if ts.fallback_reason:
        # host-solved: byte-identical verdicts expected
        assert et == eh, (seed, ts.fallback_reason)
        assert th == hh, (seed, ts.fallback_reason, th, hh)
        return th, hh
    assert set(et) <= set(eh), (
        seed, f"tensor stranded pods the oracle places: "
        f"{sorted(set(et) - set(eh))[:5]}")
    extra_placed = len(set(eh) - set(et))
    if extra_placed == 0:
        assert abs(th - hh) <= max(1, round(0.02 * hh)), (seed, th, hh)
    else:
        # oracle strandings (DEVIATIONS: affinity-group co-pack): the
        # affinity groups involved must actually exist, and the node bound
        # widens by the extra pods placed
        assert any(p.spec.affinity is not None for p in pods_t), seed
        assert abs(th - hh) <= max(1, round(0.02 * hh)) + extra_placed, \
            (seed, th, hh, extra_placed)
    return th, hh


# seed-pinned corpus: any failure names its seed for replay
@pytest.mark.parametrize("seed", list(range(1000, 1040)))
def test_fuzz_parity(seed):
    run_seed(seed)


def test_fuzz_covers_the_feature_space():
    """Meta-check: across the pinned seeds the generator actually exercised
    multi-pool, taints, selectors, spreads, affinities, and unschedulable
    pods — guarding against a silent generator regression that would turn
    the fuzzer into a trivial-parity rubber stamp."""
    saw = {"multi_pool": False, "taints": False, "selector": False,
           "spread": False, "affinity": False, "unschedulable": False}
    for seed in range(1000, 1040):
        rng = random.Random(seed)
        pools = gen_nodepools(rng)
        pods = gen_pods(random.Random(seed + 1), pools)
        saw["multi_pool"] |= len(pools) > 1
        saw["taints"] |= any(p.spec.template.spec.taints for p in pools)
        saw["selector"] |= any(p.spec.node_selector for p in pods)
        saw["spread"] |= any(p.spec.topology_spread_constraints for p in pods)
        saw["affinity"] |= any(p.spec.affinity is not None for p in pods)
        saw["unschedulable"] |= any(
            p.requests().get("cpu", 0) >= 1000_000 for p in pods)
    missing = [k for k, v in saw.items() if not v]
    assert not missing, f"fuzzer never generated: {missing}"
