"""Scenario port of /root/reference/pkg/controllers/disruption/
emptiness_test.go (773 LoC): consolidatable-condition gating, multi-node
deletes, daemonset/terminating-pod emptiness semantics, pending-pod
awareness, the consolidateAfter TTL, and the eligible-nodes metric."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import COND_CONSOLIDATABLE, NodeClaim
from karpenter_tpu.api.objects import Node, ObjectMeta, OwnerReference, Pod
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_disruption import NodeClaimDisruptionMarker
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.node_termination import NodeTermination
from karpenter_tpu.disruption.controller import (DisruptionController,
                                                 OrchestrationQueue)
from karpenter_tpu.kube.store import Store
from karpenter_tpu.metrics.registry import DISRUPTION_ELIGIBLE_NODES
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod

OD = {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_ON_DEMAND}


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    queue = OrchestrationQueue(store, cluster, clock)
    disruption = DisruptionController(store, cluster, provisioner, queue, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock),
                 NodeClaimDisruptionMarker(store, cluster, provider, clock),
                 NodeTermination(store, cluster, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr = \
        clock, store, cluster, provider, mgr
    e.provisioner, e.queue, e.disruption = provisioner, queue, disruption
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


def disrupt(env, rounds=8):
    for _ in range(rounds):
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        env.clock.step(8)


def strand_empty(env, n=1, pool_name="default", consolidate_after=None,
                 cpu="2500m"):
    """Provision n single-pod nodes, then delete the pods so the nodes sit
    empty; returns after the consolidatable TTL (if any) has elapsed."""
    pool = make_nodepool(name=pool_name)
    if consolidate_after is not None:
        pool.spec.disruption.consolidate_after = consolidate_after
    env.store.create(pool)
    pods = []
    for i in range(n):
        p = make_pod(cpu=cpu, name=f"empt-{i}", node_selector=dict(OD))
        env.store.create(p)
        pods.append(p)
        settle(env, rounds=3)
    for p in pods:
        env.store.delete(p)
    settle(env)
    env.clock.step((consolidate_after or 0.0) + 21)
    settle(env, rounds=2)
    return pool


class TestConsolidatableGating:
    """emptiness_test.go:392-472."""

    def test_deletes_empty_consolidatable_node(self, env):
        strand_empty(env)
        disrupt(env)
        assert env.store.list(Node) == []
        assert env.store.list(NodeClaim) == []

    def test_ignores_node_without_consolidatable_condition(self, env):
        strand_empty(env)
        nc = env.store.list(NodeClaim)[0]
        nc.conditions.clear(COND_CONSOLIDATABLE)
        env.store.update(nc)
        # run only the disruption pass (the marker would re-set the condition)
        env.disruption.reconcile()
        env.queue.reconcile()
        assert len(env.store.list(Node)) == 1

    def test_ignores_consolidatable_false(self, env):
        strand_empty(env)
        nc = env.store.list(NodeClaim)[0]
        nc.conditions.set_false(COND_CONSOLIDATABLE, reason="NotYet")
        env.store.update(nc)
        env.disruption.reconcile()
        env.queue.reconcile()
        assert len(env.store.list(Node)) == 1

    def test_waits_for_consolidate_after_ttl(self, env):
        """emptiness_test.go:733+: the node TTL (consolidateAfter) must
        elapse before emptiness fires."""
        pool = make_nodepool(name="default")
        pool.spec.disruption.consolidate_after = 120.0
        env.store.create(pool)
        pod = make_pod(cpu="2500m", node_selector=dict(OD))
        env.store.create(pod)
        settle(env, rounds=3)
        env.store.delete(pod)
        settle(env)
        env.clock.step(30)  # < TTL
        settle(env, rounds=2)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        assert len(env.store.list(Node)) == 1
        env.clock.step(120)  # TTL elapses
        settle(env, rounds=2)
        disrupt(env)
        assert env.store.list(Node) == []


class TestEmptinessSemantics:
    """emptiness_test.go:473-732."""

    def test_deletes_multiple_empty_nodes(self, env):
        strand_empty(env, n=3)
        disrupt(env, rounds=12)  # default 10% budget trims each pass
        assert env.store.list(Node) == []
        assert env.store.list(NodeClaim) == []

    def test_daemonset_only_node_is_empty(self, env):
        strand_empty(env)
        node = env.store.list(Node)[0]
        ds_pod = make_pod(cpu="100m")
        ds_pod.is_daemonset_pod = True
        ds_pod.metadata.owner_refs.append(
            OwnerReference(kind="DaemonSet", name="fluentd"))
        ds_pod.spec.node_name = node.name
        env.store.create(ds_pod)
        settle(env)
        disrupt(env)
        assert env.store.list(Node) == []

    def test_terminating_deployment_pods_are_empty(self, env):
        """emptiness_test.go:611-675: ReplicaSet-owned pods already being
        evicted don't hold the node."""
        strand_empty(env)
        node = env.store.list(Node)[0]
        for i in range(3):
            p = make_pod(cpu="100m", name=f"rs-pod-{i}")
            p.metadata.owner_refs.append(
                OwnerReference(kind="ReplicaSet", name="rs-1"))
            p.metadata.finalizers.append("test/hold")  # keep it terminating
            p.spec.node_name = node.name
            env.store.create(p)
            env.store.delete(p)  # stamps deletionTimestamp, pod remains
        settle(env)
        disrupt(env, rounds=4)
        # the emptiness decision fires: the claim is deleting (full drain
        # can't finish here because the test finalizer pins the pods)
        [nc] = env.store.list(NodeClaim)
        assert nc.metadata.deletion_timestamp is not None

    def test_terminating_statefulset_pod_is_not_empty(self, env):
        """emptiness_test.go:676-732: sticky identity — the replacement pod
        can't exist until the old one dies, so the node is NOT empty."""
        strand_empty(env)
        node = env.store.list(Node)[0]
        p = make_pod(cpu="100m", name="ss-pod-0")
        p.metadata.owner_refs.append(
            OwnerReference(kind="StatefulSet", name="ss-1"))
        p.metadata.finalizers.append("test/hold")
        p.spec.node_name = node.name
        env.store.create(p)
        env.store.delete(p)
        settle(env)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        env.clock.step(20)
        env.queue.reconcile()
        settle(env, rounds=2)
        assert len(env.store.list(Node)) == 1
        [nc] = env.store.list(NodeClaim)
        assert nc.metadata.deletion_timestamp is None  # emptiness never fired

    def test_considers_pending_pods(self, env):
        """emptiness_test.go:497-554: a huge pending pod that needs the
        node's capacity keeps the (nearly empty) node alive."""
        pool = make_nodepool(name="default")
        env.store.create(pool)
        big = make_pod(cpu="30", memory="16Gi", name="big-seed",
                       node_selector=dict(OD))
        env.store.create(big)
        settle(env, rounds=3)
        assert len(env.store.list(Node)) == 1
        node = env.store.list(Node)[0]
        # swap the big seed for a small pod: node is now mostly idle
        env.store.delete(big)
        small = make_pod(cpu="1", name="small")
        small.spec.node_name = node.name
        env.store.create(small)
        settle(env)
        env.clock.step(21)
        settle(env, rounds=2)
        # a pending pod that only fits on this node (everything else would
        # need a new claim, which the simulation must not prefer silently)
        huge = make_pod(cpu="28", memory="8Gi", name="huge",
                        node_selector=dict(OD))
        env.store.create(huge)
        # single disruption pass BEFORE the provisioner binds the pod
        env.disruption.reconcile()
        env.queue.reconcile()
        # the node survives: the simulation counts the pending pod
        assert len(env.store.list(Node)) >= 1
        assert env.store.get(Node, node.name) is not None


class TestEligibleNodesMetric:
    """emptiness_test.go:86-114."""

    def test_eligible_nodes_gauge(self, env):
        strand_empty(env, n=2)
        env.disruption.reconcile()
        assert DISRUPTION_ELIGIBLE_NODES.value({"reason": "empty"}) >= 0
        # after the fleet drains there is nothing eligible
        disrupt(env)
        env.disruption.reconcile()
        assert DISRUPTION_ELIGIBLE_NODES.value({"reason": "empty"}) == 0
