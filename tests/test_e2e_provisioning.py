"""End-to-end provisioning loop against the kwok simulated provider:
pending pods -> batcher -> tensor solve -> NodeClaims -> launch -> register ->
initialize -> bind; then node deletion -> drain -> reschedule."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.node_termination import NodeTermination
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    mgr.register(provisioner,
                 PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock),
                 NodeTermination(store, cluster, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr, e.provisioner = \
        clock, store, cluster, provider, mgr, provisioner
    return e


def settle(env, rounds=6):
    """Run the control loop through the batch window until quiet."""
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)  # pass the batch idle window
    env.mgr.run_until_quiet()


class TestProvisioningE2E:
    def test_pods_get_nodes_and_bind(self, env):
        env.store.create(make_nodepool(name="default"))
        for p in make_pods(10, cpu="500m", memory="256Mi"):
            env.store.create(p)
        settle(env)
        pods = env.store.list(Pod)
        assert all(p.spec.node_name for p in pods), \
            [(p.name, p.spec.node_name) for p in pods]
        nodes = env.store.list(Node)
        assert nodes, "no nodes fabricated"
        for n in nodes:
            assert n.metadata.labels.get(api_labels.NODE_REGISTERED_LABEL_KEY) == "true"
            assert n.metadata.labels.get(api_labels.NODE_INITIALIZED_LABEL_KEY) == "true"
            assert not any(t.key == api_labels.UNREGISTERED_TAINT_KEY
                           for t in n.spec.taints)
        claims = env.store.list(NodeClaim)
        assert all(c.launched() and c.registered() and c.initialized()
                   for c in claims)
        assert env.cluster.synced()

    def test_batch_window_delays_solve(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="500m"))
        env.mgr.run_until_quiet()  # batch window still open: no claims yet
        assert env.store.list(NodeClaim) == []
        env.clock.step(1.1)
        env.mgr.run_until_quiet()
        assert len(env.store.list(NodeClaim)) == 1

    def test_no_nodepool_means_pod_errors(self, env):
        env.store.create(make_pod())
        settle(env)
        assert env.store.list(NodeClaim) == []
        assert env.store.list(Node) == []

    def test_node_delete_drains_and_reschedules(self, env):
        env.store.create(make_nodepool(name="default"))
        for p in make_pods(5, cpu="500m"):
            env.store.create(p)
        settle(env)
        nodes = env.store.list(Node)
        assert nodes
        first = nodes[0]
        bound_before = [p for p in env.store.list(Pod)
                        if p.spec.node_name == first.name]
        assert bound_before
        env.store.delete(first)
        settle(env)
        # node + its claim are gone; every pod is bound somewhere live
        assert env.store.get(Node, first.name) is None
        live_nodes = {n.name for n in env.store.list(Node)}
        for p in env.store.list(Pod):
            assert p.spec.node_name in live_nodes

    def test_pdb_blocks_drain_until_removed(self, env):
        from karpenter_tpu.api.objects import LabelSelector, ObjectMeta
        from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m", labels={"app": "guarded"})
        env.store.create(pod)
        settle(env)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "guarded"}),
                         max_unavailable="0")))
        node = env.store.list(Node)[0]
        env.store.delete(node)
        settle(env, rounds=3)
        # drain is blocked: node still present, pod still bound there
        live = env.store.get(Node, node.name)
        assert live is not None
        assert env.store.get(Pod, pod.name, pod.namespace).spec.node_name \
            == node.name
        # removing the PDB unblocks the drain
        env.store.delete(env.store.get(
            PodDisruptionBudget, "pdb", "default"))
        settle(env, rounds=4)
        assert env.store.get(Node, node.name) is None

    def test_termination_grace_period_forces_drain(self, env):
        pool = make_nodepool(name="default")
        pool.spec.template.spec.termination_grace_period = 60.0
        env.store.create(pool)
        pod = make_pod(cpu="500m")
        pod.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.create(pod)
        settle(env)
        node = env.store.list(Node)[0]
        env.store.delete(node)
        settle(env, rounds=2)
        # do-not-disrupt blocks the graceful drain
        assert env.store.get(Node, node.name) is not None
        env.clock.step(61)  # past the TGP deadline
        settle(env, rounds=4)
        assert env.store.get(Node, node.name) is None
        # pod rescheduled onto replacement capacity
        live = env.store.get(Pod, pod.name, pod.namespace)
        assert live is not None and live.spec.node_name

    def test_existing_capacity_reused(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="100m", memory="64Mi"))
        settle(env)
        n_nodes = len(env.store.list(Node))
        assert n_nodes == 1
        # a second small pod fits the already-provisioned node
        env.store.create(make_pod(cpu="100m", memory="64Mi"))
        settle(env)
        assert len(env.store.list(Node)) == n_nodes
        assert all(p.spec.node_name for p in env.store.list(Pod))
