"""End-to-end provisioning loop against the kwok simulated provider:
pending pods -> batcher -> tensor solve -> NodeClaims -> launch -> register ->
initialize -> bind; then node deletion -> drain -> reschedule."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.node_termination import NodeTermination
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    mgr.register(provisioner,
                 PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock),
                 NodeTermination(store, cluster, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr, e.provisioner = \
        clock, store, cluster, provider, mgr, provisioner
    return e


def settle(env, rounds=6):
    """Run the control loop through the batch window until quiet."""
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)  # pass the batch idle window
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


class TestProvisioningE2E:
    def test_pods_get_nodes_and_bind(self, env):
        env.store.create(make_nodepool(name="default"))
        for p in make_pods(10, cpu="500m", memory="256Mi"):
            env.store.create(p)
        settle(env)
        pods = env.store.list(Pod)
        assert all(p.spec.node_name for p in pods), \
            [(p.name, p.spec.node_name) for p in pods]
        nodes = env.store.list(Node)
        assert nodes, "no nodes fabricated"
        for n in nodes:
            assert n.metadata.labels.get(api_labels.NODE_REGISTERED_LABEL_KEY) == "true"
            assert n.metadata.labels.get(api_labels.NODE_INITIALIZED_LABEL_KEY) == "true"
            assert not any(t.key == api_labels.UNREGISTERED_TAINT_KEY
                           for t in n.spec.taints)
        claims = env.store.list(NodeClaim)
        assert all(c.launched() and c.registered() and c.initialized()
                   for c in claims)
        assert env.cluster.synced()

    def test_batch_window_delays_solve(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="500m"))
        env.mgr.run_until_quiet()  # batch window still open: no claims yet
        assert env.store.list(NodeClaim) == []
        env.clock.step(1.1)
        env.mgr.run_until_quiet()
        assert len(env.store.list(NodeClaim)) == 1

    def test_no_nodepool_means_pod_errors(self, env):
        env.store.create(make_pod())
        settle(env)
        assert env.store.list(NodeClaim) == []
        assert env.store.list(Node) == []

    def test_node_delete_drains_and_reschedules(self, env):
        env.store.create(make_nodepool(name="default"))
        for p in make_pods(5, cpu="500m"):
            env.store.create(p)
        settle(env)
        nodes = env.store.list(Node)
        assert nodes
        first = nodes[0]
        bound_before = [p for p in env.store.list(Pod)
                        if p.spec.node_name == first.name]
        assert bound_before
        env.store.delete(first)
        settle(env)
        # node + its claim are gone; every pod is bound somewhere live
        assert env.store.get(Node, first.name) is None
        live_nodes = {n.name for n in env.store.list(Node)}
        for p in env.store.list(Pod):
            assert p.spec.node_name in live_nodes

    def test_pdb_blocks_drain_until_removed(self, env):
        from karpenter_tpu.api.objects import LabelSelector, ObjectMeta
        from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m", labels={"app": "guarded"})
        env.store.create(pod)
        settle(env)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "guarded"}),
                         max_unavailable="0")))
        node = env.store.list(Node)[0]
        env.store.delete(node)
        settle(env, rounds=3)
        # drain is blocked: node still present, pod still bound there
        live = env.store.get(Node, node.name)
        assert live is not None
        assert env.store.get(Pod, pod.name, pod.namespace).spec.node_name \
            == node.name
        # removing the PDB unblocks the drain
        env.store.delete(env.store.get(
            PodDisruptionBudget, "pdb", "default"))
        settle(env, rounds=4)
        assert env.store.get(Node, node.name) is None

    def test_termination_grace_period_forces_drain(self, env):
        pool = make_nodepool(name="default")
        pool.spec.template.spec.termination_grace_period = 60.0
        env.store.create(pool)
        pod = make_pod(cpu="500m")
        pod.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.create(pod)
        settle(env)
        node = env.store.list(Node)[0]
        env.store.delete(node)
        settle(env, rounds=2)
        # do-not-disrupt blocks the graceful drain
        assert env.store.get(Node, node.name) is not None
        env.clock.step(61)  # past the TGP deadline
        settle(env, rounds=4)
        assert env.store.get(Node, node.name) is None
        # pod rescheduled onto replacement capacity
        live = env.store.get(Pod, pod.name, pod.namespace)
        assert live is not None and live.spec.node_name

    def test_existing_capacity_reused(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="100m", memory="64Mi"))
        settle(env)
        n_nodes = len(env.store.list(Node))
        assert n_nodes == 1
        # a second small pod fits the already-provisioned node
        env.store.create(make_pod(cpu="100m", memory="64Mi"))
        settle(env)
        assert len(env.store.list(Node)) == n_nodes
        assert all(p.spec.node_name for p in env.store.list(Pod))


class TestDeletingNodeCarryover:
    """suite_test.go:3443-3645: which pods on a deleting node get modeled
    as reschedulable while capacity is replaced."""

    def _deleting_node_with(self, env, pod):
        env.store.create(make_nodepool(name="default"))
        anchor = make_pod(cpu="500m", name="anchor")
        env.store.create(anchor)
        settle(env)
        node = env.store.list(Node)[0]
        pod.spec.node_name = node.name
        pod.status.phase = "Running"
        env.store.create(pod)
        settle(env)
        env.store.delete(node)
        return node

    def test_terminal_pods_not_rescheduled(self, env):
        """suite_test.go:3469-3495: Succeeded/Failed pods on a deleting
        node need no replacement capacity."""
        done = make_pod(cpu="3500m", name="finished")
        node = self._deleting_node_with(env, done)
        done.status.phase = "Succeeded"
        env.store.update(done)
        settle(env)
        assert env.store.get(Node, node.name) is None
        # only the anchor pod needed a home: one live node, no extra
        live = env.store.list(Node)
        assert len(live) == 1
        assert env.store.get(Pod, "anchor", "default").spec.node_name == \
            live[0].name

    def test_daemonset_pods_not_rescheduled(self, env):
        """suite_test.go:3496-3552."""
        from karpenter_tpu.api.objects import OwnerReference
        ds = make_pod(cpu="3500m", name="ds-pod")
        ds.metadata.owner_refs.append(
            OwnerReference(kind="DaemonSet", name="ds", uid="u1"))
        node = self._deleting_node_with(env, ds)
        settle(env)
        assert env.store.get(Node, node.name) is None
        live = env.store.list(Node)
        assert len(live) == 1  # no capacity modeled for the daemonset pod

    def test_terminating_statefulset_pod_is_rescheduled(self, env):
        """suite_test.go:3597-3645: a TERMINATING StatefulSet pod still
        reserves replacement capacity — its sticky identity means the
        recreate can't happen until it dies, so the capacity must already
        exist for availability."""
        from karpenter_tpu.api.objects import OwnerReference
        sts = make_pod(cpu="3500m", name="sts-0")
        sts.metadata.owner_refs.append(
            OwnerReference(kind="StatefulSet", name="sts", uid="u2"))
        node = self._deleting_node_with(env, sts)
        sts.metadata.deletion_timestamp = env.clock.now()  # terminating
        env.store.update(sts)
        settle(env)
        # the node lingers while the terminating pod is still dying (its
        # kubelet grace hasn't elapsed) — and during that window the
        # provisioner has already modeled capacity for BOTH the anchor and
        # the future sts-0 replacement (3500m forces a big node)
        assert env.store.get(Node, node.name) is not None
        total_cpu = sum(n.status.allocatable.get("cpu", 0)
                        for n in env.store.list(Node)
                        if n.metadata.deletion_timestamp is None)
        assert total_cpu >= 4000, total_cpu
        # once the pod's grace period elapses the kubelet-sim finishes the
        # kill and the node completes termination
        env.clock.step(31)
        settle(env)
        assert env.store.get(Node, node.name) is None
        assert env.store.get(Pod, "sts-0", "default") is None

    def test_terminating_replicaset_pod_not_rescheduled(self, env):
        """suite_test.go:3553-3596: terminating REPLICASET pods get
        recreated elsewhere immediately; no capacity is modeled."""
        from karpenter_tpu.api.objects import OwnerReference
        rs = make_pod(cpu="3500m", name="rs-pod")
        rs.metadata.owner_refs.append(
            OwnerReference(kind="ReplicaSet", name="rs", uid="u3"))
        node = self._deleting_node_with(env, rs)
        rs.metadata.deletion_timestamp = env.clock.now()
        env.store.update(rs)
        settle(env)
        # only ONE small live replacement node (the anchor's): no capacity
        # was modeled for the dying ReplicaSet pod even while its node
        # lingers through the kill grace
        live = [n for n in env.store.list(Node)
                if n.metadata.deletion_timestamp is None]
        assert len(live) == 1
        assert live[0].status.allocatable.get("cpu", 0) < 3500
        env.clock.step(31)
        settle(env)
        assert env.store.get(Node, node.name) is None


class TestBindTimeTaintCheck:
    """VERDICT r4 #8: a node tainted between nomination and bind must not
    receive the pod — the kube-scheduler the reference delegates binding to
    honors taints at bind time."""

    def _provision_until_registered(self, env):
        """Run everything EXCEPT the binder until the node is registered."""
        for _ in range(6):
            env.mgr.run_until_quiet()
            env.clock.step(1.1)
        env.mgr.run_until_quiet()

    def test_disrupt_between_nominate_and_bind(self):
        from karpenter_tpu.api.objects import Taint
        clock = FakeClock()
        store = Store(clock)
        cluster = Cluster(store, clock)
        wire_informers(store, cluster)
        provider = KwokCloudProvider(store=store)
        mgr = Manager(store, clock)
        provisioner = Provisioner(store, cluster, provider, clock)
        binder = Binder(store, cluster, provisioner)
        # binder deliberately NOT registered: the test controls bind timing
        mgr.register(provisioner, PodTrigger(provisioner),
                     NodeClaimLifecycle(store, cluster, provider, clock))

        class E:
            pass
        env = E()
        env.mgr, env.clock = mgr, clock

        store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        store.create(pod)
        self._provision_until_registered(env)
        nodes = store.list(Node)
        assert len(nodes) == 1
        assert provisioner.nominations, "expected a nomination"
        assert not store.get(Pod, pod.name, pod.namespace).spec.node_name

        # the disruption controller taints the node before the bind lands
        node = nodes[0]
        node.spec.taints = list(node.spec.taints) + [
            Taint(key=api_labels.DISRUPTED_TAINT_KEY, effect="NoSchedule")]
        store.update(node)

        binder.reconcile()
        live = store.get(Pod, pod.name, pod.namespace)
        assert not live.spec.node_name, \
            "pod bound onto a disrupted node (stale-bind race)"
        assert not provisioner.nominations  # dropped, pod back in the pool

        # the re-plan nominates a fresh node and the bind succeeds there
        self._provision_until_registered(env)
        binder.reconcile()
        live = store.get(Pod, pod.name, pod.namespace)
        assert live.spec.node_name
        bound_node = store.get(Node, live.spec.node_name)
        assert not any(t.key == api_labels.DISRUPTED_TAINT_KEY
                       for t in bound_node.spec.taints)

    def test_startup_taints_do_not_block_bind(self):
        """The claim's own startup taints clear during initialization; they
        must not bounce the nomination (that would re-plan forever)."""
        from karpenter_tpu.api.objects import Taint
        clock = FakeClock()
        store = Store(clock)
        cluster = Cluster(store, clock)
        wire_informers(store, cluster)
        provider = KwokCloudProvider(store=store)
        mgr = Manager(store, clock)
        provisioner = Provisioner(store, cluster, provider, clock)
        binder = Binder(store, cluster, provisioner)
        mgr.register(provisioner, PodTrigger(provisioner),
                     NodeClaimLifecycle(store, cluster, provider, clock))

        class E:
            pass
        env = E()
        env.mgr, env.clock = mgr, clock

        store.create(make_nodepool(
            name="default",
            startup_taints=[Taint(key="example.com/agent-not-ready",
                                  effect="NoSchedule")]))
        pod = make_pod(cpu="500m")
        store.create(pod)
        self._provision_until_registered(env)
        # re-add the startup taint as if initialization hadn't cleared it yet
        node = store.list(Node)[0]
        node.spec.taints = list(node.spec.taints) + [
            Taint(key="example.com/agent-not-ready", effect="NoSchedule")]
        store.update(node)
        binder.reconcile()
        live = store.get(Pod, pod.name, pod.namespace)
        assert live.spec.node_name  # startup taint didn't bounce the bind
