"""Scenario port of /root/reference/pkg/controllers/provisioning/scheduling/
suite_test.go (3,916 LoC): custom constraints (node selectors x NodePool
requirements x operators), preferential fallback (required-term and
preferred-term relaxation ladders), instance-type compatibility, binpacking,
daemonset overhead, and existing-node packing. Host oracle is the
conformance target; plain-constraint scenarios also assert tensor parity."""

from collections import Counter

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (NodeSelectorRequirement, ObjectMeta,
                                       Pod, PodSpec)
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.utils import resources as res

from factories import (make_nodepool, make_pod, make_pods, make_scheduler,
                       make_state_node)

ZONE = api_labels.LABEL_TOPOLOGY_ZONE
ARCH = api_labels.LABEL_ARCH
OS = api_labels.LABEL_OS
IT = api_labels.LABEL_INSTANCE_TYPE
CT = api_labels.CAPACITY_TYPE_LABEL_KEY


def its():
    return kwok.construct_instance_types()


def hsolve(pods, pools=None, catalog=None, state_nodes=(), daemons=()):
    pools = pools or [make_nodepool()]
    catalog = catalog if catalog is not None else its()
    s = make_scheduler(pools, catalog, pods, state_nodes=state_nodes,
                       daemonset_pods=daemons)
    return s.solve(pods)


def tsolve(pods, pools=None, catalog=None):
    pools = pools or [make_nodepool()]
    catalog = catalog if catalog is not None else its()
    it_map = {p.name: list(catalog) for p in pools}
    ts = TensorScheduler(pools, it_map, force_tensor=True)
    r = ts.solve(pods)
    assert ts.fallback_reason == "", ts.fallback_reason
    return r


class TestCustomConstraints:
    """suite_test.go:142-467 — pool labels/requirements x pod selectors."""

    def test_unconstrained_pod_schedules(self):
        assert not hsolve([make_pod()]).pod_errors

    def test_conflicting_node_selector_fails(self):
        pool = make_nodepool(labels={"team": "a"})
        h = hsolve([make_pod(node_selector={"team": "b"})], pools=[pool])
        assert len(h.pod_errors) == 1

    def test_matching_pool_label_schedules(self):
        pool = make_nodepool(labels={"team": "a"})
        h = hsolve([make_pod(node_selector={"team": "a"})], pools=[pool])
        assert not h.pod_errors

    def test_undefined_selector_key_fails(self):
        # nothing in the pool or catalog defines "mystery"
        h = hsolve([make_pod(node_selector={"mystery": "x"})])
        assert len(h.pod_errors) == 1

    def test_pool_requirement_defines_custom_key(self):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("team", "In", ("a", "b"))])
        h = hsolve([make_pod(node_selector={"team": "a"})], pools=[pool])
        assert not h.pod_errors

    def test_selector_outside_pool_requirement_fails(self):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("team", "In", ("a", "b"))])
        h = hsolve([make_pod(node_selector={"team": "c"})], pools=[pool])
        assert len(h.pod_errors) == 1

    @pytest.mark.parametrize("op,values,ok", [
        ("In", ("test-zone-a",), True),
        ("In", ("no-such-zone",), False),
        ("NotIn", ("test-zone-a",), True),
        ("Exists", (), True),
        ("DoesNotExist", (), False),  # every node has a zone
    ])
    def test_zone_requirement_operators(self, op, values, ok):
        req = [[NodeSelectorRequirement(ZONE, op, values)]]
        h = hsolve([make_pod(required_affinity=req)])
        assert (not h.pod_errors) is ok

    def test_gt_lt_requirements(self):
        """suite_test.go:253-270 over an integer-valued custom key."""
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("gen", "In", ("2", "4", "8"))])
        ok = hsolve([make_pod(required_affinity=[[
            NodeSelectorRequirement("gen", "Gt", ("3",))]])], pools=[pool])
        assert not ok.pod_errors
        bad = hsolve([make_pod(required_affinity=[[
            NodeSelectorRequirement("gen", "Gt", ("8",))]])], pools=[pool])
        assert len(bad.pod_errors) == 1
        ok2 = hsolve([make_pod(required_affinity=[[
            NodeSelectorRequirement("gen", "Lt", ("3",))]])], pools=[pool])
        assert not ok2.pod_errors

    def test_notin_on_undefined_key_schedules(self):
        """suite_test.go:484-512: NotIn/DoesNotExist tolerate unknown keys."""
        h = hsolve([make_pod(required_affinity=[[
            NodeSelectorRequirement("mystery", "NotIn", ("x",))]])])
        assert not h.pod_errors
        h2 = hsolve([make_pod(required_affinity=[[
            NodeSelectorRequirement("mystery", "DoesNotExist", ())]])])
        assert not h2.pod_errors

    def test_hostname_selector_never_schedules(self):
        """suite_test.go:214-221: you can't target a node that doesn't
        exist yet by hostname."""
        h = hsolve([make_pod(node_selector={
            api_labels.LABEL_HOSTNAME: "some-node"})])
        assert len(h.pod_errors) == 1

    def test_compatible_pods_share_a_node(self):
        """suite_test.go:592-611."""
        a = make_pod(cpu="100m", required_affinity=[[
            NodeSelectorRequirement(ZONE, "In",
                                    ("test-zone-a", "test-zone-b"))]])
        b = make_pod(cpu="100m", node_selector={ZONE: "test-zone-a"})
        h = hsolve([a, b])
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 1
        assert h.new_nodeclaims[0].requirements.get(ZONE).values_list() == \
            ["test-zone-a"]

    def test_incompatible_pods_get_separate_nodes(self):
        """suite_test.go:612-631."""
        a = make_pod(cpu="100m", node_selector={ZONE: "test-zone-a"})
        b = make_pod(cpu="100m", node_selector={ZONE: "test-zone-b"})
        h = hsolve([a, b])
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 2
        t = tsolve([make_pod(cpu="100m", node_selector={ZONE: "test-zone-a"}),
                    make_pod(cpu="100m", node_selector={ZONE: "test-zone-b"})])
        assert len(t.new_nodeclaims) == 2

    @pytest.mark.parametrize("key,value", [
        (ZONE, "test-zone-b"),
        (ARCH, "arm64"),
        (OS, "linux"),
        (CT, "spot"),
    ])
    def test_well_known_label_selectors_schedule(self, key, value):
        h = hsolve([make_pod(node_selector={key: value})])
        assert not h.pod_errors
        for nc in h.new_nodeclaims:
            assert nc.requirements.get(key).values_list() == [value]


class TestPreferentialFallback:
    """suite_test.go:1092-1212."""

    def test_final_required_term_not_relaxed(self):
        req = [[NodeSelectorRequirement(ZONE, "In", ("invalid",))]]
        h = hsolve([make_pod(required_affinity=req)])
        assert len(h.pod_errors) == 1

    def test_relaxes_multiple_required_terms(self):
        req = [
            [NodeSelectorRequirement(ZONE, "In", ("invalid",))],
            [NodeSelectorRequirement(ZONE, "In", ("also-invalid",))],
            [NodeSelectorRequirement(ZONE, "In", ("test-zone-a",))],
            [NodeSelectorRequirement(ZONE, "In", ("test-zone-b",))],
        ]
        h = hsolve([make_pod(required_affinity=req)])
        assert not h.pod_errors
        claim = h.new_nodeclaims[0]
        assert claim.requirements.get(ZONE).values_list() == ["test-zone-a"]

    def test_relaxes_all_preferred_terms(self):
        pref = [(1, [NodeSelectorRequirement(ZONE, "In", ("invalid",))]),
                (1, [NodeSelectorRequirement(IT, "In", ("invalid",))])]
        h = hsolve([make_pod(preferred_affinity=pref)])
        assert not h.pod_errors

    def test_relaxes_heaviest_preference_first(self):
        """suite_test.go:1155-1186: weight-100 impossible preference drops
        first; the weight-50 zone preference then holds."""
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            ZONE, "In", ("test-zone-a", "test-zone-b"))])
        pref = [
            (100, [NodeSelectorRequirement(IT, "In", ("no-such-type",))]),
            (50, [NodeSelectorRequirement(ZONE, "In", ("test-zone-b",))]),
            (1, [NodeSelectorRequirement(ZONE, "In", ("test-zone-a",))]),
        ]
        h = hsolve([make_pod(preferred_affinity=pref)], pools=[pool])
        assert not h.pod_errors
        claim = h.new_nodeclaims[0]
        assert claim.requirements.get(ZONE).values_list() == ["test-zone-b"]

    def test_requirement_beats_conflicting_preference(self):
        req = [[NodeSelectorRequirement(ZONE, "In", ("test-zone-c",))]]
        pref = [(1, [NodeSelectorRequirement(ZONE, "NotIn", ("test-zone-c",))])]
        h = hsolve([make_pod(required_affinity=req, preferred_affinity=pref)])
        assert not h.pod_errors
        claim = h.new_nodeclaims[0]
        assert claim.requirements.get(ZONE).values_list() == ["test-zone-c"]

    def test_conflicting_preferences_schedule(self):
        pref = [(1, [NodeSelectorRequirement(ZONE, "In", ("invalid",)),
                     NodeSelectorRequirement(ZONE, "NotIn", ("invalid",))])]
        h = hsolve([make_pod(preferred_affinity=pref)])
        assert not h.pod_errors


class TestInstanceTypeCompatibility:
    """suite_test.go:1213-1500."""

    def test_arch_selector_filters_instance_types(self):
        h = hsolve([make_pod(node_selector={ARCH: "arm64"})])
        assert not h.pod_errors
        for nc in h.new_nodeclaims:
            for it in nc.instance_type_options:
                assert it.requirements.get(ARCH).values_list() == ["arm64"]

    def test_instance_type_selector_pins_type(self):
        name = its()[0].name
        h = hsolve([make_pod(node_selector={IT: name})])
        assert not h.pod_errors
        assert [i.name for i in h.new_nodeclaims[0].instance_type_options] \
            == [name]

    def test_oversized_pod_fails(self):
        h = hsolve([make_pod(cpu="10000")])
        assert len(h.pod_errors) == 1
        t = tsolve([make_pod(cpu="10000")])
        assert len(t.pod_errors) == 1

    def test_memory_bound_filtering(self):
        """Only instance types with enough memory survive in the claim."""
        h = hsolve([make_pod(cpu="100m", memory="100Gi")])
        assert not h.pod_errors
        need = res.parse_list({"memory": "100Gi"})["memory"]
        for it in h.new_nodeclaims[0].instance_type_options:
            assert it.allocatable().get("memory", 0) >= need


class TestBinpacking:
    """suite_test.go:1501-1817."""

    def test_packs_small_pods_densely(self):
        h = hsolve(make_pods(20, cpu="100m", memory="64Mi"))
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 1

    def test_large_pods_split_across_nodes(self):
        biggest = max(it.capacity.get("cpu", 0) for it in its())
        per_pod = biggest // 2 + 1  # two can never share the largest node
        pods = [Pod(metadata=ObjectMeta(name=f"big-{i}", namespace="default"),
                    spec=PodSpec(),
                    container_requests=[{"cpu": per_pod}])
                for i in range(3)]
        h = hsolve(pods)
        assert not h.pod_errors
        assert len(h.new_nodeclaims) == 3

    def test_ffd_order_big_pods_first(self):
        """Mixed sizes pack big-first so smalls backfill (queue.go:76-112)."""
        pods = make_pods(2, cpu="3") + make_pods(10, cpu="100m")
        h = hsolve(pods)
        assert not h.pod_errors
        # smalls should have backfilled into the big pods' nodes
        assert len(h.new_nodeclaims) <= 3

    def test_daemonset_overhead_reserved(self):
        """suite_test.go:2153+: daemonset requests shrink the usable node."""
        daemon = make_pod(cpu="1", memory="1Gi")
        h = hsolve(make_pods(4, cpu="500m"), daemons=[daemon])
        assert not h.pod_errors
        for nc in h.new_nodeclaims:
            want = 4_000 // len(h.new_nodeclaims) * 500 // 500
            assert nc.requests.get("cpu", 0) >= 1_000  # daemon included

    def test_daemonset_with_incompatible_selector_not_counted(self):
        daemon = make_pod(cpu="10", node_selector={"no-such": "label"})
        h = hsolve(make_pods(2, cpu="500m"), daemons=[daemon])
        assert not h.pod_errors
        for nc in h.new_nodeclaims:
            assert nc.requests.get("cpu", 0) < 10_000


class TestExistingNodes:
    """suite_test.go:2427-2607."""

    def test_prefers_existing_capacity(self):
        sn = make_state_node("live-1", cpu="8", memory="16Gi")
        h = hsolve(make_pods(4, cpu="500m"), state_nodes=[sn])
        assert not h.pod_errors
        assert not h.new_nodeclaims
        assert sum(len(en.pods) for en in h.existing_nodes) == 4

    def test_overflow_spills_to_new_node(self):
        sn = make_state_node("live-1", cpu="1", memory="2Gi")
        h = hsolve(make_pods(4, cpu="500m", memory="256Mi"),
                   state_nodes=[sn])
        assert not h.pod_errors
        assert h.new_nodeclaims  # the 1-cpu node can't hold all four
        assert sum(len(en.pods) for en in h.existing_nodes) >= 1

    def test_existing_node_taints_respected(self):
        from karpenter_tpu.api.objects import Taint
        sn = make_state_node("tainted", cpu="8")
        sn.node.spec.taints = [Taint(key="dedicated", value="x")]
        h = hsolve(make_pods(2, cpu="500m"), state_nodes=[sn])
        assert not h.pod_errors
        assert all(not en.pods for en in h.existing_nodes)
        assert h.new_nodeclaims

    def test_existing_node_zone_counts_for_topology(self):
        """An existing node's zone participates in spread accounting."""
        from factories import spread_zone
        sn = make_state_node("live-a", zone="test-zone-a", cpu="32",
                             memory="64Gi")
        pods = make_pods(4, cpu="100m", labels={"app": "demo"},
                         spread=[spread_zone(key="app", value="demo")])
        h = hsolve(pods, state_nodes=[sn])
        assert not h.pod_errors
        zones = Counter()
        for nc in h.new_nodeclaims:
            zv = nc.requirements.get(ZONE).values_list()
            if len(zv) == 1:
                zones[zv[0]] += len(nc.pods)
        for en in h.existing_nodes:
            zones["test-zone-a"] += len(en.pods)
        assert max(zones.values()) - min(zones.values()) <= 1


class TestExistsOperator:
    def test_exists_requirement_does_not_overwrite_selector_value(self):
        """suite_test.go:632-644: a pool-level Exists requirement admits any
        value; the pod's concrete selector value wins on the claim."""
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("team", "Exists", ())])
        h = hsolve([make_pod(node_selector={"team": "payments"})],
                   pools=[pool])
        assert not h.pod_errors
        assert h.new_nodeclaims[0].requirements.get("team").values_list() \
            == ["payments"]
