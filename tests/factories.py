"""Object factories for tests, in the spirit of /root/reference/pkg/test
(test.Pod(test.PodOptions{...}) etc.)."""

from __future__ import annotations

import itertools
from typing import Optional

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodepool import (NodeClaimTemplate, NodeClaimTemplateSpec, NodePool,
                                        NodePoolSpec)
from karpenter_tpu.api.objects import (Affinity, LabelSelector, NodeAffinity,
                                       NodeSelectorRequirement, NodeSelectorTerm, ObjectMeta,
                                       Pod, PodAffinity, PodAffinityTerm, PodSpec,
                                       PreferredSchedulingTerm, TopologySpreadConstraint,
                                       WeightedPodAffinityTerm)
from karpenter_tpu.provisioning.domains import build_topology_domains
from karpenter_tpu.provisioning.scheduler import Scheduler
from karpenter_tpu.provisioning.topology import ClusterView, Topology
from karpenter_tpu.utils import resources as res

_seq = itertools.count(1)


def make_pod(cpu="100m", memory="128Mi", labels=None, node_selector=None,
             tolerations=None, spread=None, required_affinity=None,
             preferred_affinity=None, pod_affinity=None, pod_anti_affinity=None,
             preferred_pod_affinity=None, preferred_pod_anti_affinity=None,
             namespace="default", name=None, host_ports=None) -> Pod:
    affinity = None
    na = None
    if required_affinity or preferred_affinity:
        na = NodeAffinity(
            required_terms=[NodeSelectorTerm(match_expressions=tuple(term))
                            for term in (required_affinity or [])],
            preferred=[PreferredSchedulingTerm(w, NodeSelectorTerm(match_expressions=tuple(t)))
                       for w, t in (preferred_affinity or [])])
    pa = None
    if pod_affinity or preferred_pod_affinity:
        pa = PodAffinity(required=list(pod_affinity or []),
                         preferred=[WeightedPodAffinityTerm(w, t)
                                    for w, t in (preferred_pod_affinity or [])])
    paa = None
    if pod_anti_affinity or preferred_pod_anti_affinity:
        paa = PodAffinity(required=list(pod_anti_affinity or []),
                          preferred=[WeightedPodAffinityTerm(w, t)
                                     for w, t in (preferred_pod_anti_affinity or [])])
    if na or pa or paa:
        affinity = Affinity(node_affinity=na, pod_affinity=pa, pod_anti_affinity=paa)
    return Pod(
        metadata=ObjectMeta(name=name or f"pod-{next(_seq):04d}", namespace=namespace,
                            labels=dict(labels or {})),
        spec=PodSpec(node_selector=dict(node_selector or {}),
                     tolerations=list(tolerations or []),
                     topology_spread_constraints=list(spread or []),
                     affinity=affinity,
                     host_ports=list(host_ports or [])),
        container_requests=[res.parse_list({"cpu": cpu, "memory": memory})])


def make_pods(n, **kw):
    return [make_pod(**kw) for _ in range(n)]


def make_nodepool(name="default", requirements=(), taints=(), startup_taints=(),
                  labels=None, limits=None, weight=None) -> NodePool:
    return NodePool(
        metadata=ObjectMeta(name=name),
        spec=NodePoolSpec(
            template=NodeClaimTemplate(
                metadata_labels=dict(labels or {}),
                spec=NodeClaimTemplateSpec(
                    requirements=list(requirements), taints=list(taints),
                    startup_taints=list(startup_taints))),
            limits=res.parse_list(limits) if limits else {},
            weight=weight))


def spread_zone(max_skew=1, key="app", value="demo"):
    return TopologySpreadConstraint(
        topology_key=api_labels.LABEL_TOPOLOGY_ZONE, max_skew=max_skew,
        label_selector=LabelSelector(match_labels={key: value}))


def spread_hostname(max_skew=1, key="app", value="demo"):
    return TopologySpreadConstraint(
        topology_key=api_labels.LABEL_HOSTNAME, max_skew=max_skew,
        label_selector=LabelSelector(match_labels={key: value}))


def affinity_term(topology_key, key="app", value="demo"):
    return PodAffinityTerm(topology_key=topology_key,
                           label_selector=LabelSelector(match_labels={key: value}))


def make_state_node(name, nodepool="default", cpu="4", memory="8Gi",
                    zone=None, initialized=True, labels=None):
    """A live StateNode the schedulers can pack onto."""
    from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus
    from karpenter_tpu.state.statenode import StateNode

    lbl = {api_labels.LABEL_HOSTNAME: name,
           api_labels.NODEPOOL_LABEL_KEY: nodepool}
    if zone:
        lbl[api_labels.LABEL_TOPOLOGY_ZONE] = zone
    if initialized:
        lbl[api_labels.NODE_INITIALIZED_LABEL_KEY] = "true"
    lbl.update(labels or {})
    alloc = res.parse_list({"cpu": cpu, "memory": memory, "pods": "110"})
    return StateNode(node=Node(
        metadata=ObjectMeta(name=name, namespace="", labels=lbl),
        spec=NodeSpec(provider_id=f"t://{name}"),
        status=NodeStatus(capacity=dict(alloc), allocatable=alloc)))


class StaticClusterView:
    """ClusterView stub: scheduled pods pinned to named nodes with labels."""

    def __init__(self, pods_on_nodes, node_labels):
        self._pods = list(pods_on_nodes)
        self._node_labels = dict(node_labels)

    def list_pods(self, namespace, selector):
        return [p for p in self._pods
                if p.namespace == namespace and selector.matches(p.labels)]

    def node_labels(self, node_name):
        return self._node_labels.get(node_name)

    def for_pods_with_anti_affinity(self):
        for p in self._pods:
            aff = p.spec.affinity
            if aff is not None and aff.pod_anti_affinity is not None \
                    and aff.pod_anti_affinity.required:
                labels = self._node_labels.get(p.spec.node_name)
                if labels is not None:
                    yield p, labels


def running_on(pods, node_name):
    """Mark pods as scheduled+running on a node (countDomains inputs)."""
    for p in pods:
        p.spec.node_name = node_name
        p.status.phase = "Running"
    return pods


def make_scheduler(nodepools, instance_types, pods, state_nodes=(), daemonset_pods=(),
                   cluster: Optional[ClusterView] = None) -> Scheduler:
    if not isinstance(instance_types, dict):
        instance_types = {np.name: list(instance_types) for np in nodepools}
    domains = build_topology_domains(nodepools, instance_types)
    topo = Topology(cluster or ClusterView(), domains, pods)
    return Scheduler(nodepools, instance_types, topo,
                     state_nodes=state_nodes, daemonset_pods=daemonset_pods)
