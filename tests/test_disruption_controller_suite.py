"""Scenario port of /root/reference/pkg/controllers/disruption/suite_test.go
(2,139 LoC): candidate-filtering table, disruption-budget mapping exclusions,
disruption taints (stale cleanup + failure rollback), pod eviction cost, and
decision metrics."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import (COND_CONSOLIDATABLE, COND_INITIALIZED,
                                         COND_INSTANCE_TERMINATING, NodeClaim)
from karpenter_tpu.api.nodepool import Budget, NodePool
from karpenter_tpu.api.objects import (LabelSelector, Node, ObjectMeta,
                                       OwnerReference, Pod)
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_disruption import NodeClaimDisruptionMarker
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.node_termination import NodeTermination
from karpenter_tpu.disruption.controller import (DisruptionController,
                                                 OrchestrationQueue,
                                                 QueuedCommand)
from karpenter_tpu.disruption.helpers import (build_disruption_budget_mapping,
                                              get_candidates)
from karpenter_tpu.disruption.types import Command
from karpenter_tpu.kube.store import Store
from karpenter_tpu.metrics.registry import DISRUPTION_DECISIONS
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.disruption import (POD_DELETION_COST_ANNOTATION,
                                            eviction_cost)

from factories import make_nodepool, make_pod

OD = {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_ON_DEMAND}


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    queue = OrchestrationQueue(store, cluster, clock)
    disruption = DisruptionController(store, cluster, provisioner, queue, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock),
                 NodeClaimDisruptionMarker(store, cluster, provider, clock),
                 NodeTermination(store, cluster, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr = \
        clock, store, cluster, provider, mgr
    e.provisioner, e.queue, e.disruption = provisioner, queue, disruption
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


def provision_node(env, pool_name="default", cpu="2500m", name=None, tgp=None):
    if env.store.get(NodePool, pool_name) is None:
        env.store.create(make_nodepool(name=pool_name))
    pod = make_pod(cpu=cpu, name=name, node_selector=dict(OD))
    env.store.create(pod)
    settle(env, rounds=3)
    nc = env.store.list(NodeClaim)[-1]
    if tgp is not None:
        nc.spec.termination_grace_period = tgp
        env.store.update(nc)
    return nc, env.store.get(Node, nc.status.node_name), pod


def candidates(env, disruption_class="graceful", disrupting=()):
    return get_candidates(env.cluster, env.provisioner, lambda c: True,
                          disrupting_provider_ids=disrupting,
                          disruption_class=disruption_class)


class TestCandidateFiltering:
    """suite_test.go:834-1774."""

    def test_do_not_disrupt_pod_blocks_without_tgp(self, env):
        nc, node, pod = provision_node(env)
        pod.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.update(pod)
        assert candidates(env) == []

    def test_do_not_disrupt_pod_with_tgp_allows_eventual(self, env):
        """suite_test.go:958-986."""
        nc, node, pod = provision_node(env, tgp=300.0)
        pod.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.update(pod)
        assert len(candidates(env, disruption_class="eventual")) == 1

    def test_do_not_disrupt_pod_with_tgp_blocks_graceful(self, env):
        """suite_test.go:1019-1047."""
        nc, node, pod = provision_node(env, tgp=300.0)
        pod.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.update(pod)
        assert candidates(env, disruption_class="graceful") == []

    def test_pdb_blocked_pod_with_tgp_allows_eventual(self, env):
        """suite_test.go:987-1018."""
        nc, node, pod = provision_node(env, tgp=300.0)
        pod.metadata.labels["app"] = "blocked"
        env.store.update(pod)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "blocked"}),
                         max_unavailable="0")))
        assert candidates(env, disruption_class="graceful") == []
        assert len(candidates(env, disruption_class="eventual")) == 1

    def test_do_not_disrupt_mirror_pod_blocks(self, env):
        """suite_test.go:881-918 + statenode.go:221-223: the do-not-disrupt
        sweep covers every ACTIVE pod — mirror pods may deliberately block
        disruption through the annotation (corrected round 5; PDBs on
        mirror pods remain exempt, see test_candidate_gating_corpus)."""
        nc, node, pod = provision_node(env)
        mirror = make_pod(cpu="100m", name="mirror")
        mirror.metadata.owner_refs.append(OwnerReference(kind="Node",
                                                         name=node.name))
        mirror.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        mirror.spec.node_name = node.name
        env.store.create(mirror)
        settle(env)
        assert candidates(env) == []

    def test_do_not_disrupt_daemonset_pod_blocks(self, env):
        """suite_test.go:919-957."""
        nc, node, pod = provision_node(env)
        ds = make_pod(cpu="100m", name="ds")
        ds.is_daemonset_pod = True
        ds.metadata.owner_refs.append(OwnerReference(kind="DaemonSet",
                                                     name="fluentd"))
        ds.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        ds.spec.node_name = node.name
        env.store.create(ds)
        settle(env)
        assert candidates(env) == []

    def test_do_not_disrupt_terminating_pod_does_not_block(self, env):
        """suite_test.go:1147-1176."""
        nc, node, pod = provision_node(env)
        doomed = make_pod(cpu="100m", name="doomed")
        doomed.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        doomed.metadata.finalizers.append("test/hold")
        doomed.spec.node_name = node.name
        env.store.create(doomed)
        env.store.delete(doomed)  # terminating, still present
        settle(env)
        assert len(candidates(env)) == 1

    def test_do_not_disrupt_terminal_pod_does_not_block(self, env):
        """suite_test.go:1177-1214."""
        nc, node, pod = provision_node(env)
        done = make_pod(cpu="100m", name="done")
        done.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        done.status.phase = "Succeeded"
        done.spec.node_name = node.name
        env.store.create(done)
        settle(env)
        assert len(candidates(env)) == 1

    def test_do_not_disrupt_on_node_blocks(self, env):
        """suite_test.go:1215-1237."""
        nc, node, pod = provision_node(env)
        node.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.update(node)
        assert candidates(env) == []

    def test_fully_blocking_pdb_blocks(self, env):
        """suite_test.go:1238-1273."""
        nc, node, pod = provision_node(env)
        pod.metadata.labels["app"] = "blocked"
        env.store.update(pod)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "blocked"}),
                         max_unavailable="0")))
        assert candidates(env) == []

    def test_blocking_pdb_on_mirror_pod_does_not_block(self, env):
        """suite_test.go:1321-1366."""
        nc, node, pod = provision_node(env)
        mirror = make_pod(cpu="100m", name="mirror", labels={"app": "blocked"})
        mirror.metadata.owner_refs.append(OwnerReference(kind="Node",
                                                         name=node.name))
        mirror.spec.node_name = node.name
        env.store.create(mirror)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "blocked"}),
                         max_unavailable="0")))
        settle(env)
        assert len(candidates(env)) == 1

    def test_blocking_pdb_on_terminal_pod_does_not_block(self, env):
        """suite_test.go:1432-1475."""
        nc, node, pod = provision_node(env)
        done = make_pod(cpu="100m", name="done", labels={"app": "blocked"})
        done.status.phase = "Failed"
        done.spec.node_name = node.name
        env.store.create(done)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "blocked"}),
                         max_unavailable="0")))
        settle(env)
        assert len(candidates(env)) == 1

    def test_node_only_representation_not_considered(self, env):
        """suite_test.go:1514-1532: no NodeClaim -> not disruptable."""
        from karpenter_tpu.api.objects import NodeSpec, NodeStatus
        from karpenter_tpu.utils import resources as res
        alloc = res.parse_list({"cpu": "4", "memory": "8Gi", "pods": "110"})
        env.store.create(make_nodepool(name="default"))
        env.store.create(Node(
            metadata=ObjectMeta(name="orphan", namespace="", labels={
                api_labels.LABEL_HOSTNAME: "orphan",
                api_labels.NODEPOOL_LABEL_KEY: "default",
                api_labels.NODE_INITIALIZED_LABEL_KEY: "true"}),
            spec=NodeSpec(provider_id="test://orphan"),
            status=NodeStatus(capacity=dict(alloc), allocatable=alloc)))
        assert candidates(env) == []

    def test_nodeclaim_only_representation_not_considered(self, env):
        """suite_test.go:1533-1551: claim with no Node is not initialized."""
        env.store.create(make_nodepool(name="default"))
        nc = NodeClaim(metadata=ObjectMeta(name="lone", namespace="", labels={
            api_labels.NODEPOOL_LABEL_KEY: "default"}))
        nc.status.provider_id = "test://lone"
        env.store.create(nc)
        assert candidates(env) == []

    def test_nominated_node_not_considered(self, env):
        """suite_test.go:1552-1572."""
        nc, node, pod = provision_node(env)
        env.cluster.nominate_node_for_pod(node.name, make_pod(name="pend"))
        assert candidates(env) == []

    def test_deleting_node_not_considered(self, env):
        """suite_test.go:1573-1594."""
        nc, node, pod = provision_node(env)
        env.cluster.mark_for_deletion(nc.status.provider_id)
        assert candidates(env) == []

    def test_uninitialized_not_considered(self, env):
        """suite_test.go:1616-1635."""
        nc, node, pod = provision_node(env)
        del node.metadata.labels[api_labels.NODE_INITIALIZED_LABEL_KEY]
        env.store.update(node)
        assert candidates(env) == []

    def test_no_nodepool_label_not_considered(self, env):
        """suite_test.go:1636-1654."""
        nc, node, pod = provision_node(env)
        del node.metadata.labels[api_labels.NODEPOOL_LABEL_KEY]
        env.store.update(node)
        assert candidates(env) == []

    def test_nonexistent_nodepool_not_considered(self, env):
        """suite_test.go:1655-1679."""
        nc, node, pod = provision_node(env)
        env.store.delete(env.store.get(NodePool, "default"))
        assert candidates(env) == []

    def test_missing_optional_labels_still_considered(self, env):
        """suite_test.go:1680-1751: capacity-type / zone / instance-type
        labels and even an unresolvable instance type don't gate candidacy."""
        nc, node, pod = provision_node(env)
        for key in (api_labels.CAPACITY_TYPE_LABEL_KEY,
                    api_labels.LABEL_TOPOLOGY_ZONE):
            node.metadata.labels.pop(key, None)
        node.metadata.labels[api_labels.LABEL_INSTANCE_TYPE] = "no-such-type"
        env.store.update(node)
        got = candidates(env)
        assert len(got) == 1
        assert got[0].instance_type is None

    def test_in_queue_candidate_excluded(self, env):
        """suite_test.go:1752-1774."""
        nc, node, pod = provision_node(env)
        assert len(candidates(env)) == 1
        assert candidates(env, disrupting=(nc.status.provider_id,)) == []


class TestBudgetMapping:
    """suite_test.go:601-778."""

    def _fleet(self, env, n=4):
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(pool)
        for i in range(n):
            env.store.create(make_pod(cpu="2500m", name=f"w-{i}",
                                      node_selector=dict(OD)))
            settle(env, rounds=3)
        return pool

    def test_full_budget_counts_all_nodes(self, env):
        self._fleet(env)
        assert build_disruption_budget_mapping(
            env.cluster, "underutilized")["default"] == 4

    def test_uninitialized_nodes_not_counted(self, env):
        """suite_test.go:648-678."""
        self._fleet(env)
        node = env.store.list(Node)[0]
        del node.metadata.labels[api_labels.NODE_INITIALIZED_LABEL_KEY]
        env.store.update(node)
        assert build_disruption_budget_mapping(
            env.cluster, "underutilized")["default"] == 3

    def test_instance_terminating_not_counted(self, env):
        """suite_test.go:679-710."""
        self._fleet(env)
        nc = env.store.list(NodeClaim)[0]
        nc.conditions.set_true(COND_INSTANCE_TERMINATING, reason="Deleting")
        env.store.update(nc)
        assert build_disruption_budget_mapping(
            env.cluster, "underutilized")["default"] == 3

    def test_never_negative(self, env):
        """suite_test.go:711-731: more disrupting nodes than budget."""
        pool = self._fleet(env)
        pool.spec.disruption.budgets = [Budget(nodes="1")]
        env.store.update(pool)
        for nc in env.store.list(NodeClaim)[:3]:
            env.cluster.mark_for_deletion(nc.status.provider_id)
        assert build_disruption_budget_mapping(
            env.cluster, "underutilized")["default"] == 0

    def test_marked_for_deletion_consumes_budget(self, env):
        """suite_test.go:732-755."""
        self._fleet(env)
        nc = env.store.list(NodeClaim)[0]
        env.cluster.mark_for_deletion(nc.status.provider_id)
        assert build_disruption_budget_mapping(
            env.cluster, "underutilized")["default"] == 3

    def test_not_ready_node_consumes_budget(self, env):
        """suite_test.go:756-778."""
        self._fleet(env)
        node = env.store.list(Node)[0]
        node.status.conditions.append(
            {"type": "Ready", "status": "False"})
        env.store.update(node)
        assert build_disruption_budget_mapping(
            env.cluster, "underutilized")["default"] == 3


class TestDisruptionTaints:
    """suite_test.go:465-600."""

    def test_stale_taint_removed_when_not_in_queue(self, env):
        """suite_test.go:526-545: taints left by a crashed disruption action
        are cleaned on the next loop."""
        nc, node, pod = provision_node(env)
        node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
        env.store.update(node)
        env.disruption.reconcile()
        node = env.store.get(Node, node.name)
        assert not any(t.matches(DISRUPTED_NO_SCHEDULE_TAINT)
                       for t in node.spec.taints)

    def test_taint_kept_while_command_in_queue(self, env):
        nc, node, pod = provision_node(env)
        node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
        env.store.update(node)
        cand = candidates(env, disrupting=())  # node not yet marked
        assert len(cand) == 1
        qc = QueuedCommand(command=Command(candidates=cand, reason="drifted"),
                           enqueued_at=env.clock.now(),
                           replacement_names=["ghost-replacement"])
        env.queue.add(qc)
        env.disruption.reconcile()
        node = env.store.get(Node, node.name)
        assert any(t.matches(DISRUPTED_NO_SCHEDULE_TAINT)
                   for t in node.spec.taints)

    def test_rollback_untaints_failed_disruption(self, env):
        """suite_test.go:546-600: replacement dies -> candidates untainted
        and unmarked."""
        nc, node, pod = provision_node(env)
        node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
        env.store.update(node)
        cand = candidates(env)
        qc = QueuedCommand(command=Command(candidates=cand, reason="drifted"),
                           enqueued_at=env.clock.now(),
                           replacement_names=["never-created"])
        env.queue.add(qc)
        env.cluster.mark_for_deletion(nc.status.provider_id)
        env.queue.reconcile()  # replacement missing -> rollback
        node = env.store.get(Node, node.name)
        assert not any(t.matches(DISRUPTED_NO_SCHEDULE_TAINT)
                       for t in node.spec.taints)
        assert not env.cluster.nodes[nc.status.provider_id].mark_for_deletion


class TestPodEvictionCost:
    """suite_test.go:779-833."""

    def test_standard_cost(self):
        assert eviction_cost(make_pod()) == 1.0

    def test_positive_deletion_cost_raises(self):
        p = make_pod()
        p.metadata.annotations[POD_DELETION_COST_ANNOTATION] = "100"
        assert eviction_cost(p) > 1.0

    def test_negative_deletion_cost_lowers(self):
        p = make_pod()
        p.metadata.annotations[POD_DELETION_COST_ANNOTATION] = "-100"
        assert eviction_cost(p) < 1.0

    def test_higher_costs_order(self):
        costs = []
        for raw in ("-100", "0", "100", "10000"):
            p = make_pod()
            p.metadata.annotations[POD_DELETION_COST_ANNOTATION] = raw
            costs.append(eviction_cost(p))
        assert costs == sorted(costs)
        assert len(set(costs)) == len(costs)

    def test_priority_raises_cost(self):
        lo_, hi = make_pod(), make_pod()
        lo_.spec.priority = 0
        hi.spec.priority = 1_000_000
        assert eviction_cost(hi) > eviction_cost(lo_)


class TestDecisionMetrics:
    """suite_test.go:1775-1965 (decision counters)."""

    def test_delete_decision_counter_increments(self, env):
        before = DISRUPTION_DECISIONS.value(
            {"decision": "delete", "reason": "Empty",
             "consolidation_type": "empty"})
        pool = make_nodepool(name="default")
        env.store.create(pool)
        pod = make_pod(cpu="2500m", node_selector=dict(OD))
        env.store.create(pod)
        settle(env, rounds=3)
        env.store.delete(pod)
        settle(env)
        env.clock.step(21)
        settle(env, rounds=2)
        for _ in range(6):
            env.disruption.reconcile()
            env.queue.reconcile()
            settle(env, rounds=2)
            env.clock.step(8)
        assert env.store.list(Node) == []
        after = DISRUPTION_DECISIONS.value(
            {"decision": "delete", "reason": "Empty",
             "consolidation_type": "empty"})
        assert after == before + 1


class TestSimulateScheduling:
    """suite_test.go:168-464."""

    def test_deleting_node_pods_ride_the_simulation(self, env):
        """suite_test.go:180-244: reschedulable pods on deleting nodes are
        added to the pending set so their capacity need is modeled."""
        from karpenter_tpu.disruption.helpers import simulate_scheduling
        nc_a, node_a, pod_a = provision_node(env, name="pod-a")
        nc_b, node_b, pod_b = provision_node(env, name="pod-b")
        # node B is deleting (some other controller's action)
        env.cluster.mark_for_deletion(nc_b.status.provider_id)
        cands = candidates(env)
        assert len(cands) == 1  # only A is a candidate
        results, errors = simulate_scheduling(env.cluster, env.provisioner,
                                              cands)
        assert errors == {}
        # both A's pod and B's pod were simulated somewhere
        placed = {p.uid for ex in results.existing_nodes for p in ex.pods}
        placed |= {p.uid for nc in results.new_nodeclaims for p in nc.pods}
        assert pod_a.uid in placed and pod_b.uid in placed

    def test_uninitialized_node_dependency_rejected(self, env):
        """helpers.go:93-111: a command whose simulation parks pods on a
        NOT-initialized managed node must surface errors for those pods."""
        from karpenter_tpu.disruption.helpers import simulate_scheduling
        nc_a, node_a, pod_a = provision_node(env, name="squeeze")
        # a second, uninitialized node with room
        nc_b, node_b, pod_b = provision_node(env, name="other")
        env.store.delete(pod_b)
        del node_b.metadata.labels[api_labels.NODE_INITIALIZED_LABEL_KEY]
        env.store.update(node_b)
        settle(env)
        cands = [c for c in candidates(env) if c.name == node_a.name]
        assert len(cands) == 1
        results, _ = simulate_scheduling(env.cluster, env.provisioner, cands)
        landed_on_b = [p for ex in results.existing_nodes
                       if ex.state_node.name() == node_b.name
                       for p in ex.pods]
        for p in landed_on_b:
            assert p.uid in results.pod_errors

    def test_deleting_node_pods_allowed_on_uninitialized_nodes(self, env):
        """suite_test.go:245-366 (successive replaces): pods that came off a
        DELETING node may land on an uninitialized node without erroring —
        its replacement is assumed to come up."""
        from karpenter_tpu.disruption.helpers import simulate_scheduling
        nc_a, node_a, pod_a = provision_node(env, name="first")
        nc_b, node_b, pod_b = provision_node(env, name="second")
        env.store.delete(pod_b)
        settle(env)
        # B is mid-replacement: deleting, and an uninitialized node C exists
        env.cluster.mark_for_deletion(nc_b.status.provider_id)
        bpod = make_pod(cpu="100m", name="displaced")
        bpod.spec.node_name = node_b.name
        env.store.create(bpod)
        # no settle: the provisioner would (correctly) nominate a target for
        # the displaced pod, which blocks A's candidacy — this scenario
        # drives the simulation directly
        cands = [c for c in candidates(env) if c.name == node_a.name]
        assert len(cands) == 1
        results, errors = simulate_scheduling(env.cluster, env.provisioner,
                                              cands)
        # the displaced pod must not produce a candidate-blocking error
        assert bpod.uid not in errors
