from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim, NodeClaimSpec
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, fake_instance_types
from karpenter_tpu.cloudprovider.types import (
    InsufficientCapacityError, NodeClaimNotFoundError, order_by_price,
    satisfies_min_values, truncate)
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.utils import resources as res


def test_kwok_catalog_shape():
    its = kwok.construct_instance_types()
    assert len(its) == 144
    for it in its:
        assert len(it.offerings) == 8
        assert it.capacity[res.CPU] > 0
    # price formula: 1 cpu, factor 2 => 0.025 + 0.002
    it = next(i for i in its if i.name == "c-1x-amd64-linux")
    od = [o for o in it.offerings if o.capacity_type == api_labels.CAPACITY_TYPE_ON_DEMAND][0]
    spot = [o for o in it.offerings if o.capacity_type == api_labels.CAPACITY_TYPE_SPOT][0]
    assert abs(od.price - 0.027) < 1e-9
    assert abs(spot.price - 0.027 * 0.7) < 1e-9


def test_order_by_price_and_truncate():
    its = kwok.construct_instance_types()
    reqs = Requirements()
    ordered = order_by_price(its, reqs)
    prices = [it.offerings.available().compatible(reqs).cheapest().price for it in ordered]
    assert prices == sorted(prices)
    truncated, err = truncate(its, reqs, 60)
    assert err is None and len(truncated) == 60


def test_min_values_satisfied():
    its = fake_instance_types(6)
    reqs = Requirements([Requirement(api_labels.LABEL_INSTANCE_TYPE, IN,
                                     [it.name for it in its], min_values=3)])
    needed, err = satisfies_min_values(its, reqs)
    assert err is None and needed == 3


def test_min_values_unsatisfied():
    its = fake_instance_types(2)
    reqs = Requirements([Requirement(api_labels.LABEL_INSTANCE_TYPE, IN,
                                     [it.name for it in its], min_values=5)])
    needed, err = satisfies_min_values(its, reqs)
    assert err is not None and needed == 2


def _claim(cpu="1", zone=None):
    reqs = []
    if zone:
        reqs.append(NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE, IN, (zone,)))
    return NodeClaim(spec=NodeClaimSpec(
        requirements=reqs, resources_requests=res.parse_list({"cpu": cpu})))


def test_fake_create_cheapest_and_records():
    cp = FakeCloudProvider()
    nc = cp.create(_claim())
    assert nc.status.provider_id.startswith("fake://")
    assert len(cp.create_calls) == 1
    # cheapest compatible = 1-cpu spot
    assert nc.metadata.labels[api_labels.CAPACITY_TYPE_LABEL_KEY] == api_labels.CAPACITY_TYPE_SPOT


def test_fake_injectable_errors_and_caps():
    cp = FakeCloudProvider()
    cp.next_create_err = InsufficientCapacityError("boom")
    try:
        cp.create(_claim())
        assert False
    except InsufficientCapacityError:
        pass
    cp.reset()
    cp.allowed_create_calls = 1
    cp.create(_claim())
    try:
        cp.create(_claim())
        assert False
    except InsufficientCapacityError:
        pass


def test_fake_delete_and_get():
    cp = FakeCloudProvider()
    nc = cp.create(_claim())
    assert cp.get(nc.status.provider_id) is nc
    cp.delete(nc)
    try:
        cp.get(nc.status.provider_id)
        assert False
    except NodeClaimNotFoundError:
        pass


def test_kwok_provider_fabricates_node():
    cp = kwok.KwokCloudProvider()
    nc = cp.create(_claim(cpu="3", zone="test-zone-b"))
    assert nc.status.provider_id.startswith("kwok://")
    _, node = cp.created[nc.status.provider_id]
    assert node.labels[api_labels.LABEL_TOPOLOGY_ZONE] == "test-zone-b"
    assert any(t.key == api_labels.UNREGISTERED_TAINT_KEY for t in node.spec.taints)
    assert node.status.allocatable[res.CPU] >= 3000
