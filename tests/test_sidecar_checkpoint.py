"""Session checkpoints over the wire codec (ISSUE 17): round-trips of
everything a server-side session IS — template table, pod row columns,
state-node mirrors and revision tokens, dedupe nonces, the response cache
and the last acked digest — seeded from the parity fuzzer's generator
corpus, plus the loud-reject matrix (truncation, wrong kind, unknown
checkpoint schema version, delta-wire skew, corrupt digests, stripped
fields/blobs) and the KARPENTER_SIDECAR_MAX_SESSIONS boot contract."""

import random

import pytest

from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.sidecar import codec, wire
from karpenter_tpu.sidecar import server as srv
from karpenter_tpu.sidecar.client import RemoteScheduler, SolverSession

from factories import make_pods, make_nodepool, make_state_node
from test_parity_fuzzer import gen_nodepools, gen_pods


@pytest.fixture(scope="module")
def fleet_one():
    """One isolated Replica (NOT the module default) with a handoff store
    attached, so drain/export tests cannot leak into other modules."""
    rep = srv.Replica(name="ckpt-test", handoff=srv.HandoffStore())
    server, port = srv.serve(port=0, replica=rep)
    yield f"127.0.0.1:{port}", rep
    server.stop(grace=None)


def _live_session(address, rep, tenant, pods, rounds=3, seed=5):
    """Drive a real session to a non-trivial state: bootstrap + churned
    delta solves so rows, templates, state nodes, the response cache and
    the dedupe nonce are all populated. Returns the SERVER-side _Session."""
    rng = random.Random(seed)
    session = SolverSession(address, tenant=tenant)
    rs = RemoteScheduler(address, [make_nodepool()],
                         {"default": construct_instance_types()},
                         state_nodes=[make_state_node(f"{tenant}-n1",
                                                      zone="test-zone-a")],
                         session=session)
    for round_ in range(rounds):
        rs.solve(pods)
        rng.shuffle(pods)
        pods = pods[:max(2, len(pods) - 2)] + make_pods(
            2, cpu=f"{200 + 100 * round_}m")
    with rep.sessions_lock:
        server_session = rep.sessions[session._session_id]
    return server_session, session


class TestCheckpointRoundTrip:
    """encode -> decode -> re-encode over REAL session state must be
    lossless and byte-stable; the restored session must be
    indistinguishable from the one that was exported."""

    @pytest.mark.parametrize("seed", [3, 11, 29])
    def test_fuzzer_corpus_sessions_round_trip(self, fleet_one, seed):
        address, rep = fleet_one
        rng = random.Random(seed)
        pods = gen_pods(rng, gen_nodepools(rng))[:24]
        live, _ = _live_session(address, rep, f"fuzz-{seed}", pods,
                                seed=seed)
        with live.lock:
            data = srv.export_session_checkpoint(live)
        st = codec.decode_session_checkpoint(data)
        assert st["session"] == live.id
        assert st["tenant"] == live.tenant
        assert st["templates"] == live.template_list
        assert st["rows"] == [(int(t), float(ts)) for t, ts in live.rows]
        assert st["state_revs"] == live.state_tokens
        assert st["ds_token"] == live.ds_token
        assert st["cluster_token"] == live.cluster_token
        assert st["last_req_seq"] == live.last_req_seq
        assert st["digest"] == live.last_digest
        assert st["counters"]["solves"] == live.solves
        assert st["responses"] == list(live.response_cache.items())

    def test_restore_rebuilds_an_equivalent_session(self, fleet_one):
        address, rep = fleet_one
        live, _ = _live_session(address, rep, "restore-me",
                                make_pods(8, cpu="250m"))
        with live.lock:
            data = srv.export_session_checkpoint(live)
        restored = srv.restore_session_checkpoint(data)
        assert restored.id == live.id
        assert restored.tenant == live.tenant
        assert restored.last_digest == live.last_digest
        assert restored.last_req_seq == live.last_req_seq
        assert restored.template_list == live.template_list
        assert restored.tmpl_digest == live.tmpl_digest
        assert restored.state_tokens == live.state_tokens
        assert list(restored.response_cache) == list(live.response_cache)
        assert restored.solves == live.solves
        # a restored session re-exports BYTE-IDENTICAL: the checkpoint is
        # a fixed point, so a session can migrate replica-to-replica any
        # number of times without drift
        with restored.lock:
            again = srv.export_session_checkpoint(restored)
        assert again == data

    def test_empty_session_round_trips(self):
        """A session that never solved still checkpoints (rows/templates/
        responses empty) — and with no bootstrap payload captured, the
        export re-serializes the CreateSession request itself."""
        live = srv._Session("empty-1", [make_nodepool()],
                            {"default": construct_instance_types()[:8]},
                            tenant="empty")
        with live.lock:
            data = srv.export_session_checkpoint(live)
        st = codec.decode_session_checkpoint(data)
        assert st["rows"] == [] and st["templates"] == []
        assert st["responses"] == [] and st["tenant"] == "empty"
        restored = srv.restore_session_checkpoint(data)
        assert restored.id == live.id and restored.rows == []
        assert restored.tenant == "empty"

    def test_drain_exports_every_session_to_the_handoff(self):
        """server.drain() with a handoff store attached checkpoints every
        live session — the migration a rolling restart rides on."""
        rep = srv.Replica(name="ckpt-drain", handoff=srv.HandoffStore())
        server, port = srv.serve(port=0, replica=rep)
        try:
            address = f"127.0.0.1:{port}"
            live, _ = _live_session(address, rep, "drainee",
                                    make_pods(6, cpu="500m"))
            sid, digest = live.id, live.last_digest
            server.drain(grace=2.0)
            data = rep.handoff.get(sid)
            assert data is not None and rep.handoff.puts >= 1
            assert srv.restore_session_checkpoint(data).last_digest == digest
        finally:
            server.stop(grace=None)


# -- the loud-reject matrix ---------------------------------------------------


def _synthetic_checkpoint(seed=7):
    """A valid checkpoint frame built WITHOUT a server: the offline
    session assembles a fuzzer-corpus delta, the codec mirror applies it,
    and the mirror state becomes the session-state dict."""
    rng = random.Random(seed)
    pools = gen_nodepools(rng)
    pods = gen_pods(rng, pools)[:16]
    sess = SolverSession("127.0.0.1:1")
    sess._session_id = "offline"
    header, blobs, commit, _ = sess._delta_request(pods, [], [], None, None,
                                                   False)
    commit()
    template_list = [d for _tid, d in header.get("templates_new", ())]
    template_keys = [codec.template_content_key(d) for d in template_list]
    rows = codec.apply_pod_delta([], header, blobs)
    state_revs = {"n1": "3", "n2": "7"}
    digest = codec.batch_digest(
        [r[0] for r in rows], [r[1] for r in rows],
        codec.templates_digest(template_keys), state_revs, "ds9", "c4")
    st = {
        "session": "synthetic-1",
        "tenant": "acme",
        "bootstrap": b"opaque bootstrap payload bytes",
        "templates": template_list,
        "rows": rows,
        "state_nodes": [{"name": "n1"}, {"name": "n2"}],
        "state_revs": state_revs,
        "daemonset": [],
        "ds_token": "ds9",
        "cluster": None,
        "cluster_token": "c4",
        "topo_revision": 4,
        "last_req_seq": 9,
        "responses": [("a" * 16, b"first response"),
                      ("b" * 16, b""),
                      ("c" * 16, b"third")],
        "counters": {"solves": 5, "resyncs": 0, "dedup_hits": 2},
        "digest": digest,
    }
    return codec.encode_session_checkpoint(st), st


def _mutate(data, header_fn=None, blob_fn=None):
    header, blobs = wire.unpack(data)
    blobs = {k: bytes(v) for k, v in blobs.items()}
    if header_fn is not None:
        header_fn(header)
    if blob_fn is not None:
        blob_fn(blobs)
    return wire.pack(header, blobs)


class TestCheckpointRejects:
    """Every malformed frame refuses LOUDLY — a checkpoint that cannot be
    proven whole must never become a live session."""

    def test_synthetic_frame_decodes_clean(self):
        data, st = _synthetic_checkpoint()
        out = codec.decode_session_checkpoint(data)
        assert out["digest"] == st["digest"]
        assert out["rows"] == st["rows"]
        assert out["responses"] == st["responses"]
        assert out["bootstrap"] == st["bootstrap"]
        assert out["counters"] == st["counters"]

    def test_garbage_rejects(self):
        with pytest.raises(ValueError):
            codec.decode_session_checkpoint(b"not a frame at all")

    @pytest.mark.parametrize("cut", [1, 7, 64])
    def test_truncated_frame_rejects(self, cut):
        data, _ = _synthetic_checkpoint()
        with pytest.raises(ValueError):
            codec.decode_session_checkpoint(data[:-cut])

    def test_wrong_message_kind_rejects(self):
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, lambda h: h.update(kind="delta_solve"))
        with pytest.raises(ValueError, match="not a session checkpoint"):
            codec.decode_session_checkpoint(bad)

    def test_unknown_checkpoint_version_rejects(self):
        """The v1-downgrade skew vector: a frame from a NEWER replica
        (ckpt=2) reaching a v1 reader mid-roll must refuse, not misparse
        half-understood session state."""
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, lambda h: h.update(ckpt=2))
        with pytest.raises(codec.CheckpointVersionError,
                           match="roll every sidecar replica"):
            codec.decode_session_checkpoint(bad)

    def test_missing_checkpoint_version_rejects(self):
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, lambda h: h.pop("ckpt"))
        with pytest.raises(codec.CheckpointVersionError):
            codec.decode_session_checkpoint(bad)

    def test_delta_wire_skew_rejects(self):
        """A checkpoint whose MIRRORS speak a newer delta schema cannot be
        restored onto this replica — reject at restore, not on every
        subsequent solve."""
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data,
                      lambda h: h.update(v=codec.DELTA_SCHEMA_VERSION + 1))
        with pytest.raises(codec.DeltaVersionError):
            codec.decode_session_checkpoint(bad)

    @pytest.mark.parametrize("field", ["session", "templates", "state_revs",
                                       "ds_token", "last_req_seq", "digest"])
    def test_stripped_header_field_rejects(self, field):
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, lambda h: h.pop(field))
        with pytest.raises(ValueError, match="missing field"):
            codec.decode_session_checkpoint(bad)

    @pytest.mark.parametrize("blob", ["row_tid", "row_ts", "bootstrap"])
    def test_stripped_blob_rejects(self, blob):
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, blob_fn=lambda b: b.pop(blob))
        with pytest.raises(ValueError, match="missing blob"):
            codec.decode_session_checkpoint(bad)

    def test_row_column_disagreement_rejects(self):
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, blob_fn=lambda b: b.update(
            row_ts=b["row_ts"][:-8]))
        with pytest.raises(ValueError, match="row columns disagree"):
            codec.decode_session_checkpoint(bad)

    def test_row_template_reference_out_of_range_rejects(self):
        data, st = _synthetic_checkpoint()
        n = len(st["templates"])
        bad = _mutate(data, blob_fn=lambda b: b.update(
            row_tid=wire.pack_u32([n + 3] + [r[0] for r in st["rows"][1:]])))
        with pytest.raises(ValueError, match="references template"):
            codec.decode_session_checkpoint(bad)

    def test_response_cache_blob_length_mismatch_rejects(self):
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, blob_fn=lambda b: b.update(
            responses=b["responses"] + b"trailing junk"))
        with pytest.raises(ValueError, match="length mismatch"):
            codec.decode_session_checkpoint(bad)

    def test_corrupt_digest_rejects(self):
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, lambda h: h.update(digest="deadbeef" * 8))
        with pytest.raises(codec.DigestMismatchError,
                           match="refusing to resurrect"):
            codec.decode_session_checkpoint(bad)

    def test_tampered_state_rev_flips_the_digest_check(self):
        """The digest covers the revision tokens: silently rewinding one
        node's revision inside the frame is caught, not restored."""
        data, _ = _synthetic_checkpoint()
        bad = _mutate(data, lambda h: h["state_revs"].update(n1="999"))
        with pytest.raises(codec.DigestMismatchError):
            codec.decode_session_checkpoint(bad)

    def test_empty_frame_digest_is_recomputed(self):
        """A frame with no digest field VALUE (legacy empty string) still
        decodes — the restored digest is recomputed from the parts, so the
        handshake on the next solve stays sound."""
        data, st = _synthetic_checkpoint()
        tolerated = _mutate(data, lambda h: h.update(digest=""))
        out = codec.decode_session_checkpoint(tolerated)
        assert out["digest"] == st["digest"]


# -- $KARPENTER_SIDECAR_MAX_SESSIONS (satellite a) ----------------------------


class TestMaxSessionsEnv:
    """The session-table bound is configurable and a typo fails LOUDLY at
    boot — the KARPENTER_LOO_MIN_CANDIDATES contract."""

    def test_default_without_env(self, monkeypatch):
        monkeypatch.delenv("KARPENTER_SIDECAR_MAX_SESSIONS", raising=False)
        assert srv._max_sessions_from_env() == 8

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SIDECAR_MAX_SESSIONS", "17")
        assert srv._max_sessions_from_env() == 17

    def test_replica_reads_the_env(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SIDECAR_MAX_SESSIONS", "3")
        assert srv.Replica(name="env-read").max_sessions == 3

    @pytest.mark.parametrize("bad", ["0", "-3", "abc", "8.5", ""])
    def test_invalid_values_exit_loudly(self, monkeypatch, bad):
        monkeypatch.setenv("KARPENTER_SIDECAR_MAX_SESSIONS", bad)
        with pytest.raises(SystemExit) as exc:
            srv._max_sessions_from_env()
        assert "KARPENTER_SIDECAR_MAX_SESSIONS" in str(exc.value)
