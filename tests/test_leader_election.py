"""Leader election (operator.go:137-141 analog): file-lease acquire, renew,
expiry steal, graceful handoff — and the operator only reconciles while it
holds the lease."""

import threading
import time

import pytest

from karpenter_tpu.api.objects import Node
from karpenter_tpu.operator.leaderelection import FileLease
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod


class TestFileLease:
    def test_acquire_then_rival_blocked(self, tmp_path):
        clock = FakeClock()
        path = str(tmp_path / "lease")
        a = FileLease(path, "op-a", lease_duration=15, clock=clock)
        b = FileLease(path, "op-b", lease_duration=15, clock=clock)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert a.holder() == "op-a"

    def test_renew_extends(self, tmp_path):
        clock = FakeClock()
        a = FileLease(str(tmp_path / "lease"), "op-a", lease_duration=15,
                      clock=clock)
        b = FileLease(str(tmp_path / "lease"), "op-b", lease_duration=15,
                      clock=clock)
        assert a.try_acquire()
        clock.step(10)
        assert a.renew()
        clock.step(10)  # 20s since acquire, 10s since renew: still held
        assert not b.try_acquire()

    def test_expired_lease_stolen(self, tmp_path):
        clock = FakeClock()
        a = FileLease(str(tmp_path / "lease"), "op-a", lease_duration=15,
                      clock=clock)
        b = FileLease(str(tmp_path / "lease"), "op-b", lease_duration=15,
                      clock=clock)
        assert a.try_acquire()
        clock.step(16)  # op-a died: no renewal within the lease duration
        assert b.try_acquire()
        assert b.holder() == "op-b"
        # the late-waking old leader discovers the loss on renew
        assert not a.renew()

    def test_release_enables_immediate_takeover(self, tmp_path):
        clock = FakeClock()
        a = FileLease(str(tmp_path / "lease"), "op-a", lease_duration=15,
                      clock=clock)
        b = FileLease(str(tmp_path / "lease"), "op-b", lease_duration=15,
                      clock=clock)
        assert a.try_acquire()
        a.release()
        assert b.try_acquire()  # no expiry wait after graceful handoff

    def test_release_of_non_holder_is_noop(self, tmp_path):
        clock = FakeClock()
        a = FileLease(str(tmp_path / "lease"), "op-a", clock=clock)
        b = FileLease(str(tmp_path / "lease"), "op-b", clock=clock)
        assert a.try_acquire()
        b.release()
        assert a.holder() == "op-a"


class TestRenewDeadline:
    def test_wedged_renewal_thread_surrenders_leadership(self, tmp_path):
        """client-go aborts leadership when RenewDeadline elapses without a
        successful renew. A renewal thread that is blocked (wedged fcntl
        lock, scheduling stall) never flips _lease_lost — the run loop's
        deadline check must catch it BEFORE the lease expires and a standby
        legitimately steals it, or two leaders reconcile concurrently."""
        clock = FakeClock()
        op = Operator(options=Options(leader_elect=True,
                                      lease_file=str(tmp_path / "l")),
                      clock=clock)
        lease = op._lease()
        assert lease.try_acquire()
        t = op._start_renewal(lease)
        op._renew_stop.set()  # wedge: no renew attempt will ever complete
        t.join(timeout=5)
        assert not op._lease_lost.is_set()
        assert not op._renew_deadline_passed(lease)
        clock.step(9)   # renew deadline = 2/3 * 15 s = 10 s
        assert not op._renew_deadline_passed(lease)
        clock.step(2)   # 11 s since last renew: deadline passed...
        assert op._renew_deadline_passed(lease)
        clock.step(5)   # ...and only at 16 s could a standby steal the lease
        rival = FileLease(lease.path, "rival", lease_duration=15, clock=clock)
        assert rival.try_acquire()


class TestOperatorLeadership:
    def test_standby_does_not_reconcile(self, tmp_path):
        """Two operators over one lease: only the leader provisions; the
        standby serves probes but runs no controllers."""
        lease = str(tmp_path / "op.lease")
        leader = Operator(options=Options(
            metrics_port=0, health_probe_port=0, leader_elect=True,
            lease_file=lease))
        standby = Operator(options=Options(
            metrics_port=0, health_probe_port=0, leader_elect=True,
            lease_file=lease))
        stop = {"v": False}

        def run(op):
            op.run(stop=lambda: stop["v"], tick_seconds=0.02)

        t1 = threading.Thread(target=run, args=(leader,), daemon=True)
        t1.start()
        time.sleep(0.3)
        t2 = threading.Thread(target=run, args=(standby,), daemon=True)
        t2.start()
        time.sleep(0.3)
        # work lands in BOTH stores (separate processes in real life);
        # only the leader's controllers may act on it
        for op in (leader, standby):
            op.store.create(make_nodepool(name="default"))
            op.store.create(make_pod(cpu="500m"))
        deadline = time.time() + 60
        while time.time() < deadline and not leader.store.list(Node):
            time.sleep(0.2)
        stop["v"] = True
        t1.join(timeout=5)
        t2.join(timeout=5)
        assert leader.store.list(Node), "leader must provision"
        assert not standby.store.list(Node), "standby must not reconcile"


class FakeLeaseApi:
    """In-memory coordination-API double with resourceVersion CAS — the
    serialization semantics KubeLease depends on."""

    base_url = "https://fake"

    def __init__(self):
        self.lease = None
        self._rv = 0

    def _request(self, method, url, body=None):
        import urllib.error

        def err(code):
            return urllib.error.HTTPError(url, code, "", {}, None)

        if method == "GET":
            if self.lease is None:
                raise err(404)
            import copy
            return copy.deepcopy(self.lease)
        if method == "POST":
            if self.lease is not None:
                raise err(409)
            self._rv += 1
            body.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
            self.lease = body
            return body
        if method == "PUT":
            if self.lease is None:
                raise err(404)
            if body.get("metadata", {}).get("resourceVersion") != \
                    self.lease["metadata"]["resourceVersion"]:
                raise err(409)
            self._rv += 1
            body["metadata"]["resourceVersion"] = str(self._rv)
            self.lease = body
            return body
        if method == "DELETE":
            self.lease = None
            return None
        raise AssertionError(method)


class TestKubeLease:
    def _pair(self):
        from karpenter_tpu.operator.leaderelection import KubeLease
        from karpenter_tpu.utils.clock import FakeClock
        api = FakeLeaseApi()
        clock = FakeClock()
        a = KubeLease(api, "replica-a", lease_duration=15.0, clock=clock)
        b = KubeLease(api, "replica-b", lease_duration=15.0, clock=clock)
        return api, clock, a, b

    def test_first_candidate_acquires(self):
        _, _, a, b = self._pair()
        assert a.try_acquire()
        assert a.holder() == "replica-a"
        assert not b.try_acquire()  # lease held and fresh

    def test_renewal_extends(self):
        _, clock, a, b = self._pair()
        assert a.try_acquire()
        clock.step(10)
        assert a.renew()
        clock.step(10)  # 20s since acquire but only 10 since renew
        assert not b.try_acquire()

    def test_expired_lease_stolen_with_transition_count(self):
        api, clock, a, b = self._pair()
        assert a.try_acquire()
        # b must OBSERVE the record unchanged for a full lease_duration by
        # its own clock before stealing — the remote renewTime is never
        # trusted directly (clock skew would allow stealing from a healthy
        # leader otherwise)
        assert not b.try_acquire()  # first observation starts b's window
        clock.step(16)              # record unchanged for > lease_duration
        assert b.try_acquire()
        assert b.holder() == "replica-b"
        assert api.lease["spec"]["leaseTransitions"] == 1
        # the deposed leader's renew must fail
        assert not a.renew()

    def test_renewing_leader_is_never_stolen_from(self, ):
        _, clock, a, b = self._pair()
        assert a.try_acquire()
        for _ in range(6):
            assert not b.try_acquire()  # each renew restarts b's window
            clock.step(10)
            assert a.renew()
        assert a.holder() == "replica-a"

    def test_concurrent_steal_loses_cas(self):
        api, clock, a, b = self._pair()
        assert a.try_acquire()
        clock.step(16)
        # b reads the expired lease, then a renews-revives it first
        live = api._request("GET", "u")
        assert a.try_acquire()  # holder==a: renew path revives it
        # now b's PUT carries a stale resourceVersion
        live["spec"]["holderIdentity"] = "replica-b"
        import urllib.error
        try:
            api._request("PUT", "u", live)
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code == 409
        assert raised

    def test_release_lets_next_acquire_immediately(self):
        _, _, a, b = self._pair()
        assert a.try_acquire()
        a.release()
        assert b.try_acquire()
        assert b.holder() == "replica-b"

    def test_release_by_non_holder_is_noop(self):
        _, _, a, b = self._pair()
        assert a.try_acquire()
        b.release()
        assert a.holder() == "replica-a"

    def test_operator_picks_kube_lease_for_kube_backend(self):
        from karpenter_tpu.kube.apiserver import KubeApiStore
        from karpenter_tpu.operator.leaderelection import KubeLease
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        store = KubeApiStore("https://fake:6443")
        op = Operator.__new__(Operator)
        op.options = Options(leader_elect=True, store_backend="kube")
        op.store = store
        from karpenter_tpu.utils.clock import FakeClock
        op.clock = FakeClock()
        assert isinstance(op._lease(), KubeLease)
