"""Requirement/taint validation battery (VERDICT r2 #10).

Accept/reject table mirrors /root/reference/pkg/apis/v1/
nodeclaim_validation.go:62-151 (ValidateRequirement + validateTaints) and
the webhook behaviors its suite pins."""

import itertools

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import NodeSelectorRequirement, Taint
from karpenter_tpu.api.validation import (is_qualified_name,
                                          is_valid_label_value,
                                          validate_requirement,
                                          validate_requirements,
                                          validate_taints)

from factories import make_nodepool


def req(key, op, values=(), min_values=None):
    r = NodeSelectorRequirement(key=key, operator=op, values=tuple(values))
    if min_values is not None:
        # NodeClaim-side selector shape carries min_values
        class R:
            pass
        rr = R()
        rr.key, rr.operator, rr.values, rr.min_values = key, op, tuple(values), min_values
        return rr
    return r


ACCEPT = [
    req(api_labels.LABEL_INSTANCE_TYPE, "In", ["m5.large"]),
    req(api_labels.LABEL_TOPOLOGY_ZONE, "In", ["us-west-2a", "us-west-2b"]),
    req(api_labels.CAPACITY_TYPE_LABEL_KEY, "NotIn", ["spot"]),
    req("example.com/team", "Exists"),
    req("example.com/team", "DoesNotExist"),
    req("node.kubernetes.io/instance-type", "In", ["g4dn.xlarge"]),  # exception domain
    req(api_labels.LABEL_ARCH, "In", ["amd64"]),
    req("karpenter.k8s.aws/instance-cpu", "Gt", ["4"]),
    req("karpenter.k8s.aws/instance-cpu", "Lt", ["0"]),   # 0 is non-negative
    req("beta.kubernetes.io/instance-type", "In", ["m5.large"]),  # normalized
    req(api_labels.LABEL_INSTANCE_TYPE, "In", ["a", "b", "c"], min_values=2),
]

REJECT = [
    # unsupported operator
    req(api_labels.LABEL_INSTANCE_TYPE, "IsGreaterThan", ["1"]),
    req(api_labels.LABEL_INSTANCE_TYPE, "in", ["m5.large"]),
    # restricted domains (kubernetes.io / k8s.io / karpenter.sh) unless
    # well-known or exception
    req("kubernetes.io/custom", "In", ["x"]),
    req("k8s.io/custom", "In", ["x"]),
    req(f"{api_labels.GROUP}/custom", "In", ["x"]),
    req(api_labels.LABEL_HOSTNAME, "In", ["node-1"]),
    # malformed key / values
    req("-bad", "In", ["x"]),
    req("a/b/c", "In", ["x"]),
    req("example.com/" + "k" * 64, "In", ["x"]),
    req("example.com/team", "In", ["bad value!"]),
    req("example.com/team", "In", ["-leading"]),
    # In needs values; minValues must fit
    req("example.com/team", "In", []),
    req(api_labels.LABEL_INSTANCE_TYPE, "In", ["a"], min_values=2),
    # Gt/Lt single non-negative integer
    req("example.com/cpu", "Gt", ["1", "2"]),
    req("example.com/cpu", "Gt", ["-1"]),
    req("example.com/cpu", "Lt", ["abc"]),
    req("example.com/cpu", "Gt", []),
]


class TestRequirementTable:
    @pytest.mark.parametrize("r", ACCEPT,
                             ids=[f"{r.key}-{r.operator}" for r in ACCEPT])
    def test_accepted(self, r):
        assert validate_requirement(r) == []

    @pytest.mark.parametrize("r", REJECT, ids=[
        f"{i}-{r.key}-{r.operator}" for i, r in enumerate(REJECT)])
    def test_rejected(self, r):
        assert validate_requirement(r) != []

    def test_errors_aggregate(self):
        # several violations -> several errors (multierr behavior)
        r = req("kubernetes.io/custom", "BadOp", [])
        errs = validate_requirement(r)
        assert len(errs) >= 2

    def test_validate_requirements_prefixes(self):
        errs = validate_requirements([req("kubernetes.io/custom", "In", ["x"])])
        assert errs and "in requirements, restricted" in errs[0]


class TestQualifiedNames:
    def test_name_part_rules(self):
        assert is_qualified_name("simple") == []
        assert is_qualified_name("with-dash_and.dot9") == []
        assert is_qualified_name("") != []
        assert is_qualified_name("x" * 64) != []
        assert is_qualified_name("trailing-") != []

    def test_prefix_rules(self):
        assert is_qualified_name("example.com/name") == []
        assert is_qualified_name("UPPER.com/name") != []
        assert is_qualified_name(("a" * 254) + "/name") != []
        assert is_qualified_name("/name") != []

    def test_label_values(self):
        assert is_valid_label_value("") == []
        assert is_valid_label_value("ok-value.1") == []
        assert is_valid_label_value("has space") != []
        assert is_valid_label_value("x" * 64) != []


class TestTaintTable:
    def test_valid_taints(self):
        errs = validate_taints(
            [Taint(key="dedicated", value="gpu", effect="NoSchedule"),
             Taint(key="dedicated", value="gpu", effect="NoExecute")],
            [Taint(key="startup.example.com/gate", effect="NoSchedule")])
        assert errs == []

    def test_empty_key_rejected(self):
        assert validate_taints([Taint(key="", effect="NoSchedule")]) != []

    def test_bad_effect_rejected(self):
        assert validate_taints(
            [Taint(key="k", effect="NoSchedule2")]) != []

    def test_duplicate_key_effect_rejected(self):
        errs = validate_taints(
            [Taint(key="k", value="a", effect="NoSchedule"),
             Taint(key="k", value="b", effect="NoSchedule")])
        assert any("duplicate" in e for e in errs)

    def test_duplicate_spans_startup_taints(self):
        errs = validate_taints(
            [Taint(key="k", effect="NoSchedule")],
            [Taint(key="k", effect="NoSchedule")])
        assert any("duplicate" in e for e in errs)

    def test_bad_value_rejected(self):
        assert validate_taints(
            [Taint(key="k", value="bad value!", effect="NoSchedule")]) != []


class TestOperatorLevelRejection:
    def test_nodepool_condition_set_false(self):
        """Runtime validation catches what admission can't: duplicate taint
        Key/Effect pairs aren't schema-expressible, so the apiserver admits
        them and the validation controller flags the condition
        (validation/controller.go:51-76)."""
        from karpenter_tpu.api.objects import Taint
        from karpenter_tpu.controllers.nodepool_aux import (
            COND_VALIDATION_SUCCEEDED, NodePoolValidation)
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock
        store = Store(FakeClock())
        pool = make_nodepool(
            name="bad",
            taints=[Taint(key="example.com/k", effect="NoSchedule"),
                    Taint(key="example.com/k", effect="NoSchedule")])
        store.create(pool)  # schema admits duplicate taints
        NodePoolValidation(store).reconcile(pool)
        cond = next(c for c in pool.status.conditions
                    if c["type"] == COND_VALIDATION_SUCCEEDED)
        assert cond["status"] == "False"
        assert "duplicate" in cond["message"]

    def test_nodepool_condition_true_when_valid(self):
        from karpenter_tpu.controllers.nodepool_aux import (
            COND_VALIDATION_SUCCEEDED, NodePoolValidation)
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock
        store = Store(FakeClock())
        pool = make_nodepool(
            name="good",
            requirements=[req(api_labels.LABEL_ARCH, "In", ["amd64"])])
        store.create(pool)
        NodePoolValidation(store).reconcile(pool)
        cond = next(c for c in pool.status.conditions
                    if c["type"] == COND_VALIDATION_SUCCEEDED)
        assert cond["status"] == "True"

class TestStoreAdmission:
    """VERDICT r4 #6: the store enforces the CRD schema at create/update —
    a malformed NodePool/NodeClaim is rejected the way the reference's
    apiserver rejects it (karpenter.sh_nodepools.yaml CEL + patterns,
    nodeclaim_validation.go battery's schema subset)."""

    def _store(self):
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock
        return Store(FakeClock())

    def _rejects(self, store, obj, needle=""):
        from karpenter_tpu.kube.store import InvalidError
        import pytest as _pytest
        with _pytest.raises(InvalidError) as ei:
            store.create(obj)
        assert needle in str(ei.value)

    def test_accept_reject_table(self):
        """The accept/reject table from nodeclaim_validation.go:1-151's
        schema-enforced subset, driven against Store.create."""
        from karpenter_tpu.api import labels as api_labels
        store = self._store()
        # accepted shapes
        store.create(make_nodepool(name="ok-plain"))
        store.create(make_nodepool(
            name="ok-reqs",
            requirements=[req(api_labels.LABEL_ARCH, "In", ["amd64"]),
                          req("example.com/team", "NotIn", ["infra"]),
                          req("example.com/gen", "Gt", ["3"]),
                          req("example.com/feature", "Exists", [])]))
        # rejected shapes
        self._rejects(self._store(), make_nodepool(
            name="bad-op",
            requirements=[req(api_labels.LABEL_ARCH, "Weird", ["x"])]),
            "unsupported operator")
        self._rejects(self._store(), make_nodepool(
            name="bad-in-empty",
            requirements=[req(api_labels.LABEL_ARCH, "In", [])]),
            "must have a value")
        self._rejects(self._store(), make_nodepool(
            name="bad-gt",
            requirements=[req("example.com/gen", "Gt", ["three"])]),
            "single positive integer")
        self._rejects(self._store(), make_nodepool(
            name="bad-gt-neg",
            requirements=[req("example.com/gen", "Lt", ["-3"])]),
            "single positive integer")
        self._rejects(self._store(), make_nodepool(
            name="bad-restricted",
            requirements=[req("kubernetes.io/custom", "In", ["x"])]),
            "restricted")
        self._rejects(self._store(), make_nodepool(
            name="bad-nodepool-label",
            requirements=[req(api_labels.NODEPOOL_LABEL_KEY, "In", ["x"])]),
            "restricted")
        self._rejects(self._store(), make_nodepool(
            name="bad-key",
            requirements=[req("-bad-key-", "In", ["x"])]),
            "qualified name")
        self._rejects(self._store(), make_nodepool(
            name="bad-value",
            requirements=[req("example.com/t", "In", ["bad value!"])]),
            "label value")
        self._rejects(self._store(), make_nodepool(
            name="bad-exists-values",
            requirements=[req("example.com/t", "Exists", ["x"])]),
            "forbids values")

    def test_minvalues_rules(self):
        from karpenter_tpu.api import labels as api_labels
        r = req(api_labels.LABEL_ARCH, "In", ["amd64"], min_values=2)
        self._rejects(self._store(), make_nodepool(
            name="bad-minvalues", requirements=[r]), "minimum number")
        r2 = req(api_labels.LABEL_ARCH, "In", ["amd64", "arm64"],
                 min_values=51)
        self._rejects(self._store(), make_nodepool(
            name="bad-minvalues-51", requirements=[r2]), "between 1 and 50")

    def test_nodepool_field_bounds(self):
        from karpenter_tpu.api.nodepool import Budget
        pool = make_nodepool(name="bad-weight")
        pool.spec.weight = 101
        self._rejects(self._store(), pool, "between 1 and 100")
        pool = make_nodepool(name="bad-budget")
        pool.spec.disruption.budgets = [Budget(nodes="150%")]
        self._rejects(self._store(), pool, "absolute count")
        pool = make_nodepool(name="bad-budget-sched")
        pool.spec.disruption.budgets = [Budget(nodes="10%",
                                               schedule="0 9 * * 1")]
        self._rejects(self._store(), pool, "'schedule' must be set with")
        pool = make_nodepool(name="ok-budget")
        pool.spec.disruption.budgets = [
            Budget(nodes="10%", schedule="0 9 * * 1", duration=3600.0)]
        self._store().create(pool)

    def test_nodeclaim_admission_and_spec_immutability(self):
        import dataclasses
        from karpenter_tpu.api import labels as api_labels
        from karpenter_tpu.api.nodeclaim import NodeClaim, NodeClaimSpec
        from karpenter_tpu.api.objects import ObjectMeta
        from karpenter_tpu.kube.store import InvalidError
        from karpenter_tpu.provisioning.scheduler import _SelectorReq
        store = self._store()
        nc = NodeClaim(
            metadata=ObjectMeta(name="nc-ok", namespace=""),
            spec=NodeClaimSpec(requirements=[
                _SelectorReq(api_labels.LABEL_ARCH, "In", ("amd64",))]))
        store.create(nc)
        # status/condition updates on the SAME object are fine
        nc.status.provider_id = "t://x"
        store.update(nc)
        # a replacement object with a mutated spec is rejected
        clone = NodeClaim(
            metadata=ObjectMeta(name="nc-ok", namespace="",
                                uid=nc.metadata.uid),
            spec=NodeClaimSpec(requirements=[
                _SelectorReq(api_labels.LABEL_ARCH, "In", ("arm64",))]))
        with pytest.raises(InvalidError) as ei:
            store.update(clone)
        assert "immutable" in str(ei.value)
        bad = NodeClaim(
            metadata=ObjectMeta(name="nc-bad", namespace=""),
            spec=NodeClaimSpec(requirements=[
                _SelectorReq("kubernetes.io/custom", "In", ("x",))]))
        with pytest.raises(InvalidError):
            store.create(bad)


from karpenter_tpu.api.nodepool import Budget
from karpenter_tpu.kube.store import InvalidError, Store


class TestDisruptionCelTable:
    """Accept/reject table from nodepool_validation_cel_test.go:67-275
    (the disruption block: durations, budgets, crons, reasons), enforced at
    the store boundary like the apiserver's CEL rules."""

    _seq = itertools.count(1)

    def _pool(self, mutate):
        pool = make_nodepool(name=f"celpool-{next(self._seq)}")
        mutate(pool)
        return pool

    def _accepts(self, store, mutate):
        try:
            store.create(self._pool(mutate))
            return True
        except InvalidError:
            return False

    @pytest.fixture
    def store(self):
        from karpenter_tpu.utils.clock import FakeClock
        return Store(FakeClock())

    def test_consolidate_after_rules(self, store):
        assert not self._accepts(store, lambda p: setattr(
            p.spec.disruption, "consolidate_after", -1.0))
        assert self._accepts(store, lambda p: setattr(
            p.spec.disruption, "consolidate_after", None))  # Never
        assert self._accepts(store, lambda p: setattr(
            p.spec.disruption, "consolidate_after", 30.0))

    def test_expire_after_rules(self, store):
        assert not self._accepts(store, lambda p: setattr(
            p.spec.template.spec, "expire_after", -1.0))
        assert self._accepts(store, lambda p: setattr(
            p.spec.template.spec, "expire_after", None))
        assert self._accepts(store, lambda p: setattr(
            p.spec.template.spec, "expire_after", 3600.0))

    def test_budget_cron_rules(self, store):
        def bad_cron(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule="*crontab", duration=3600.0)]
        assert not self._accepts(store, bad_cron)

        def short_cron(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule="* * *", duration=3600.0)]
        assert not self._accepts(store, short_cron)

        def special_cron(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule="@daily", duration=3600.0)]
        assert self._accepts(store, special_cron)

    def test_budget_duration_rules(self, store):
        def negative(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule="* * * * *", duration=-3600.0)]
        assert not self._accepts(store, negative)

        def cron_without_duration(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule="* * * * *", duration=None)]
        assert not self._accepts(store, cron_without_duration)

        def duration_without_cron(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule=None, duration=3600.0)]
        assert not self._accepts(store, duration_without_cron)

        def both(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule="* * * * *", duration=6900.0)]
        assert self._accepts(store, both)

        def neither(p):
            p.spec.disruption.budgets = [Budget(nodes="10")]
        assert self._accepts(store, neither)

    def test_budget_nodes_rules(self, store):
        for bad in ("-10", "-10%", "1000%", "129%"):
            def mutate(p, bad=bad):
                p.spec.disruption.budgets = [Budget(nodes=bad)]
            assert not self._accepts(store, mutate), bad
        for ok in ("0", "10", "100%", "0%"):
            def mutate(p, ok=ok):
                p.spec.disruption.budgets = [Budget(nodes=ok)]
            assert self._accepts(store, mutate), ok

    def test_one_bad_budget_rejects_the_pool(self, store):
        def mutate(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", schedule="@daily", duration=3600.0),
                Budget(nodes="10", schedule="*", duration=3600.0)]
        assert not self._accepts(store, mutate)

    def test_budget_reason_enum(self, store):
        def bad(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10", reasons=["CloudProviderInterruption"])]
        assert not self._accepts(store, bad)

        def ok(p):
            p.spec.disruption.budgets = [
                Budget(nodes="10",
                       reasons=["Underutilized", "Empty", "Drifted"])]
        assert self._accepts(store, ok)
