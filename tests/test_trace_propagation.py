"""Cross-process trace propagation (ISSUE 12): the delta wire's trace_ctx
joins the operator-side sidecar.rpc span, the server-side session/queue/
solve tree, and the device spans under ONE trace_id — and retries, hedges
and duplicate deliveries under wire chaos must never mint a second server
span tree (the PR-11 idempotency nonce answers them from the dedupe cache
before any span opens). Legacy wire shapes (v1 delta without trace_ctx,
and the pre-delta no-`v` wire) are still served."""

import grpc
import pytest

from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.obs.tracer import TRACER, Tracer
from karpenter_tpu.sidecar import codec, wire
from karpenter_tpu.sidecar import server as srv
from karpenter_tpu.sidecar.client import (RemoteScheduler, RetryPolicy,
                                          SolverSession)
from karpenter_tpu.sidecar.wire_chaos import ChaosChannel
from karpenter_tpu.utils.chaos import WireFaultInjector

from factories import make_nodepool, make_pods

pytestmark = pytest.mark.chaos


def _fast_policy(**over):
    kw = dict(deadline=10.0, max_attempts=5, backoff_base=0.002,
              backoff_cap=0.01, retry_budget=32.0, refund=1.0,
              sleep=lambda _s: None)
    kw.update(over)
    return RetryPolicy(**kw)


def _pair(addr, its, pool, tenant="", injector=None, **kw):
    channel = None
    if injector is not None:
        channel = ChaosChannel(
            grpc.insecure_channel(addr, options=srv.GRPC_OPTIONS), injector)
    kw.setdefault("retry", _fast_policy())
    session = SolverSession(addr, channel=channel, tenant=tenant, **kw)
    rs = RemoteScheduler(addr, [pool], {"default": its}, session=session)
    return rs, session


@pytest.fixture()
def sidecar():
    server, port = srv.serve(port=0)
    TRACER.clear()
    yield f"127.0.0.1:{port}", server
    server.stop(grace=None)


def _server_trees(trace_id):
    """Server span trees in the (shared in-process) ring for a trace_id:
    the traces rooted at sidecar.solve — the client's tree roots at
    sidecar.rpc, so the two sides of one trace_id stay countable."""
    return [t for t in TRACER.traces()
            if t.trace_id == trace_id and t.root.name == "sidecar.solve"]


def _client_trees(trace_id):
    return [t for t in TRACER.traces()
            if t.trace_id == trace_id and any(
                s.name == "sidecar.rpc" for s in t.spans)]


class TestTracerAdoption:
    def test_adopted_root_joins_remote_trace(self):
        tr = Tracer()
        tr.adopt("t-remote-1", "sidecar.rpc#0")
        with tr.span("sidecar.solve"):
            assert tr.current_trace_id() == "t-remote-1"
        t = tr.last()
        assert t.trace_id == "t-remote-1"
        assert t.root.attrs["remote_parent"] == "sidecar.rpc#0"
        # adoption is one-shot: the next root minted locally again
        with tr.span("solve"):
            assert tr.current_trace_id().startswith("t0")

    def test_adopt_is_noop_while_a_trace_is_active(self):
        tr = Tracer()
        with tr.span("solve"):
            tr.adopt("t-remote-2")
            with tr.span("inner"):
                pass
            assert tr.current_trace_id() != "t-remote-2"
        # and the pending-adoption slot stayed clean
        with tr.span("solve"):
            assert tr.current_trace_id() != "t-remote-2"

    def test_adopt_while_disabled_never_leaks(self):
        tr = Tracer(enabled=False)
        tr.adopt("t-remote-3")
        tr.enabled = True
        with tr.span("solve"):
            assert tr.current_trace_id() != "t-remote-3"

    def test_current_ctx_names_the_active_span(self):
        tr = Tracer()
        assert tr.current_ctx() is None
        with tr.span("provisioner.pass"):
            with tr.span("sidecar.rpc"):
                ctx = tr.current_ctx()
        assert ctx["id"].startswith("t")
        assert ctx["span"] == "sidecar.rpc#1"
        tr.enabled = False
        assert tr.current_ctx() is None


class TestCleanJoin:
    def test_one_trace_id_joins_client_server_device(self, sidecar):
        addr, _ = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"))
        r = rs.solve(make_pods(5, cpu="500m"))
        tid = r.trace_id
        assert tid, "no trace_id rider on the v2 wire"
        assert len(_client_trees(tid)) == 1
        trees = _server_trees(tid)
        assert len(trees) == 1, [t.summary() for t in TRACER.traces()]
        names = {s.name for s in trees[0].spans}
        # queue-wait is a real span, the solve nests inside the session
        # tree, and the device truth rides the same trace
        assert {"sidecar.queue", "sidecar.apply", "solve",
                "device.dispatch", "device.execute"} <= names, names
        # the remote parent names the client's rpc span
        assert trees[0].root.attrs["remote_parent"].startswith("sidecar.rpc")

    def test_fresh_solves_get_fresh_trace_ids(self, sidecar):
        addr, _ = sidecar
        rs, _ = _pair(addr, construct_instance_types()[:12],
                      make_nodepool(name="default"))
        pods = make_pods(4, cpu="250m")
        t1 = rs.solve(pods).trace_id
        t2 = rs.solve(pods).trace_id
        assert t1 and t2 and t1 != t2
        assert len(_server_trees(t1)) == 1
        assert len(_server_trees(t2)) == 1


class TestChaosSingleServerTree:
    def test_duplicate_delivery_yields_one_server_tree(self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=5)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj)
        pods = make_pods(5, cpu="500m")
        rs.solve(pods)  # bootstrap
        inj.inject_next("duplicate")
        r = rs.solve(pods)
        assert r.trace_id
        assert len(_server_trees(r.trace_id)) == 1
        assert session.resyncs == 0

    def test_retry_after_drop_yields_one_server_tree(self, sidecar):
        # drop: the request never arrives; the retry (identical bytes,
        # same nonce + trace_ctx) is the one real apply
        addr, _ = sidecar
        inj = WireFaultInjector(seed=6)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj)
        pods = make_pods(5, cpu="500m")
        rs.solve(pods)
        inj.inject_next("drop")
        r = rs.solve(pods)
        assert r.retries >= 1
        assert r.trace_id and len(_server_trees(r.trace_id)) == 1

    def test_retry_after_lost_response_yields_one_server_tree(self, sidecar):
        # disconnect: applied but the response is lost — the retry is
        # answered from the nonce dedupe cache BEFORE any span opens, so
        # the first apply's tree stays the only one
        addr, _ = sidecar
        inj = WireFaultInjector(seed=7)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj)
        pods = make_pods(5, cpu="500m")
        rs.solve(pods)
        inj.inject_next("disconnect")
        r = rs.solve(pods)
        assert r.retries >= 1
        assert r.trace_id and len(_server_trees(r.trace_id)) == 1

    def test_hedge_race_yields_one_server_tree(self, sidecar):
        # a delayed primary triggers the hedge; both deliveries reach the
        # server, exactly one solves — the other is a dedupe hit
        addr, _ = sidecar
        inj = WireFaultInjector(seed=8, delay_seconds=0.2)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj,
                            retry=_fast_policy(hedge_delay=0.02))
        pods = make_pods(5, cpu="500m")
        rs.solve(pods)
        inj.inject_next("delay")
        r = rs.solve(pods)
        assert r.trace_id
        assert len(_server_trees(r.trace_id)) == 1

    def test_seeded_chaos_soak_every_solve_single_tree(self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=12, drop=0.15, duplicate=0.15,
                                disconnect=0.15)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj)
        pods = make_pods(6, cpu="250m")
        tids = []
        for _ in range(8):
            r = rs.solve(pods)
            assert r.trace_id
            tids.append(r.trace_id)
        assert len(set(tids)) == len(tids)
        for tid in tids:
            assert len(_server_trees(tid)) == 1, tid


class TestLegacyWire:
    def test_v1_delta_without_trace_ctx_still_served(self, sidecar):
        """An older client speaking schema v1 (no trace_ctx field): the
        server serves it and roots its OWN local trace instead."""
        addr, _ = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"))
        orig = session._call_resilient

        def downgrade(method, payload):
            if method == "SolveSession":
                header, blobs = wire.unpack(payload)
                header.pop("trace_ctx", None)
                header["v"] = 1
                payload = wire.pack(header,
                                    {k: bytes(v) for k, v in blobs.items()})
            return orig(method, payload)

        session._call_resilient = downgrade
        r = rs.solve(make_pods(4, cpu="250m"))
        assert not r.pod_errors
        tid = r.trace_id
        assert tid, "server should still trace v1 solves (locally rooted)"
        trees = _server_trees(tid)
        assert len(trees) == 1
        # locally rooted: no remote parent, and no client tree shares it
        assert "remote_parent" not in trees[0].root.attrs
        assert _client_trees(tid) == []

    def test_unknown_future_version_still_rejected_loudly(self, sidecar):
        addr, _ = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"))
        orig = session._call_resilient

        def futurize(method, payload):
            if method == "SolveSession":
                header, blobs = wire.unpack(payload)
                header["v"] = 99
                payload = wire.pack(header,
                                    {k: bytes(v) for k, v in blobs.items()})
            return orig(method, payload)

        session._call_resilient = futurize
        with pytest.raises(grpc.RpcError) as ei:
            rs.solve(make_pods(3, cpu="250m"))
        assert ei.value.code() in (grpc.StatusCode.INVALID_ARGUMENT,
                                   grpc.StatusCode.FAILED_PRECONDITION)

    def test_accepted_versions(self):
        codec.check_delta_version({"v": 1})
        codec.check_delta_version({"v": 2})
        with pytest.raises(codec.DeltaVersionError):
            codec.check_delta_version({"v": 3})
        with pytest.raises(codec.DeltaVersionError):
            codec.check_delta_version({})


class TestSubsystemRider:
    """The fallback-ledger subsystem flag crosses the wire: a disruption
    candidate probe served by the sidecar must not move the SERVER
    process's headline provisioning totals (the in-process
    ledger_subsystem contract, carried as a v2 header rider)."""

    def test_disruption_probe_rides_the_wire(self, sidecar):
        from karpenter_tpu.obs import fallbacks as fb
        addr, _ = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"))
        pods = make_pods(3, cpu="250m")
        fb.LEDGER.reset()
        rs.solve(pods)  # control: a live solve moves the headline totals
        assert fb.LEDGER.snapshot()["solves"] == 1
        rs.ledger_subsystem = "disruption"
        rs.solve(pods)
        snap = fb.LEDGER.snapshot()
        assert snap["solves"] == 1, (
            "a wire-flagged disruption probe moved the provisioning totals")
